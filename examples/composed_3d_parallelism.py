"""Composed 3D parallelism: dp x tp x pp in ONE jitted train step.

The reference composes its distribution mechanisms per job (Spark
orchestration + per-node ParallelWrapper + Aeron gradient sharing,
`dl4j-spark-parameterserver`); the TPU-native form is one mesh with
three axes and one compiled step:

- 'data'  — batch sharding + gradient psum (DP)
- 'model' — Megatron sequence-parallel tensor parallelism for the MLP
            (all_gather before the column-parallel matmul, psum_scatter
            after the row-parallel one) with RING ATTENTION over the
            same axis for the long-context path
- 'pipe'  — GPipe microbatch pipeline via a scan of compute + ppermute

Run on real chips, or simulate the mesh on CPU:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/composed_3d_parallelism.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS") == "cpu":
    # a 1-device CPU run would degenerate the whole point of this
    # example — force the virtual 8-way mesh before jax initializes
    if "device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax                                                 # noqa: E402
import jax.numpy as jnp                                    # noqa: E402
import numpy as np                                         # noqa: E402

from deeplearning4j_tpu.parallel.composed import (         # noqa: E402
    composed_oracle, composed_train_step, init_stage_params)
from deeplearning4j_tpu.parallel.mesh import make_mesh     # noqa: E402


def main():
    n = len(jax.devices())
    if n >= 8:
        axes = {"data": n // 4, "model": 2, "pipe": 2}
    elif n >= 4:
        axes = {"data": 1, "model": 2, "pipe": 2}
    else:
        axes = {"data": 1, "model": 1, "pipe": max(1, n)}
    used = int(np.prod(list(axes.values())))
    mesh = make_mesh(axes, jax.devices()[:used])
    print(f"mesh: {axes} over {used} device(s)")

    S, D, H, FF = axes["pipe"], 16, 4, 32
    T = 8 * axes["model"]
    B = 4 * S * axes["data"]
    rng = np.random.RandomState(0)
    params = init_stage_params(rng, S, D, H, FF)
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.5)
    y = jnp.asarray(rng.randn(B, T, D).astype(np.float32) * 0.5)

    step = composed_train_step(mesh, H, lr=0.1)
    losses = []
    p = params
    for i in range(10):
        p, loss = step(p, x, y)
        losses.append(float(loss))
    print("losses:", " ".join(f"{v:.4f}" for v in losses))
    assert losses[-1] < losses[0], "training did not reduce the loss"

    # sanity: the sharded step's first loss equals single-device math
    oracle = float(jnp.mean((composed_oracle(params, x, H) - y) ** 2))
    assert abs(losses[0] - oracle) < 1e-3 * max(1.0, oracle)
    print(f"matches single-device oracle (first loss {oracle:.4f}) — ok")


if __name__ == "__main__":
    main()
