"""Multi-model serving fleet demo (docs/serving.md): a long tail of
models through `serving.ModelFleet` — SLO-aware routing, warm-pool LRU
eviction backed by the persistent AOT executable cache, and shed ordering
under overload.

Shows the fleet surface end to end:
 1. deploy 8 models into a 3-slot warm pool — each with a
    `LatencySLO(target_p99_ms, priority)`,
 2. sweep the long tail twice: the first pass pays the compiles, the
    second re-admits every evicted model from the persistent cache with
    ZERO fresh compiles,
 3. force sustained SLO pressure on the high-priority model and watch the
    router shed low-priority traffic first,
 4. the `/fleet` topology endpoint and fleet-aware `/readyz`.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def _net(seed, hidden):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=10, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def main():
    import tempfile

    from deeplearning4j_tpu.serving import (LatencySLO, ModelFleet,
                                            RejectedError)

    cache_dir = tempfile.mkdtemp(prefix="fleet-exec-cache-")
    fleet = ModelFleet(max_resident=3, max_batch=8, batch_timeout_ms=2.0,
                       cache_dir=cache_dir)

    # 1. a long tail of low-priority models plus one high-priority ranker
    for i in range(7):
        fleet.deploy(f"tail-{i}", _net(seed=i, hidden=24 + 8 * i),
                     slo=LatencySLO(target_p99_ms=200.0, priority=0))
    ranker = fleet.deploy("ranker", _net(seed=99, hidden=64),
                          slo=LatencySLO(target_p99_ms=20.0, priority=10),
                          warm=True)
    print(f"deployed 8 models into a 3-slot warm pool "
          f"(resident: {fleet.pool.resident_names()})")

    # 2. sweep the tail twice — second pass is pure cache deserialization
    rng = np.random.RandomState(0)
    for sweep in range(2):
        before = fleet.cache.stats["compiles"]
        for i in rng.permutation(7):
            x = rng.rand(2, 16).astype(np.float32)
            assert fleet.output(f"tail-{i}", x).shape == (2, 10)
        fresh = fleet.cache.stats["compiles"] - before
        print(f"sweep {sweep}: {fresh} fresh compiles, "
              f"{fleet.cache.stats['disk_hits']} cumulative disk hits, "
              f"resident now {fleet.pool.resident_names()}")
    assert fleet.member("tail-0").last_admission_fresh_compiles == 0

    # 3. sustained breach on the ranker -> lower priority sheds FIRST
    for _ in range(fleet.policy.breach_after):
        ranker.tracker.observe(10_000.0)      # simulate sustained pressure
    shed = 0
    for i in range(4):
        try:
            fleet.output("tail-0", rng.rand(2, 16).astype(np.float32))
        except RejectedError:
            shed += 1
    y = fleet.output("ranker", rng.rand(2, 16).astype(np.float32))
    print(f"under pressure: {shed}/4 low-priority requests shed, "
          f"ranker still served (shape {y.shape})")
    for _ in range(fleet.policy.clear_after):
        ranker.tracker.observe(1.0)           # pressure clears

    # 4. topology endpoint + fleet-aware readiness
    import json
    import urllib.request

    from deeplearning4j_tpu.ui.server import UIServer
    ui = UIServer().attach_fleet(fleet)
    port = ui.start(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10) as r:
        topo = json.loads(r.read())[0]
    print(f"/fleet: {len(topo['models'])} models, resident "
          f"{topo['resident']}, slices free "
          f"{topo['capacity']['slices_free']}, warm admissions "
          f"{sum(1 for m in topo['models'].values() if m['state'] != 'cold' and m['last_admission_fresh_compiles'] == 0)}")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=10) as r:
        print(f"/readyz: {json.loads(r.read())['ready']} "
              "(cold tail models do not block readiness)")
    ui.stop()

    fleet.shutdown()
    print("fleet drained and shut down")


if __name__ == "__main__":
    main()
