"""Data-parallel SPMD training with ParallelWrapper (reference
dl4j-examples `MultiGpuLenetMnistExample.java` — ParallelWrapper over
GPUs; here one jitted step sharded over a jax device mesh).

Run with real chips, or simulate a mesh on CPU:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/data_parallel_training.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax
import numpy as np

from deeplearning4j_tpu.data import SyntheticMnist
from deeplearning4j_tpu.parallel import ParallelWrapper
from deeplearning4j_tpu.zoo import LeNet


def main():
    print(f"devices: {jax.devices()}")
    net = LeNet(n_classes=10).init_model()

    pw = (ParallelWrapper.builder(net)
          .workers(len(jax.devices()))
          .training_mode("SHARED_GRADIENTS")   # every mode = sync all-reduce
          .build())

    # global batch 64 → 64/n_devices per device, gradients all-reduced
    # over ICI by XLA inside the one compiled step
    it = SyntheticMnist(64, n_batches=20, seed=0)
    pw.fit(it, epochs=2)
    print(f"loss after DP training: {net.score():.4f}")

    # fused SPMD dispatch: k data-parallel steps (per-step all-reduce
    # inside) in ONE compiled dispatch — the r5 host-latency lever
    ds = next(iter(SyntheticMnist(64, n_batches=1, seed=2)))
    xs = np.broadcast_to(np.asarray(ds.features),
                         (4,) + np.asarray(ds.features).shape).copy()
    ys = np.broadcast_to(np.asarray(ds.labels),
                         (4,) + np.asarray(ds.labels).shape).copy()
    losses = pw.fit_steps(xs, ys)
    print(f"fused block of {len(losses)} DP steps in one dispatch, "
          f"loss -> {float(losses[-1]):.4f}")

    # the trained params live sharded/replicated on the mesh; normal
    # single-host inference just works
    x = next(iter(SyntheticMnist(8, n_batches=1, seed=1))).features
    print("predictions:", np.asarray(net.output(x)).argmax(1))


if __name__ == "__main__":
    main()
