"""Self-healing serving fleet demo (docs/robustness.md): the serving
side of fault tolerance — `serving/resilience.py` composed by
`serving.ModelFleet`.

Shows the whole failure story end to end:
 1. a 2-replica member under client flood, with an int8 standby
    registered for the degraded-mode ladder,
 2. one replica KILLED mid-flood (`utils.chaos.ReplicaChaos`) — every
    accepted request still answers: the dispatch fails over to the
    healthy replica and the victim's circuit breaker opens,
 3. the reconcile tick heals: routing-first teardown, bounded drain,
    respawn on the SAME slice through the persistent AOT cache with
    zero fresh compiles,
 4. the degraded ladder steps full -> hedges_off -> quantized under
    sustained pressure (routing flips to the int8 standby, zero
    compiles) and recovers with hysteresis, all visible on /healthz,
 5. a crc-guarded topology snapshot, then a "restarted" fleet process
    rebuilding its pre-crash shape with zero cold compiles.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def _net(seed=7, hidden=32):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=10, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def main():
    import tempfile

    from deeplearning4j_tpu.serving import (LatencySLO, ModelFleet,
                                            FleetPolicy)
    from deeplearning4j_tpu.utils.chaos import ReplicaChaos

    work = tempfile.mkdtemp(prefix="self-healing-fleet-")
    cache_dir = os.path.join(work, "exec-cache")
    snap_path = os.path.join(work, "topology.json")
    rng = np.random.RandomState(0)

    def build():
        return ModelFleet(
            max_resident=2, n_slices=2, max_batch=8, batch_timeout_ms=1.0,
            cache_dir=cache_dir, snapshot_path=snap_path,
            policy=FleetPolicy(drain_timeout_s=1.0))

    # 1. two replicas + an int8 standby for the ladder's quantized level
    fleet = build()
    m = fleet.deploy("ranker", _net(),
                     slo=LatencySLO(target_p99_ms=200.0, priority=10),
                     replicas=2, warm=True)
    fleet.prepare_quantized("ranker")
    print(f"deployed 'ranker' x2 replicas on slices "
          f"{[r.slice.index for r in m.group.replicas]}, "
          f"f32 v{m.serving_version} serving, "
          f"int8 v{m.quantized_version} standing by")

    # 2. kill one replica mid-flood: the client sees ZERO failures
    victim = m.group.replicas[0]
    victim_slice = victim.slice.index
    ReplicaChaos(mode="kill", at_dispatch=0).arm(victim)
    futs = [fleet.submit("ranker", rng.rand(2, 16).astype(np.float32),
                         deadline_ms=5000.0) for _ in range(32)]
    failed = sum(1 for f in futs if f.exception(timeout=30) is not None)
    print(f"replica killed mid-flood: {len(futs) - failed}/{len(futs)} "
          f"served, {failed} failed "
          f"(failovers: {fleet.instruments.failovers.value}, "
          f"victim breaker: {victim.breaker.state})")
    assert failed == 0 and victim.poisoned

    # 3. the reconcile tick respawns it — same slice, zero compiles
    rec = fleet.controller.reconcile()
    act = next(a for a in rec["actions"] if a["action"] == "respawn")
    print(f"healed: respawned on slice {act['slice']} "
          f"(cause={act['cause']}, fresh_compiles="
          f"{act['fresh_compiles']}, {act['respawn_ms']:.0f} ms)")
    assert act["slice"] == victim_slice and act["fresh_compiles"] == 0
    assert all(r.healthy for r in m.group.snapshot())

    # 4. sustained pressure walks the degraded ladder down, one named
    #    level per flip; at 'quantized' the SAME submit serves int8
    for _ in range(2 * fleet.ladder.down_after):
        fleet.ladder.observe(True)
    assert fleet.healthz()["degraded_mode"] == "quantized"
    before = fleet.cache.stats["compiles"]
    fleet.output("ranker", rng.rand(2, 16).astype(np.float32))
    print(f"ladder at '{fleet.ladder.name}': routing flipped to int8 "
          f"v{fleet._route_version(m)} "
          f"({fleet.cache.stats['compiles'] - before} fresh compiles)")
    for _ in range(2 * fleet.ladder.up_after):
        fleet.ladder.observe(False)                 # hysteresis recovery
    print(f"pressure cleared: ladder recovered to '{fleet.ladder.name}' "
          f"after {len(fleet.ladder.transitions)} audited transitions")

    # 5. snapshot, "crash", rebuild to the pre-crash topology
    fleet.save_snapshot()
    shape_before = sorted(r.slice.index for r in m.group.snapshot())
    fleet.shutdown()

    fleet2 = build()                                # the restarted process
    fleet2.deploy("ranker", _net(),
                  slo=LatencySLO(target_p99_ms=200.0, priority=10))
    report = fleet2.restore_snapshot()
    m2 = fleet2.member("ranker")
    print(f"restored from snapshot: members {report['restored']}, "
          f"replicas back on slices "
          f"{sorted(r.slice.index for r in m2.group.snapshot())} "
          f"(fresh compiles: {report['fresh_compiles']})")
    assert report["fresh_compiles"] == 0
    assert sorted(r.slice.index
                  for r in m2.group.snapshot()) == shape_before
    fleet2.output("ranker", rng.rand(2, 16).astype(np.float32))
    fleet2.shutdown()
    print("fleet drained and shut down")


if __name__ == "__main__":
    main()
