"""Transfer learning: train a base net, freeze its features, replace the
head for a new task, fine-tune (reference dl4j-examples
`EditLastLayerOthersFrozen.java` + `TransferLearningHelper`)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.transferlearning import (TransferLearning,
                                                    TransferLearningHelper)
from deeplearning4j_tpu.train.updaters import Adam


def data(n, n_classes, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    labels = (x[:, :n_classes].argmax(1))
    return x, np.eye(n_classes, dtype=np.float32)[labels]


def main():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([DenseLayer(n_out=32, activation="relu"),
                   DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=4, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    base = MultiLayerNetwork(conf).init()
    x, y = data(256, 4, seed=0)
    for _ in range(30):
        base.fit(x, y)
    print(f"base task loss: {base.score():.4f}")

    # freeze layers 0-1, swap the 4-way head for a 2-way one
    derived = (TransferLearning.builder(base)
               .set_feature_extractor(1)
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=2, loss="mcxent",
                                      activation="softmax"))
               .build())
    x2, y2 = data(256, 2, seed=1)
    for _ in range(30):
        derived.fit(x2, y2)
    print(f"fine-tuned new-task loss: {derived.score():.4f}")

    # helper: featurize once through the frozen trunk, then train the head
    # on cached features (fast path for repeated epochs; original 4-class
    # head, so original-task labels)
    helper = TransferLearningHelper(base, frozen_till=1)
    feats = helper.featurize(DataSet(x, y))
    helper.fit_featurized(feats)
    print("featurize-then-fit path OK")


if __name__ == "__main__":
    main()
