"""Quantized serving demo (docs/quantization.md): post-training
quantization end to end — calibrate a trained model, quantize its
weights to per-channel int8, gate on f32 parity, then roll the quantized
version through a serving fleet and watch the warm-pool residency drop.

Shows the quant surface end to end:
 1. train a small MLP, calibrate activation ranges with the percentile
    observer (outlier-clipping histograms over a representative sample),
 2. `quantize_model`: int8 weights + bf16 fallback report, ~4x fewer
    resident parameter bytes, dequantize fused into the jitted forward,
 3. `parity_check` accuracy gate (top-1 disagreement vs the f32 model),
 4. distinct f32/int8 executable fingerprints — the quantized program is
    its own entry in the serving + persistent AOT caches,
 5. `fleet.quantize("m")`: zero-downtime quantized version roll, f32
    predecessor demoted to host, residency re-budgeted at int8 bytes.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def _net(n_in=32, hidden=128, n_out=10):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=hidden, activation="relu"),
                   DenseLayer(n_out=hidden, activation="relu"),
                   OutputLayer(n_out=n_out, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def main():
    import tempfile

    import jax

    from deeplearning4j_tpu.compile import model_fingerprint
    from deeplearning4j_tpu.quant import (calibrate, parity_check,
                                          quantize_model)
    from deeplearning4j_tpu.serving import ModelFleet

    rng = np.random.RandomState(0)
    net = _net()
    # a learnable synthetic task: class = argmax of a fixed projection
    proj = rng.randn(32, 10).astype(np.float32)
    x_train = rng.randn(512, 32).astype(np.float32)
    y_train = np.eye(10, dtype=np.float32)[np.argmax(x_train @ proj, -1)]
    for _ in range(20):
        net.fit(x_train, y_train)

    # 1. calibrate over a representative sample
    calib = [rng.randn(64, 32).astype(np.float32) for _ in range(8)]
    stats = calibrate(net, calib, observer="percentile", percentile=99.9)
    print(f"calibrated {len(stats.ranges)} activation ranges over "
          f"{stats.batches} batches (crc 0x{stats.crc32():08x})")

    # 2. quantize: per-channel int8, bf16 fallback for hostile tensors
    qm = quantize_model(net, calibration=stats)
    f32_bytes = sum(l.nbytes
                    for l in jax.tree_util.tree_leaves(net.params_))
    print(f"dtype report: {qm.report}")
    print(f"resident bytes: {f32_bytes} f32 -> {qm.bytes_resident()} "
          f"quantized ({f32_bytes / qm.bytes_resident():.2f}x smaller)")

    # 3. accuracy gate BEFORE anything serves
    x_eval = rng.randn(512, 32).astype(np.float32)
    r = parity_check(net, qm, x_eval)
    print(f"parity: {r['task']} delta = {r['delta']:.4f}")
    assert r["delta"] <= 0.01, "quantization hurt accuracy; do not roll"

    # 4. the quantized program is its own executable-cache entry
    print(f"fingerprint f32   = {model_fingerprint(net)[:16]}…")
    print(f"fingerprint int8  = {model_fingerprint(qm)[:16]}…")

    # 5. fleet-wide quantized version roll
    cache_dir = tempfile.mkdtemp(prefix="quant-exec-cache-")
    with ModelFleet(max_resident=2, max_batch=8, batch_timeout_ms=2.0,
                    cache_dir=cache_dir) as fleet:
        fleet.deploy("m", net)
        before = fleet.output("m", x_eval[:4])
        b0 = fleet.resident_bytes()
        entry = fleet.quantize("m", calibration=stats)
        b1 = fleet.resident_bytes()
        after = fleet.output("m", x_eval[:4])   # served by v2 (int8)
        print(f"fleet roll: v{entry.version} source={entry.source}, "
              f"residency {b0} -> {b1} bytes "
              f"({b0 / max(b1, 1):.2f}x)")
        assert np.argmax(after, -1).tolist() == \
            np.argmax(before, -1).tolist()
    print("OK")


if __name__ == "__main__":
    main()
