"""Fault-tolerant training — checkpoints, auto-resume, divergence guard.

A production run dies mid-training (preemption, OOM, plain crash) and is
relaunched with the same command line; the relaunch must pick up where
the dead process stopped and finish with *bitwise-identical* parameters
to a run that was never interrupted.  This example stages that whole
story in one process (docs/robustness.md):

1. train a reference net uninterrupted;
2. train the same net under `FaultTolerantTrainer` with an async
   `CheckpointManager`, and let a `chaos.KillSwitch` hook crash the run
   partway;
3. "relaunch": rebuild the net from scratch, point a fresh trainer at
   the same checkpoint directory, train again — it auto-resumes from the
   newest intact checkpoint, fast-forwards the iterator, and the final
   parameters match the reference bit for bit;
4. re-run with a poisoned (exploding) batch in the stream and a
   `DivergenceGuard` that skips the bad update instead of letting one
   rotten batch destroy the run.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import shutil
import tempfile

import numpy as np

from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.resilience import (CheckpointManager,
                                                 DivergenceGuard,
                                                 FaultTolerantTrainer)
from deeplearning4j_tpu.utils import chaos

rng = np.random.default_rng(0)
X = rng.standard_normal((96, 16)).astype(np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 96)]
EPOCHS = 4                              # batch 8 -> 12 steps/epoch, 48 total


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list([DenseLayer(n_out=32, activation="tanh"),
                   OutputLayer(n_out=4, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def data():
    return ArrayDataSetIterator(X, Y, 8)


def main():
    work = tempfile.mkdtemp(prefix="ft_example_")
    try:
        # ---- 1. the uninterrupted reference ----------------------------
        norm = NormalizerStandardize().fit(data())
        ref = make_net()
        FaultTolerantTrainer(ref, normalizer=norm).fit(data(), epochs=EPOCHS)
        print(f"reference: {ref.iteration} steps, "
              f"score {float(ref.score()):.6f}")

        # ---- 2. the run that dies --------------------------------------
        ckpt_dir = os.path.join(work, "ckpt")
        net = make_net()
        mgr = CheckpointManager(ckpt_dir, keep_last=3, save_every_steps=5,
                                async_save=True)
        boom = chaos.KillSwitch(at_step=30, mode="exception",
                                marker=os.path.join(work, "killed_once"))
        try:
            FaultTolerantTrainer(net, mgr, normalizer=norm,
                                 hooks=[boom]).fit(data(), epochs=EPOCHS)
        except chaos.ChaosError:
            print(f"crashed at step {net.iteration} "
                  f"(newest checkpoint: step {mgr.latest_step()})")

        # ---- 3. the "relaunch" -----------------------------------------
        # Fresh process in real life: nothing survives but the checkpoint
        # directory.  No normalizer is passed in — the trainer rebuilds it
        # from checkpoint metadata.
        net = make_net()
        mgr = CheckpointManager(ckpt_dir, keep_last=3, save_every_steps=5,
                                async_save=True)
        trainer = FaultTolerantTrainer(net, mgr)
        trainer.fit(data(), epochs=EPOCHS)
        print(f"resumed from step {trainer.resumed_from['step']}, "
              f"finished at {net.iteration}")
        bitwise = np.array_equal(np.asarray(ref.params()),
                                 np.asarray(net.params()))
        print(f"bitwise match with uninterrupted run: {bitwise}")
        assert bitwise, "auto-resume must be invisible to the math"

        # ---- 4. divergence guard ---------------------------------------
        Xbad = X.copy()
        Xbad[40:48] = np.nan            # batch 5 is corrupt: NaN loss
        guarded = make_net()
        guard = DivergenceGuard(policy="skip", max_score=50.0)
        FaultTolerantTrainer(guarded, normalizer=norm, divergence=guard).fit(
            ArrayDataSetIterator(Xbad, Y, 8), epochs=EPOCHS)
        print(f"guard skipped {guard.events} poisoned update(s); final "
              f"score {float(guarded.score()):.6f} stayed finite")
        assert np.isfinite(float(guarded.score()))
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
