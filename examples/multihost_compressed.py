"""Hierarchical compressed cross-host gradient all-reduce (the Aeron
threshold GradientSharing role at DCN scale — SURVEY.md §3.4).

This script is both driver and worker.  Run it plain and it launches a
simulated 2-host gang (`LocalLauncher`: real OS processes, each with its
own XLA CPU client, coupled ONLY by the TCP gradient mesh), once with
the dense f32 wire and once with threshold-compressed int streams, then
compares bytes-on-wire and final loss.  Inside a launched worker (the
launcher env is set) it trains with `HierarchicalGradientSharing`:
the compiled grad half reduces over the local devices (ICI role), the
host-side exchange combines across processes (DCN role, error-feedback
residuals), the compiled apply half updates.

    python examples/multihost_compressed.py
"""
import json
import os
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np                                         # noqa: E402

STEPS, BATCH, N_IN = 80, 32, 16


def worker():
    """One simulated host: train on this rank's shard of a shared
    deterministic stream, exchanging gradients over the TCP mesh."""
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import HierarchicalGradientSharing
    from deeplearning4j_tpu.parallel.multihost import ENV_NPROC, ENV_PID
    from deeplearning4j_tpu.train.updaters import Sgd

    out_dir, mode = sys.argv[1], sys.argv[2]
    rank = int(os.environ[ENV_PID])
    world = int(os.environ[ENV_NPROC])
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=32, activation="tanh"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    # rank/world/port resolve from the env the launcher exported
    net.set_gradient_sharing(HierarchicalGradientSharing(
        threshold=5e-3, compressed=(mode == "compressed")))

    rng = np.random.RandomState(0)      # same stream on every rank
    for _ in range(STEPS):
        x = rng.randn(world * BATCH, N_IN).astype(np.float32)
        labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
        y = np.eye(3, dtype=np.float32)[labels]
        net.fit(x[rank::world], y[rank::world])   # this rank's shard

    stats = net.gradient_sharing.stats()
    stats["final_loss"] = net.score()
    with open(os.path.join(out_dir, f"{mode}_{rank}.json"), "w") as f:
        json.dump(stats, f)
    net.set_gradient_sharing(None)      # close the mesh sockets
    print(f"rank {rank}/{world} [{mode}]: final loss "
          f"{stats['final_loss']:.4f}, wire bytes "
          f"{stats['bytes_sent_total'] + stats['bytes_received_total']}")


def driver():
    from deeplearning4j_tpu.parallel.multihost import (LocalLauncher,
                                                       free_port)
    me = os.path.abspath(__file__)
    results = {}
    with tempfile.TemporaryDirectory() as td:
        for mode in ("dense", "compressed"):
            print(f"--- launching 2-host gang ({mode} wire) ---")
            LocalLauncher(num_processes=2, devices_per_process=2).run(
                me, [td, mode], timeout=300.0, gradient_port=free_port())
            stats = []
            for r in range(2):
                with open(os.path.join(td, f"{mode}_{r}.json")) as f:
                    stats.append(json.load(f))
            results[mode] = {
                "wire_bytes": sum(s["bytes_sent_total"]
                                  + s["bytes_received_total"]
                                  for s in stats),
                "final_loss": float(np.mean([s["final_loss"]
                                             for s in stats]))}
    d, c = results["dense"], results["compressed"]
    print(f"\ndense:      {d['wire_bytes']:>9} bytes on wire, "
          f"final loss {d['final_loss']:.4f}")
    print(f"compressed: {c['wire_bytes']:>9} bytes on wire, "
          f"final loss {c['final_loss']:.4f}")
    print(f"=> {d['wire_bytes'] / c['wire_bytes']:.1f}x fewer cross-host "
          f"bytes, loss delta "
          f"{abs(c['final_loss'] - d['final_loss']) / d['final_loss']:.2%}")


if __name__ == "__main__":
    if os.environ.get("DL4J_TPU_PROCESS_ID") is not None:
        worker()
    else:
        driver()
