"""Fused multi-step training dispatch — hiding host latency on TPU.

The reference's canonical hot loop (`MultiLayerNetwork.fit(DataSetIterator)`,
SURVEY.md §3.1) dispatches one compiled step per batch.  Through a remote
PJRT link each dispatch costs ~3 ms of host latency (measured,
bench_artifacts/PERF_ANALYSIS.md round 5) — dead time the TPU spends idle.

The TPU-native fix: `fit(iterator, fused_steps=k)` stacks k consecutive
batches and trains them in ONE compiled dispatch (`lax.scan` over the
steps axis), so the host pays its latency once per k steps.  The math is
identical to per-step dispatch — same updater chain, rng stream, and
iteration counters — which this example asserts.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import time

import numpy as np

from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Adam


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list([DenseLayer(n_out=64, activation="relu"),
                   DenseLayer(n_out=64, activation="relu"),
                   OutputLayer(n_out=4, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def main():
    rng = np.random.RandomState(0)
    x = rng.rand(1024, 16).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 1024)]

    # 1) the explicit API: a [k, batch, ...] block -> one dispatch
    net = make_net()
    xs = x.reshape(16, 64, 16)        # 16 steps of batch 64
    ys = y.reshape(16, 64, 4)
    losses = net.fit_steps(xs, ys)
    print(f"fit_steps: {len(losses)} steps in one dispatch, "
          f"loss {float(losses[0]):.4f} -> {float(losses[-1]):.4f}")

    # 2) the iterator form: fit(..., fused_steps=k) fuses blocks of k
    #    and falls back to per-step dispatch for the epoch tail
    fused, plain = make_net(), make_net()
    t0 = time.perf_counter()
    fused.fit(ArrayDataSetIterator(x, y, batch_size=64), epochs=3,
              fused_steps=8)
    t_fused = time.perf_counter() - t0
    t0 = time.perf_counter()
    plain.fit(ArrayDataSetIterator(x, y, batch_size=64), epochs=3)
    t_plain = time.perf_counter() - t0
    print(f"3 epochs: fused {t_fused:.2f}s vs per-step {t_plain:.2f}s "
          f"(compile dominates at toy scale; the win is per-dispatch "
          f"latency x steps on real models)")

    # identical math: same final params either way
    np.testing.assert_allclose(np.asarray(fused.params()),
                               np.asarray(plain.params()), atol=0)
    assert fused.iteration == plain.iteration == 48
    print("fused and per-step training are bit-identical; "
          f"final loss {fused.score():.4f}")


if __name__ == "__main__":
    main()
