"""Autoregressive decode serving demo (docs/serving.md): prefill/decode
split with a paged, int8-quantizable KV cache and token-level continuous
batching — `serving.DecodeEngine`.

Shows the decode surface end to end:
 1. warm up an int8-KV engine (every prompt-bucket x batch-bucket program
    compiles once), then flood it with skewed prompt/generation lengths
    and prove ZERO fresh compiles,
 2. token-level continuous batching: sequences admit and retire
    mid-flight, so peak concurrency exceeds `max_decode_batch` requests
    served back to back,
 3. the paged-KV memory story: blocks held scale with actual generated
    length, and int8 pages fit several times more concurrent sequences
    into the same byte budget than an f32 contiguous cache,
 4. fleet membership: `deploy_decode` + per-token SLOs, then a replica
    killed mid-service — failover restarts the sequence from token 0 on
    a healthy replica and counts it.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def main():
    from deeplearning4j_tpu.serving import (DecodeEngine, LatencySLO,
                                            ModelFleet, TinyDecodeModel)

    model = TinyDecodeModel(vocab=96, d_model=64, n_heads=4, seed=0)
    rng = np.random.RandomState(0)

    # 1. int8-KV engine: warm every bucket, then a skewed flood recompiles
    #    nothing — prompt lengths bucket to pow2, batch rows bucket to
    #    pow2, block tables have a fixed max_pages width
    eng = DecodeEngine(model, kv_dtype="int8", num_blocks=96,
                       max_seq_len=64, max_decode_batch=4,
                       model_label="demo")
    programs = eng.warmup()
    baseline = eng.fresh_compiles()
    print(f"warmup compiled {programs} programs "
          f"({eng.fresh_compiles()} jit entries)")

    lens = [3, 5, 9, 14, 20, 33] * 3
    futs = [eng.submit(rng.randint(1, 96, size=n),
                       max_new_tokens=int(rng.randint(3, 12)),
                       deadline_ms=30_000.0)
            for n in lens]
    outs = [f.result(timeout=60) for f in futs]
    assert eng.fresh_compiles() == baseline
    toks = sum(len(o) for o in outs)
    print(f"flood: {len(outs)} sequences / {toks} tokens, prompt lengths "
          f"{sorted(set(lens))}, fresh compiles after warmup: "
          f"{eng.fresh_compiles() - baseline}")

    # 2. continuous batching: 18 sequences through a 4-row decode batch —
    #    a retiring sequence frees its row (and KV blocks) the same step,
    #    so the next waiting prompt admits mid-flight
    st = eng.stats()
    print(f"token-level batching: max_decode_batch=4 served "
          f"{len(outs)} sequences back to back; KV high water "
          f"{st['kv']['high_water']}/{st['kv']['blocks_total']} blocks, "
          f"now {st['kv']['blocks_in_use']} in use (all released)")

    # 3. memory A/B: paged int8 vs contiguous f32 worst-case reservation
    contig_f32 = 64 * model.n_heads * (model.d_model // model.n_heads) * 2 * 4
    one_seq_blocks = -(-15 // eng.page_size)   # 9 prompt + 6 generated
    paged_bytes = one_seq_blocks * eng.cache.bytes_per_block
    print(f"memory per sequence: contiguous f32 reserves {contig_f32} B "
          f"(max_seq_len worst case); paged int8 holds {paged_bytes} B "
          f"({one_seq_blocks} blocks for a 15-token sequence) — "
          f"{contig_f32 / paged_bytes:.1f}x denser")
    eng.shutdown()

    # 4. fleet membership + failover: decode members route through the
    #    same SLO admission path; a killed replica's sequences restart
    #    from token 0 on the live one (KV dies with the replica) and the
    #    restart is counted — an explicit cost, never a silent one
    from deeplearning4j_tpu.monitor.instrument import decode_instruments
    fleet = ModelFleet(max_resident=2)

    def factory(slice_):
        e = DecodeEngine(model, kv_dtype="int8", num_blocks=64,
                         max_seq_len=64, max_decode_batch=4,
                         model_label="gen")
        e.warmup()
        return e

    member = fleet.deploy_decode("gen", factory,
                                 slo=LatencySLO(target_p99_ms=1000.0),
                                 replicas=2)
    out = fleet.generate("gen", np.arange(1, 6),
                         max_new_tokens=5).result(timeout=60)
    print(f"fleet decode member '{member.name}' (kind={member.kind}, "
          f"{len(member.group.replicas)} replicas) generated "
          f"{len(out)} tokens; per-token SLO samples: "
          f"{member.latency.count}")

    before = decode_instruments().restarts("gen").value
    member.group.replicas[0].server.engine.kill()
    outs = [fleet.generate("gen", np.arange(1, 6),
                           max_new_tokens=3).result(timeout=60)
            for _ in range(6)]
    restarts = decode_instruments().restarts("gen").value - before
    print(f"replica 0 killed mid-service: {len(outs)}/6 sequences still "
          f"completed, {int(restarts)} restarted from token 0 on the "
          f"live replica (decode_sequence_restarts_total)")

    rec = fleet.controller.reconcile()
    heals = [a for a in rec["actions"] if a.get("kind") == "decode"]
    print(f"controller heal: {heals[0]['action']} cause="
          f"{heals[0]['cause']} — member respawns={member.respawns}, "
          f"readyz={fleet.readyz()['ready']}")
    fleet.shutdown()
    print("engine drained and fleet shut down")


if __name__ == "__main__":
    main()
