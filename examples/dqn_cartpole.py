"""Double-DQN with experience replay on CartPole (reference rl4j-examples
`Cartpole.java` — QLearningDiscreteDense)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from deeplearning4j_tpu.rl import (CartPole, QLearningConfiguration,
                                   QLearningDiscrete)


def main():
    env = CartPole(seed=0)
    cfg = QLearningConfiguration(
        seed=1, max_step=6_000, batch_size=64, target_update=250,
        update_start=500, gamma=0.99, eps_min=0.05, anneal_steps=3_000,
        replay_size=10_000)
    ql = QLearningDiscrete(env, cfg)
    rewards = ql.train()
    print(f"episodes: {len(rewards)}, "
          f"last-5 mean reward: {sum(rewards[-5:]) / 5:.1f}")

    policy = ql.get_policy()
    ret = policy.play(CartPole(seed=42))
    print(f"greedy policy return: {ret:.0f}")


if __name__ == "__main__":
    main()
