"""Pallas fused-kernel tier demo (docs/performance.md §7): every kernel
ships two implementations under one contract — a Pallas TPU kernel
parameterized by a `TileConfig`, and a pure-jnp reference that IS the
definition of correctness — selected per call by `ops.pallas.dispatch`.

Shows the tier end to end (on CPU the Pallas impls run in interpret
mode, so everything here works without an accelerator):
 1. conformance: flash attention vs the jnp reference, the int8-native
    matmul's integer contraction BITWISE vs the reference, fused dense
    bias+activation epilogues,
 2. dispatch: auto mode routes to the reference on CPU, forced-pallas
    drives the real kernels through interpret mode, every decision lands
    in `ops_kernel_dispatch_total{kernel=,impl=}`,
 3. tile autotuning: grid+greedy search over the kernel's tile space,
    winner persisted to `tiles-<device_kind>.json`, replayed on the next
    call with ZERO re-search,
 4. AOT identity: the installed tile schedule is part of
    `kernel_tier_fingerprint()`, so retuned programs never collide with
    default-tile or reference programs in the persistent cache.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def main():
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.compile import (autotune_tiles,
                                            kernel_tier_fingerprint,
                                            load_tile_table)
    from deeplearning4j_tpu.ops.pallas import attention as pa
    from deeplearning4j_tpu.ops.pallas import dispatch as kd
    from deeplearning4j_tpu.ops.pallas import matmul as pm
    from deeplearning4j_tpu.ops.pallas import (TileConfig, shape_class)

    kd.reset()
    rng = np.random.RandomState(0)
    interp = kd.interpret_mode()
    print(f"backend={jax.default_backend()}  interpret_mode={interp}")

    # -- 1. conformance: the reference is the spec --------------------------
    att_tile = TileConfig(block_q=32, block_kv=64)
    mm_tile = TileConfig(block_m=8, block_n=128, block_k=128)

    B, H, T, S, D = 1, 2, 100, 72, 64           # ragged on purpose
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    keep = (rng.rand(B, S) > 0.3).astype(np.float32)
    keep[:, 0] = 1.0                            # no fully-masked rows
    mask = jnp.asarray(keep)
    flash = pa.flash_attention(q, k, v, mask=mask, causal=True,
                               tile=att_tile, interpret=interp)
    ref = pa.attention_reference(q, k, v, mask=mask, causal=True)
    err = float(jnp.max(jnp.abs(flash - ref)))
    print(f"flash attention (causal+masked, ragged {T}x{S}): "
          f"max |err| = {err:.2e}")
    assert err < 2e-5

    M, K, N = 37, 70, 45
    xq = jnp.asarray(rng.randint(-128, 128, (M, K)), jnp.int8)
    wq = jnp.asarray(rng.randint(-128, 128, (K, N)), jnp.int8)
    ws = jnp.asarray(rng.rand(N) * 0.1 + 1e-3, jnp.float32)
    got = pm.int8_matmul(xq, wq, ws, tile=mm_tile, interpret=interp)
    want = pm.int8_matmul_reference(xq, wq, ws)
    assert bool(jnp.all(got == want))
    print(f"int8-native matmul ({M}x{K}x{N}): BITWISE equal to reference "
          "(integer contraction + fused dequant epilogue)")

    x = jnp.asarray(rng.randn(M, K), jnp.float32)
    w = jnp.asarray(rng.randn(K, N) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(N) * 0.1, jnp.float32)
    for act in ("relu", "gelu", "tanh"):
        got = pm.fused_dense(x, w, bias=b, activation=act,
                             tile=mm_tile, interpret=interp)
        want = pm.fused_dense_reference(x, w, bias=b, activation=act)
        assert float(jnp.max(jnp.abs(got - want))) < 2e-5
    print("fused dense bias+activation epilogues (relu/gelu/tanh): OK")

    # -- 2. dispatch: auto vs forced, observable ----------------------------
    from deeplearning4j_tpu.monitor.instrument import ops_instruments
    auto = kd.resolve("int8_matmul", xq, wq, ws)
    prev = kd.set_dispatch_mode("pallas")
    forced = kd.resolve("int8_matmul", xq, wq, ws)
    kd.set_dispatch_mode(prev)
    n_ref = ops_instruments().dispatch("int8_matmul", "reference").value
    n_pal = ops_instruments().dispatch("int8_matmul", "pallas").value
    print(f"dispatch: auto->{auto} forced->{forced}  "
          f"(counter: reference={n_ref:.0f} pallas={n_pal:.0f})")
    on_accel = kd.on_accelerator() and kd.pallas_available()
    assert auto == ("pallas" if on_accel else "reference")
    assert forced == ("pallas" if kd.pallas_available() else "reference")

    # -- 3. tile autotune: search -> persist -> replay ----------------------
    calls = {"n": 0}

    def measure(cfg):          # stand-in rate; on TPU you'd time the kernel
        calls["n"] += 1
        return -(abs(cfg.block_m - 256) + abs(cfg.block_n - 128)
                 + abs(cfg.block_k - 1024))

    sc = shape_class(m=2048, k=2048, n=2048)
    tdir = tempfile.mkdtemp(prefix="pallas-tiles-")
    try:
        tile, info = autotune_tiles("int8_matmul", sc, measure, tdir)
        print(f"tile search: {info['evaluated']} configs evaluated -> "
              f"winner (bm={tile.block_m}, bn={tile.block_n}, "
              f"bk={tile.block_k}) persisted to {os.path.basename(info['path'])}")
        n_before = calls["n"]
        tile2, info2 = autotune_tiles("int8_matmul", sc, measure, tdir)
        assert info2["source"] == "cache" and calls["n"] == n_before
        assert tile2 == tile
        print(f"tile replay: source={info2['source']}, zero re-search "
              f"({calls['n'] - n_before} measure calls)")
        table = load_tile_table(tdir)
        assert f"int8_matmul/{sc}" in table

        # -- 4. AOT identity: the tile is part of the fingerprint ----------
        fp = kernel_tier_fingerprint()
        assert fp["tiles"][f"int8_matmul/{sc}"] == tile.to_json()
        kd.clear_tiles()
        assert kernel_tier_fingerprint()["tiles"] == {}
        print(f"kernel_tier_fingerprint: mode={fp['mode']} "
              f"tiles={list(fp['tiles'])} — folded into model_fingerprint, "
              "so retuned programs get their own AOT cache entries")
    finally:
        kd.reset()
        shutil.rmtree(tdir, ignore_errors=True)

    print("pallas kernel tier demo: OK")


if __name__ == "__main__":
    main()
