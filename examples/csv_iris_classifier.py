"""CSV → TransformProcess → normalizer → classifier (the DataVec
pipeline; reference dl4j-examples `IrisClassifier.java` / datavec
examples)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.data import (CSVRecordReader,
                                     RecordReaderDataSetIterator)
from deeplearning4j_tpu.data.normalizers import NormalizerStandardize
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train.evaluation import Evaluation
from deeplearning4j_tpu.train.updaters import Adam


def iris_csv(n=150, seed=0):
    """Generate an iris-like CSV in-memory (no downloads): 3 separable
    clusters over 4 features."""
    rng = np.random.RandomState(seed)
    rows = ["sl,sw,pl,pw,species"]
    centers = np.array([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3],
                        [6.6, 3.0, 5.6, 2.0]])
    for i in range(n):
        c = i % 3
        v = centers[c] + rng.randn(4) * 0.25
        rows.append(",".join(f"{x:.2f}" for x in v) + f",{c}")
    return "\n".join(rows)


def main():
    reader = CSVRecordReader(text=iris_csv(), skip_lines=1)
    it = RecordReaderDataSetIterator(reader, batch_size=30, label_index=4,
                                     num_classes=3)

    normalizer = NormalizerStandardize()
    normalizer.fit(it)
    it.set_pre_processor(normalizer)

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(5e-2))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=30)

    ev = net.evaluate(it, Evaluation())
    print(ev.stats())
    assert ev.accuracy() > 0.9


if __name__ == "__main__":
    main()
