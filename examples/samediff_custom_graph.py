"""SameDiff-equivalent graph engine: declare a custom graph, train it,
use control flow, round-trip through serialization (reference
samediff-examples)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.train.updaters import Adam


def main():
    sd = SameDiff.create()
    x = sd.placeholder("input", shape=(-1, 4))
    y = sd.placeholder("label", shape=(-1, 3))
    w0 = sd.var("w0", "XAVIER", 4, 32)
    b0 = sd.var("b0", np.zeros(32, np.float32))
    w1 = sd.var("w1", "XAVIER", 32, 3)
    h = sd.nn.tanh(sd.nn.linear(x, w0, b0))
    logits = sd.op("matmul", h, w1, name="logits")
    sd.nn.softmax(logits, name="out")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2),
        data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))

    rng = np.random.RandomState(0)
    xs = rng.randn(128, 4).astype(np.float32)
    labels = (xs[:, 0] > 0).astype(int) + (xs[:, 1] > 0).astype(int)
    ys = np.eye(3, dtype=np.float32)[labels]
    for _ in range(60):
        sd.fit(xs, ys)
    print(f"loss: {sd.score():.4f}")
    acc = (np.asarray(sd.output({'input': xs}, 'out')['out']).argmax(1)
           == labels).mean()
    print(f"train accuracy: {acc:.2f}")

    # control flow: scan a running sum over a sequence inside the graph
    sd2 = SameDiff.create()
    seq = sd2.placeholder("seq", shape=(8,))
    total, partials = sd2.scan(
        lambda s, carry, t: (s.op("add", carry, t),) * 2,
        sd2.constant("z", np.float32(0.0)), seq, name="running")
    out = sd2.output({"seq": np.arange(8, dtype=np.float32)}, total)
    print(f"scan sum(0..7) = {float(np.asarray(out[total.name])):.0f}")

    # serialization round-trip
    sd.save("/tmp/samediff_model.zip")
    sd3 = SameDiff.load("/tmp/samediff_model.zip")
    a = np.asarray(sd.output({"input": xs[:4]}, "out")["out"])
    b = np.asarray(sd3.output({"input": xs[:4]}, "out")["out"])
    np.testing.assert_allclose(a, b, atol=1e-6)
    print("serialization round-trip: outputs identical")


if __name__ == "__main__":
    main()
