"""Async end-to-end training input pipeline — prefetch, on-device
normalization, sync-free loop.

The compiled train step leaves three host-side stalls in the steady-state
loop (docs/performance.md):

1. batches are ETL'd and normalized on host, serialized with compute;
2. every `fit` pays one host dispatch, and host `np.stack` copies pay
   again on the fused path;
3. listeners that read `score()` force a device sync every iteration.

This example composes the three fixes from `deeplearning4j_tpu.data.pipeline`:
`DevicePrefetchIterator` (producer-thread ETL + depth-bounded device
staging), `net.set_normalizer(...)` (the fitted normalizer replayed as a
jitted on-device prologue, bitwise identical to the host transform), and
`fit(..., fused_steps=k)` over pre-staged device batches (stacked inside
the compiled dispatch).  Score collection stays lazy (`score_array()`)
until read.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import time

import numpy as np

from deeplearning4j_tpu.data import (DataSet, DataSetIterator,
                                     DevicePrefetchIterator,
                                     NormalizerStandardize)
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.listeners import CollectScoresListener


class SyntheticEtlIterator(DataSetIterator):
    """Materializes each batch from raw float64 rows on demand — the
    per-batch host cost a record-reader/augmentation pipeline pays.  With
    `DevicePrefetchIterator` this work runs in the producer thread,
    overlapped with the previous steps' compute."""

    def __init__(self, raw_x, raw_y, batch):
        self.raw_x, self.raw_y, self._batch = raw_x, raw_y, batch

    def __iter__(self):
        for i in range(0, len(self.raw_x), self._batch):
            x = (self.raw_x[i:i + self._batch]).astype(np.float32)
            y = np.eye(4, dtype=np.float32)[self.raw_y[i:i + self._batch]]
            yield DataSet(x, y)

    def reset(self):
        pass

    def batch_size(self):
        return self._batch

    def __len__(self):
        return (len(self.raw_x) + self._batch - 1) // self._batch


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list([DenseLayer(n_out=64, activation="relu"),
                   DenseLayer(n_out=64, activation="relu"),
                   OutputLayer(n_out=4, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def main():
    rng = np.random.RandomState(0)
    raw_x = rng.rand(4096, 16) * 50.0          # raw float64 "records"
    raw_y = rng.randint(0, 4, 4096)
    iterator = SyntheticEtlIterator(raw_x, raw_y, batch=128)

    # fit the normalizer on host ONCE; training replays it on device
    nz = NormalizerStandardize().fit(iterator)

    net = make_net()
    net.set_normalizer(nz)                     # on-device prologue
    collect = CollectScoresListener()          # lazy: no per-iter sync
    net.listeners = [collect]

    pf = DevicePrefetchIterator(iterator, depth=2)   # double-buffer H2D
    try:
        t0 = time.perf_counter()
        net.fit(pf, epochs=3, fused_steps=8)   # streaming fused epochs
        final = float(net.score())             # the ONE blocking read
        dt = time.perf_counter() - t0
    finally:
        pf.close()                             # joins the producer thread

    scores = collect.scores                    # coercion happens here
    print(f"3 epochs x {len(iterator)} batches in {dt:.2f}s "
          f"(prefetch depth 2, fused_steps=8)")
    print(f"score: {scores[0]:.4f} -> {final:.4f}, "
          f"{len(scores)} collected without per-iteration syncs")
    assert final < scores[0]
    assert pf.active_producers() == 0


if __name__ == "__main__":
    main()
