"""Character-level LSTM language model + sampling (reference
dl4j-examples `LSTMCharModellingExample.java` — GravesLSTM char-LM)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.zoo import TextGenLSTM

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 8
SEQ_LEN = 32


def main():
    chars = sorted(set(CORPUS))
    idx = {c: i for i, c in enumerate(chars)}
    v = len(chars)
    enc = np.asarray([idx[c] for c in CORPUS], np.int32)

    # one-hot windows, next-char targets
    starts = np.arange(0, len(enc) - SEQ_LEN - 1, SEQ_LEN // 2)
    xs = np.stack([enc[s:s + SEQ_LEN] for s in starts])
    ys = np.stack([enc[s + 1:s + SEQ_LEN + 1] for s in starts])
    x = np.eye(v, dtype=np.float32)[xs]
    y = np.eye(v, dtype=np.float32)[ys]

    from deeplearning4j_tpu.train.updaters import Adam
    net = TextGenLSTM(n_classes=v, input_shape=(SEQ_LEN, v),
                      lstm_units=96, updater=Adam(5e-3)).init_model()
    for epoch in range(120):
        net.fit(x, y)
    print(f"final loss: {net.score():.3f}")

    # greedy generation from a seed
    seed = "the quick "
    state = [idx[c] for c in seed]
    rng = np.random.RandomState(0)
    for _ in range(60):
        window = state[-SEQ_LEN:]
        inp = np.eye(v, dtype=np.float32)[np.asarray(window)][None]
        probs = np.asarray(net.output(inp))[0, len(window) - 1]
        p = probs / probs.sum()
        state.append(int(rng.choice(v, p=p)))
    print("sample:", "".join(chars[i] for i in state))


if __name__ == "__main__":
    main()
