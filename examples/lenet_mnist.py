"""LeNet on MNIST — the canonical first example (reference
dl4j-examples `LeNetMNIST.java`).

Uses the real MNIST IDX files when MNIST_DIR points at them; otherwise
the deterministic synthetic stand-in (zero-egress environments)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.data import MnistDataSetIterator, SyntheticMnist
from deeplearning4j_tpu.train.evaluation import Evaluation
from deeplearning4j_tpu.zoo import LeNet


def make_iterators(batch=64):
    try:
        return (MnistDataSetIterator(batch, train=True),
                MnistDataSetIterator(batch, train=False))
    except FileNotFoundError:
        print("MNIST_DIR not set — using synthetic MNIST")
        return (SyntheticMnist(batch, n_batches=20, seed=0),
                SyntheticMnist(batch, n_batches=5, seed=1))


def main():
    train_it, test_it = make_iterators()
    net = LeNet(n_classes=10).init_model()
    print(f"LeNet: {net.num_params():,} params")

    net.fit(train_it, epochs=2)
    print(f"final train batch loss: {net.score():.4f}")

    ev = net.evaluate(test_it, Evaluation())
    print(ev.stats())

    # checkpoint round-trip with exact resume (updater state included)
    net.save("/tmp/lenet.zip")
    from deeplearning4j_tpu.nn import MultiLayerNetwork
    restored = MultiLayerNetwork.load("/tmp/lenet.zip")
    x = next(iter(test_it)).features
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(restored.output(x)), atol=1e-6)
    print("checkpoint round-trip: outputs identical")


if __name__ == "__main__":
    main()
