"""Serving runtime demo (docs/serving.md): a zoo LeNet behind
`serving.ModelServer` under concurrent mixed-shape traffic.

Shows the production-serving surface end to end:
 1. deploy from the zoo catalog with bucket warmup (all XLA compiles paid
    before traffic),
 2. many client threads submitting different batch sizes — the continuous
    batcher aggregates them into few bucket-padded dispatches,
 3. per-request deadlines + bounded-queue load shedding (typed errors),
 4. SLO metrics (p50/p99, occupancy, compile-cache hit rate) and the
    live UI `/serving` endpoint.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np                                         # noqa: E402


def main():
    from concurrent.futures import ThreadPoolExecutor

    from deeplearning4j_tpu.serving import (DeadlineExceededError,
                                            ModelServer)

    srv = ModelServer(max_batch=32, batch_timeout_ms=5.0, max_queue=256)

    # 1. deploy + warm: every power-of-two bucket compiles NOW, so no
    # request ever waits on XLA
    entry = srv.deploy("lenet", zoo="LeNet", warmup=True)
    print(f"deployed {entry.key} from {entry.source}; warmed buckets "
          f"{entry.warmed_buckets} "
          f"({srv.metrics.cache.misses.value} compiles)")

    # 2. concurrent mixed-shape clients
    def client(i):
        rs = np.random.RandomState(i)
        x = rs.rand(1 + i % 4, 28, 28, 1).astype(np.float32)
        y = srv.output("lenet", x, deadline_ms=2000.0, timeout=60)
        assert y.shape == (x.shape[0], 10)
        return x.shape[0]

    with ThreadPoolExecutor(max_workers=16) as ex:
        rows = sum(ex.map(client, range(48)))
    s = srv.stats()
    print(f"served 48 requests ({rows} rows) in {s['dispatches']} "
          f"dispatches — occupancy {s['batch_occupancy']:.1f} req/dispatch, "
          f"p50 {s['latency_ms']['p50']:.1f} ms, "
          f"p99 {s['latency_ms']['p99']:.1f} ms, cache hit rate "
          f"{s['compile_cache']['hit_rate']:.0%}")

    # 3. deadlines fail fast with a typed error
    try:
        srv.submit("lenet", np.zeros((1, 28, 28, 1), np.float32),
                   deadline_ms=0.0).result(timeout=10)
    except DeadlineExceededError as e:
        print(f"past-deadline request failed fast: {e}")

    # 4. live metrics endpoint (scrape http://127.0.0.1:<port>/serving)
    from deeplearning4j_tpu.ui.server import UIServer
    ui = UIServer.get_instance().attach_serving(srv)
    port = ui.start(0)
    import json
    import urllib.request
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/serving", timeout=10) as r:
        scraped = json.loads(r.read())
    print(f"UI /serving endpoint live on port {port}: "
          f"{scraped[0]['completed']} completed, occupancy "
          f"{scraped[0]['batch_occupancy']:.1f}")
    ui.stop()

    srv.shutdown()      # graceful: drains in-flight futures; idempotent
    srv.shutdown()
    print("server drained and shut down")


if __name__ == "__main__":
    main()
