"""BERT masked-LM pretraining step + sequence-classification fine-tune
over the BertIterator masking pipeline (reference dl4j BertIterator +
SameDiff BERT training; here via the native `zoo.BertModel`).

A toy vocab/corpus keeps it fast; swap in a real WordPiece vocab file and
corpus for production."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from deeplearning4j_tpu.nlp import BertIterator, BertWordPieceTokenizer
from deeplearning4j_tpu.train.updaters import Adam
from deeplearning4j_tpu.zoo import BertConfig, BertModel

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
         "the", "quick", "brown", "fox", "jumped", "over", "lazy", "dog",
         "good", "bad", "movie", "great", "terrible"]


def main():
    tok = BertWordPieceTokenizer(VOCAB)
    cfg = BertConfig(vocab_size=len(VOCAB), hidden=64, n_layers=2,
                     n_heads=4, intermediate=128, max_len=16)

    # --- masked-LM phase ---
    corpus = ["the quick brown fox jumped over the lazy dog"] * 16
    mlm_it = BertIterator(tok, corpus, batch_size=8, max_length=16,
                          task=BertIterator.TASK_UNSUPERVISED, seed=0)
    model = BertModel(cfg, updater=Adam(1e-3))
    model.fit(mlm_it, epochs=3)
    print(f"MLM loss after pretrain: {model.score():.4f}")

    # --- classification fine-tune (same encoder weights) ---
    sents = ["good great movie", "great good fox", "bad terrible movie",
             "terrible bad dog"] * 8
    labels = [1, 1, 0, 0] * 8
    cls_it = BertIterator(tok, sents, batch_size=8, max_length=16,
                          task=BertIterator.TASK_SEQ_CLASSIFICATION,
                          labels=labels, n_classes=2, seed=1)
    model.fit(cls_it, epochs=10)
    print(f"classifier loss: {model.score():.4f}")

    ids, mask = next(iter(cls_it)).features
    probs = np.asarray(model.output_cls(ids, mask))
    print("class probabilities (first 4):\n", probs[:4].round(3))


if __name__ == "__main__":
    main()
