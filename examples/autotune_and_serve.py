"""Persistent AOT executable cache + schedule autotuner — warm restarts.

XLA compilation dominates cold-start: every process that builds the same
model pays the same multi-second `jit` stall before its first step.  The
`deeplearning4j_tpu.compile` package removes the repeat payments:

1. `PersistentExecutableCache` — serialized compiled executables on disk,
   keyed by (jax/backend version, topology, model program, arg shapes).
   A restarted process deserializes instead of recompiling: same math,
   ~10x faster to first step (`bench.py --aot`).
2. `ScheduleAutotuner` — measures steps/sec over a small config space
   (fused_steps, prefetch depth, donation, ZeRO-1) and persists the
   winning `Schedule`; later runs `load_schedule()` and start tuned.

This example trains cold, "restarts" (fresh model objects, same cache
dir), and shows the warm path does zero compiles while producing
bit-identical scores; then it autotunes a schedule, saves it, and brings
up a ModelServer-style serving cache warm from the same directory.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import tempfile
import time

import numpy as np

from deeplearning4j_tpu.compile import (PersistentExecutableCache,
                                        ScheduleAutotuner, load_schedule,
                                        save_schedule)
from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import BucketedCompileCache
from deeplearning4j_tpu.train import Adam


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list([DenseLayer(n_out=64, activation="relu"),
                   OutputLayer(n_out=4, loss="mcxent", activation="softmax")])
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def make_data(n=512, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 16).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, n)]
    return x, y


def train(cache_dir, steps=8):
    """One 'process': build the model, route its step through the cache."""
    cache = PersistentExecutableCache(cache_dir)
    net = make_net().set_executable_cache(cache)
    x, y = make_data()
    t0 = time.perf_counter()
    net.fit(x[:64], y[:64])                  # pays (or skips) the compile
    t_first = time.perf_counter() - t0
    for i in range(1, steps):
        net.fit(x[64 * (i % 8):64 * (i % 8) + 64],
                y[64 * (i % 8):64 * (i % 8) + 64])
    return net, cache, t_first


def main():
    workdir = tempfile.mkdtemp(prefix="dl4j-aot-example-")

    # ---- 1) cold process: compiles once, stores the executable ----------
    net1, c1, t_cold = train(workdir)
    print(f"cold : first step {t_cold * 1e3:7.1f} ms   "
          f"compiles={c1.stats['compiles']} stores={c1.stats['stores']}")

    # ---- 2) 'restart': fresh objects, same directory -> zero compiles ---
    net2, c2, t_warm = train(workdir)
    print(f"warm : first step {t_warm * 1e3:7.1f} ms   "
          f"compiles={c2.stats['compiles']} disk_hits={c2.stats['disk_hits']}")
    assert c2.stats["compiles"] == 0, "warm restart must not compile"
    assert float(net1.score()) == float(net2.score()), "bitwise parity"
    print(f"       identical scores ({net2.score():.6f}), "
          f"{t_cold / max(t_warm, 1e-9):.1f}x faster to first step")

    # ---- 3) autotune a schedule and persist it --------------------------
    x, y = make_data(1024)

    def measure(schedule):
        net = make_net().set_executable_cache(PersistentExecutableCache(workdir))
        schedule.apply(net)
        it = ArrayDataSetIterator(x, y, batch_size=64)
        net.fit(it, fused_steps=schedule.fused_steps)   # compile excluded...
        it.reset()
        t0 = time.perf_counter()
        net.fit(it, fused_steps=schedule.fused_steps)   # ...time steady state
        steps = (len(x) // 64) / max(time.perf_counter() - t0, 1e-9)
        return steps

    best = ScheduleAutotuner(
        measure, space={"fused_steps": [1, 8], "prefetch_depth": [2],
                        "donation": [True]},
        refine_rounds=0).search()
    path = save_schedule(best, workdir, name="example")
    print(f"tuned: fused_steps={best.fused_steps} -> "
          f"{best.steps_per_sec:.0f} steps/s "
          f"(baseline {best.meta['baseline_steps_per_sec']:.0f}); "
          f"saved {os.path.basename(path)}")

    # a later process starts tuned instead of re-searching
    loaded = load_schedule(workdir, name="example")
    assert loaded is not None and loaded.fused_steps == best.fused_steps

    # ---- 4) serving comes up warm from the same directory ---------------
    scache = BucketedCompileCache(max_batch=16, persistent=workdir)
    scache.warmup("mlp:v1", make_net(), trailing=(16,), dtype=np.float32,
                  parallel=True)
    out = scache.run("mlp:v1", make_net(seed=9), make_data(5)[0])
    print(f"serve: warmed buckets {scache.buckets}, "
          f"compiles={scache.persistent.stats['compiles']} "
          f"disk_hits={scache.persistent.stats['disk_hits']}, "
          f"served {out.shape[0]} rows")
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
