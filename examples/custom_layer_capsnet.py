"""Custom layers via the SameDiffLayer escape hatch + CapsNet (reference
samediff-layer examples and the CapsNet config classes)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import dataclasses                                         # noqa: E402

import jax                                                 # noqa: E402
import numpy as np                                         # noqa: E402

from deeplearning4j_tpu.nn import (CapsuleLayer,           # noqa: E402
                                   CapsuleStrengthLayer, InputType,
                                   LossLayer, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   PrimaryCapsules, SameDiffLayer,
                                   register_layer)
from deeplearning4j_tpu.train.updaters import Adam         # noqa: E402


@register_layer
@dataclasses.dataclass(kw_only=True)
class GatedDense(SameDiffLayer):
    """out = (xW + b) * sigmoid(xG): declare params, write the forward in
    plain jnp — the whole escape-hatch contract."""

    n_out: int = 0

    def define_parameters(self, input_type):
        f = input_type.shape[-1]
        return {"W": (f, self.n_out), "G": (f, self.n_out),
                "b": ((self.n_out,), "ZERO")}

    def define_layer(self, params, x, mask=None):
        return (x @ params["W"] + params["b"]) * jax.nn.sigmoid(
            x @ params["G"])

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


def main():
    rng = np.random.RandomState(0)

    # --- custom gated layer in a standard network ---
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list([GatedDense(n_out=24),
                   OutputLayer(n_out=2, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.randn(64, 8).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    for _ in range(40):
        net.fit(x, y)
    print(f"gated-dense custom layer loss: {net.score():.4f}")
    # registered subclasses serialize like built-ins
    net.save("/tmp/gated.zip")
    print("saved/loadable:", bool(MultiLayerNetwork.load("/tmp/gated.zip")))

    # --- CapsNet: primary capsules -> dynamic routing -> lengths ---
    caps_conf = (NeuralNetConfiguration.builder().seed(0)
                 .updater(Adam(3e-3))
                 .list([PrimaryCapsules(capsules=4, capsule_dim=4,
                                        kernel_size=5, stride=2),
                        CapsuleLayer(capsules=3, capsule_dim=8,
                                     routings=3),
                        CapsuleStrengthLayer(),
                        LossLayer(loss="mcxent", activation="softmax")])
                 .set_input_type(InputType.convolutional(12, 12, 1))
                 .build())
    caps = MultiLayerNetwork(caps_conf).init()
    labels = rng.randint(0, 3, 48)
    imgs = np.zeros((48, 12, 12, 1), np.float32)
    for i, c in enumerate(labels):        # class = bright quadrant
        r, col = divmod(c, 2)
        imgs[i, r * 6:(r + 1) * 6, col * 6:(col + 1) * 6] = 1.0
    yc = np.eye(3, dtype=np.float32)[labels]
    for _ in range(50):
        caps.fit(imgs, yc)
    acc = (np.asarray(caps.output(imgs)).argmax(1) == labels).mean()
    print(f"capsnet quadrant task accuracy: {acc:.2f}")


if __name__ == "__main__":
    main()
