"""Keras import → pretrained-artifact conversion → dynamic-batching
serving (round-3 surface; reference analogs: `KerasModelImport`,
`ZooModel.initPretrained`, `ParallelInference` with ObservablesProvider).

Builds a Bidirectional-LSTM sequence classifier in TF-Keras with random
weights, saves the H5, then:
 1. imports it (predictions match TF),
 2. converts it to a model-zip pretrained artifact via the converter CLI
    machinery,
 3. serves it behind `DynamicBatchingInference`, with concurrent clients
    whose requests are aggregated into batched dispatches.
"""
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np                                         # noqa: E402


def main():
    import tensorflow as tf
    from deeplearning4j_tpu.modelimport import KerasModelImport
    from deeplearning4j_tpu.nn import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import (DynamicBatchingInference,
                                             ParallelInference, make_mesh)
    from deeplearning4j_tpu.zoo.convert import convert

    tf.keras.utils.set_random_seed(0)
    km = tf.keras.Sequential([
        tf.keras.layers.Input((12, 5)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.LSTM(16, return_sequences=True)),
        tf.keras.layers.TimeDistributed(
            tf.keras.layers.Dense(8, activation="tanh")),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(3, activation="softmax")])

    with tempfile.TemporaryDirectory() as tmp:
        h5 = os.path.join(tmp, "model.h5")
        km.save(h5)

        # 1. import: predictions must match TF
        net = KerasModelImport.import_keras_sequential_model_and_weights(h5)
        x = np.random.RandomState(0).randn(6, 12, 5).astype(np.float32)
        ours = np.asarray(net.output(x))
        theirs = km.predict(x, verbose=0)
        np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)
        print(f"import ok: max|Δ| vs TF = {np.abs(ours - theirs).max():.2e}")

        # 2. convert to the pretrained artifact (model zip)
        artifact = os.path.join(tmp, "model.zip")
        print(convert(h5, artifact, "zip"))
        served_net = MultiLayerNetwork.load(artifact, False)

        # 3. serve with dynamic request batching
        pi = ParallelInference(served_net, mesh=make_mesh())
        dyn = DynamicBatchingInference(pi, max_batch=32, timeout_ms=100.0)
        from concurrent.futures import ThreadPoolExecutor
        reqs = [np.random.RandomState(i).randn(n, 12, 5).astype(np.float32)
                for i, n in enumerate((1, 3, 2, 4, 1, 5))]
        with ThreadPoolExecutor(max_workers=6) as ex:
            outs = list(ex.map(dyn.output, reqs))
        dyn.shutdown()
        for r, o in zip(reqs, outs):
            assert o.shape == (r.shape[0], 3)
        print(f"served {len(reqs)} concurrent requests "
              f"({sum(r.shape[0] for r in reqs)} rows) through dynamic "
              "batching — shapes and routing correct")


if __name__ == "__main__":
    main()
