"""Elastic gang survival — heartbeats, generation-fenced re-formation,
checkpoint-coordinated resume (docs/robustness.md).

This script is both supervisor and worker.  Run it plain and it launches
a 3-process gang (`ElasticLocalRunner.run_elastic`: real OS processes
coupled only by the elastic TCP gradient mesh) and kills rank 2 mid-run
with a `chaos.PeerKiller` hook.  The survivors detect the death within
the failure deadline, re-form at world 2 under a new membership
generation (in-flight frames from the dead generation are fenced, never
summed into a gradient), rewind to the coordinated checkpoint, and keep
training.  The supervisor relaunches a replacement with
`DL4J_TPU_JOIN=1`; under the `block` rejoin policy the coordinator
admits it and the gang finishes back at world 3 — every member with
identical parameters.

    python examples/elastic_gang_training.py
"""
import json
import os
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np                                         # noqa: E402

STEPS, N_IN, N_OUT, GLOBAL_BATCH = 20, 16, 3, 12
KILL_RANK, KILL_STEP = 2, 6


def worker():
    """One gang member: train on the strided shard of a deterministic
    global stream, sharded by the member's LIVE gang rank — a
    reformation re-shards the same stream at the new world size."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import DataSetIterator
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.parallel import HierarchicalGradientSharing
    from deeplearning4j_tpu.parallel.multihost import ENV_CKPT, ENV_PID
    from deeplearning4j_tpu.train.resilience import (CheckpointManager,
                                                     ElasticTrainer)
    from deeplearning4j_tpu.train.updaters import Sgd
    from deeplearning4j_tpu.utils.chaos import PeerKiller

    out_dir = sys.argv[1]
    rank = int(os.environ[ENV_PID])
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list([DenseLayer(n_out=32, activation="tanh"),
                   OutputLayer(n_out=N_OUT, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    # heartbeat / deadline / join knobs resolve from the supervisor's env
    net.set_gradient_sharing(HierarchicalGradientSharing(
        threshold=5e-3, elastic=True))

    class GangShardIterator(DataSetIterator):
        def __iter__(self):
            for i in range(STEPS):
                rng = np.random.RandomState(1000 + i)
                xg = rng.randn(GLOBAL_BATCH, N_IN).astype(np.float32)
                labels = ((xg[:, 0] > 0).astype(int)
                          + (xg[:, 1] > 0).astype(int))
                yg = np.eye(N_OUT, dtype=np.float32)[labels]
                sharing = net.gradient_sharing
                r, w = sharing.rank, sharing.world
                yield DataSet(xg[r::w], yg[r::w])

        def __len__(self):
            return STEPS

        def batch_size(self):
            return GLOBAL_BATCH

    # only the coordinator writes checkpoints; peers rewind from the
    # same directory on every reformation
    manager = CheckpointManager(
        os.environ[ENV_CKPT], keep_last=50,
        save_every_steps=1 if rank == 0 else None)
    killer = PeerKiller(KILL_RANK, KILL_STEP, mode="kill",
                        marker=os.path.join(out_dir, "killed_once"))
    trainer = ElasticTrainer(
        net, manager, hooks=[killer], rejoin_wait_s=60.0,
        policy=os.environ.get("DL4J_TPU_ELASTIC_POLICY", "shrink"),
        save_initial=(rank == 0))
    trainer.fit(GangShardIterator(), epochs=1)

    stats = net.gradient_sharing.stats()
    for rf in trainer.reformations:
        detect = (f" (detected in {rf['detection_ms']:.1f} ms)"
                  if rf["detection_ms"] is not None else "")
        print(f"rank {rank}: reformed ({rf['cause']}) -> generation "
              f"{rf['generation']}, world {rf['world']}, resumed from "
              f"step {rf['resume_step']}{detect}", flush=True)
    np.savez(os.path.join(out_dir, f"final_{rank}.npz"),
             params=np.asarray(net.params()))
    net.set_gradient_sharing(None)      # close the gang sockets
    print(f"rank {rank}: done at iteration {net.iteration} "
          f"(world={stats['world']}, generation={stats['generation']}, "
          f"loss={net.score():.4f})", flush=True)


def supervisor():
    from deeplearning4j_tpu.parallel.multihost import ElasticLocalRunner
    me = os.path.abspath(__file__)
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "out")
        os.makedirs(out)
        print(f"--- launching 3-process elastic gang (rank {KILL_RANK} "
              f"dies at step {KILL_STEP}) ---")
        results = ElasticLocalRunner(
            num_processes=3, backoff_base_s=0.2).run_elastic(
                me, [out], timeout=300.0,
                checkpoint_dir=os.path.join(td, "ckpt"),
                policy="block", heartbeat_s=0.1, failure_deadline_s=2.0,
                relaunch=True, max_replacements=1)
        for label in sorted(results):
            rc, output = results[label]
            tail = [ln for ln in output.strip().splitlines()
                    if "rank" in ln][-2:]
            status = "ok" if rc == 0 else f"exit {rc}"
            print(f"[{label}] {status}")
            for ln in tail:
                print(f"    {ln}")
        finals = [np.load(os.path.join(out, f"final_{r}.npz"))["params"]
                  for r in range(3)]
        same = all(np.array_equal(finals[0], f) for f in finals[1:])
        print(f"\n=> all 3 members finished with "
              f"{'IDENTICAL' if same else 'DIVERGED'} parameters after "
              "kill -> shrink -> rejoin")


if __name__ == "__main__":
    if os.environ.get("DL4J_TPU_PROCESS_ID") is not None:
        worker()
    else:
        supervisor()
