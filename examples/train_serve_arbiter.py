"""Pod arbiter: preemption-safe slice handoffs between an elastic
training gang and a serving fleet (docs/robustness.md, "Pod arbiter").

One pod, two workloads.  The `SliceArbiter` owns the pod's DeviceSlice
inventory and moves slices between a training gang and a `ModelFleet` as
a two-phase, journaled state machine:

  1. serving pressure rises -> `to_serving()`: the gang commits a
     BLOCKING checkpoint, shrinks at that exact step (survivors
     bitwise-rewind), and the freed slice is leased to the fleet;
  2. pressure fades -> `to_training()`: the fleet drains the slice's
     replicas under a deadline and the gang re-admits the slice at a
     bumped generation;
  3. a crash mid-handoff (here: simulated right after the phase-1
     journal write) is recovered by a relaunched arbiter replaying the
     journal — the slice ends single-owned, the handoff completes.

Runs on CPU in a few seconds: python examples/train_serve_arbiter.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import tempfile                                            # noqa: E402

import numpy as np                                         # noqa: E402

from deeplearning4j_tpu.monitor.registry import MetricsRegistry  # noqa: E402
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import ModelFleet
from deeplearning4j_tpu.serving.slo import ArbiterPolicy
from deeplearning4j_tpu.train.arbiter import LocalElasticGang, SliceArbiter
from deeplearning4j_tpu.train.resilience import CheckpointManager
from deeplearning4j_tpu.train.updaters import Sgd

workdir = tempfile.mkdtemp(prefix="pod-arbiter-")
journal = os.path.join(workdir, "journal.json")

# ---- the training side: a model + real checkpoint manager ----
conf = (NeuralNetConfiguration.builder().seed(42).updater(Sgd(0.1))
        .list([DenseLayer(n_out=32, activation="relu"),
               OutputLayer(n_out=3, loss="mcxent", activation="softmax")])
        .set_input_type(InputType.feed_forward(8)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.RandomState(0)
x = rng.randn(32, 8).astype(np.float32)
y = np.eye(3, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
net.fit(x, y)

manager = CheckpointManager(os.path.join(workdir, "ckpt"), keep_last=20)
gang = LocalElasticGang(net, manager, slices=[0, 1, 2])

# ---- the serving side: a fleet sharing the pod ----
fleet = ModelFleet(max_resident=2, n_slices=1,
                   cache_dir=os.path.join(workdir, "exec-cache"),
                   registry_=MetricsRegistry())
fleet.deploy("classifier", model=net, input_shape=(8,), warm=True)

# ---- the arbiter over both ----
policy = ArbiterPolicy(grant_at_forecast=1.5, return_below_forecast=0.5,
                       min_training_slices=1, drain_timeout_s=2.0)
arb = SliceArbiter(journal, training=gang, fleet=fleet, policy=policy)
fleet.attach_arbiter(arb)                   # growth consults the leases
print(f"lease table: {arb.owners()}")

# 1. the morning spike: pressure over the grant threshold moves a slice
out = arb.maybe_rebalance(pressure=2.0)
print(f"to_serving : slice {out['slice']} -> fleet index "
      f"{arb.fleet_index_of(out['slice'])} "
      f"(gang checkpointed at step {out['resume_step']}, "
      f"world {gang.world}, generation {gang.generation})")
preds = fleet.submit("classifier", x[:4]).result(timeout=30)
print(f"serving on the grown fleet: predictions {preds.shape}")

# 2. the evening lull: pressure under the return threshold reclaims it
out = arb.maybe_rebalance(pressure=0.1)
print(f"to_training: slice {out['slice']} back "
      f"(drained {out['released']['drained'] or 'nothing routed'}, "
      f"gang world {gang.world}, generation {gang.generation})")

# 3. crash mid-handoff: die right after the phase-1 journal write …
class _CrashAfterPhase1(Exception):
    pass


class _Chaos:
    fired = False

    def on_journal(self, direction, phase):
        if not self.fired and phase == "shrink":
            self.fired = True
            raise _CrashAfterPhase1()       # stands in for os._exit(9)


arb.chaos = _Chaos()
try:
    arb.to_serving()
except _CrashAfterPhase1:
    print("arbiter 'crashed' after the phase-1 journal write "
          "(intent durable, nothing executed)")

# … and relaunch over the SAME journal: the constructor replays it
arb2 = SliceArbiter(journal, training=gang, fleet=fleet, policy=policy)
fleet.attach_arbiter(arb2)
rec = arb2.recovered
print(f"relaunched arbiter replayed the handoff: slice {rec['slice']} "
      f"-> {rec['outcome']} (journal replays: "
      f"{arb2.describe()['replays']})")
assert rec["outcome"] == "replayed"
assert arb2.owners()[rec["slice"]] == "serving"
assert rec["slice"] not in gang.held_slices()        # single-owned

fleet.shutdown()
print(f"final lease table: {arb2.owners()}")
print("done.")
