"""ZeRO-1 optimizer-state sharding with ParallelWrapper (Xu et al.,
arXiv:2004.13336 — "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training").

Plain data parallelism replicates the Adam moments (2x the params!) and
the weight update on every replica.  `optimizer_sharding(True)` makes the
one compiled step reduce-scatter the gradients over the data axis, run
the optimizer on each replica's 1/N shard, and all-gather the updated
params — same math, ~N× less optimizer-state HBM per replica.

Run with real chips, or simulate a mesh on CPU:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/zero1_training.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS") == "cpu":
    # a 1-device run would degenerate the sharding — force a virtual
    # 4-way mesh before jax initializes
    if "device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4").strip()
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax                                                 # noqa: E402
import numpy as np                                         # noqa: E402

from deeplearning4j_tpu.monitor import set_enabled        # noqa: E402
from deeplearning4j_tpu.monitor.registry import registry  # noqa: E402
from deeplearning4j_tpu.nn import (                       # noqa: E402
    DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
    OutputLayer)
from deeplearning4j_tpu.parallel import ParallelWrapper   # noqa: E402
from deeplearning4j_tpu.train.updaters import Adam        # noqa: E402


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list([DenseLayer(n_out=512, activation="relu"),
                   DenseLayer(n_out=512, activation="relu"),
                   OutputLayer(n_out=10, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(128)).build())
    return MultiLayerNetwork(conf).init()


def main():
    set_enabled(True)
    print(f"devices: {jax.devices()}")
    rng = np.random.RandomState(0)
    x = rng.randn(64, 128).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 64)]

    # --- A: plain data parallelism (optimizer state replicated) ---------
    net_a = make_net()
    pw_a = ParallelWrapper.builder(net_a).build()
    for _ in range(5):
        pw_a.fit(x, y)

    # --- B: ZeRO-1 — same math, sharded weight update -------------------
    net_b = make_net()
    pw_b = (ParallelWrapper.builder(net_b)
            .optimizer_sharding(True)       # the one-line opt-in
            .build())
    for _ in range(5):
        pw_b.fit(x, y)

    # parity: with_sharding_constraint is value-preserving, so the two
    # trajectories are identical
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))),
        net_a.params_, net_b.params_)
    max_diff = max(jax.tree_util.tree_leaves(diffs))
    print(f"max param diff after 5 Adam steps: {max_diff:.2e}")
    assert max_diff < 1e-5

    # the HBM headline: per-replica optimizer-state bytes, from the
    # telemetry gauge pair the wrapper records at placement
    repl = registry().get("training_opt_state_bytes", {"sharded": "false"})
    shrd = registry().get("training_opt_state_bytes", {"sharded": "true"})
    print(f"optimizer state per replica: {int(repl.value):,} B replicated "
          f"-> {int(shrd.value):,} B sharded "
          f"({repl.value / shrd.value:.1f}x smaller)")

    # composes with the fused k-step dispatch (collectives stay inside
    # the compiled scan body) — and zero1= can toggle it per call
    xs = np.broadcast_to(x, (4,) + x.shape).copy()
    ys = np.broadcast_to(y, (4,) + y.shape).copy()
    losses = pw_b.fit_steps(xs, ys, zero1=True)
    print(f"fused block of {len(losses)} sharded-update steps in one "
          f"dispatch, loss -> {float(losses[-1]):.4f}")

    # before portable checkpoints, drop back to true-shape moments
    pw_b.optimizer_sharding(False)
    print("sharding disabled; moments back at true shapes for save()")


if __name__ == "__main__":
    main()
