"""Cross-host fleet federation — host failure domains, generation-fenced
membership, warm host-loss re-placement (docs/robustness.md).

This script is both supervisor and worker.  Run it plain and it starts a
`FederationRouter` front door plus 3 worker processes, each a full
`ModelFleet` (model "m", deployed warm against one SHARED persistent AOT
cache) wrapped by a `HostAgent` that joins the router over loopback TCP.
A `HostChaos(mode="kill", os_kill=True)` hook hard-kills the host that
rendezvous-affinity routes "m" to, two dispatches into the client flood.
The router detects the EOF in milliseconds, evicts the host under a
bumped membership generation (stale in-flight replies are fenced, never
returned), fails the in-flight request over to a survivor with its
remaining deadline budget, and warm-re-places the dead host's model from
its replicated topology snapshot — zero fresh compiles.  The supervisor
then relaunches the killed host under the same host_id: it is re-admitted
at a bumped generation and offered its own snapshot back, restoring
compile-free.  No accepted request is lost at any point.

    python examples/federated_fleet.py
"""
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np                                         # noqa: E402

N_IN, N_OUT, HOSTS = 8, 3, ("h1", "h2", "h3")
KILL_AFTER = 2                    # victim dies 2 dispatches into the flood


def _net():
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.train.updaters import Sgd
    # every host builds the SAME seeded net, so a survivor re-places a
    # dead host's model straight from the shared AOT cache
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(1e-1))
            .list([DenseLayer(n_out=16, activation="relu"),
                   OutputLayer(n_out=N_OUT, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def worker(host_id: str, port: int, work_dir: str, kill_after: int):
    from deeplearning4j_tpu.serving import (FederationPolicy, HostAgent,
                                            LatencySLO, ModelFleet)
    from deeplearning4j_tpu.utils.chaos import HostChaos

    host_dir = os.path.join(work_dir, host_id)
    os.makedirs(host_dir, exist_ok=True)
    fleet = ModelFleet(max_resident=2, n_slices=2, max_batch=8,
                       batch_timeout_ms=1.0,
                       cache_dir=os.path.join(work_dir, "exec-cache"),
                       snapshot_path=os.path.join(host_dir, "snapshot.json"),
                       snapshot_interval_s=0.2, host_id=host_id)
    fleet.deploy("m", _net(),
                 slo=LatencySLO(target_p99_ms=2000.0, priority=5), warm=True)
    policy = FederationPolicy(heartbeat_interval_s=0.1,
                              failure_deadline_s=0.8,
                              straggler_deadline_s=5.0)
    agent = HostAgent(host_id, fleet, ("127.0.0.1", port), policy=policy,
                      replicas_dir=os.path.join(host_dir, "replicas"))
    agent.start(timeout=30.0)
    if kill_after >= 0:
        # marker file keeps the relaunched replacement from re-firing
        chaos = HostChaos(mode="kill", at_dispatch=kill_after, os_kill=True,
                          marker=os.path.join(work_dir, f"{host_id}.killed"))
        if chaos.armed():
            chaos.arm(agent)
    fleet.save_snapshot()            # replicate topology to the router
    if agent.restored:
        print(f"{host_id}: restored from replicated snapshot "
              f"(fresh_compiles={agent.restored['fresh_compiles']})",
              flush=True)
    with open(os.path.join(work_dir, f"{host_id}.ready"), "w") as f:
        json.dump({"generation": agent.generation}, f)
    print(f"{host_id}: joined at generation {agent.generation}", flush=True)
    stop = os.path.join(work_dir, "stop")
    while not os.path.exists(stop):
        time.sleep(0.05)
    agent.close()
    fleet.shutdown()
    print(f"{host_id}: done at generation {agent.generation}", flush=True)


def _spawn(host_id, port, work_dir, kill_after=-1):
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), host_id, str(port),
         work_dir, str(kill_after)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _wait_file(path, timeout, what):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.05)


def supervisor():
    from deeplearning4j_tpu.serving import FederationRouter
    from deeplearning4j_tpu.serving.federation import _rendezvous
    from deeplearning4j_tpu.serving.slo import FederationPolicy

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as td:
        policy = FederationPolicy(heartbeat_interval_s=0.1,
                                  failure_deadline_s=0.8,
                                  straggler_deadline_s=5.0)
        router = FederationRouter(
            policy, replicas_dir=os.path.join(td, "router-replicas"))
        port = router.start(port=0)
        victim = _rendezvous(list(HOSTS), "m")   # affinity host for "m"
        print(f"--- launching 3-host federation (router :{port}; "
              f"{victim} carries 'm' and dies {KILL_AFTER} dispatches "
              f"into the flood) ---")
        procs = {h: _spawn(h, port, td, KILL_AFTER if h == victim else -1)
                 for h in HOSTS}
        for h in HOSTS:
            _wait_file(os.path.join(td, f"{h}.ready"), 90.0, f"{h} join")
        while set(router.federation_stats()["replicas"]) < set(HOSTS):
            time.sleep(0.05)         # snapshots replicated to the router
        print(f"federation formed: hosts={router.hosts()} "
              f"generation={router.generation}")

        served = 0
        deadline = time.monotonic() + 60.0
        while not any(e["event"] == "replaced" and e["host"] == victim
                      for e in router.events):
            if time.monotonic() > deadline:
                raise TimeoutError("host never re-placed")
            x = rng.randn(2, N_IN).astype(np.float32)
            y = router.output("m", x, deadline_ms=8000.0)
            assert y.shape == (2, N_OUT)
            served += 1
        evict = next(e for e in router.events if e["event"] == "evict")
        repl = next(e for e in router.events if e["event"] == "replaced")
        print(f"served {served}/{served} requests across the host kill "
              f"(zero lost)")
        print(f"evicted {evict['host']} cause={evict['cause']} "
              f"detected in {evict['detection_ms']:.1f} ms "
              f"-> generation {evict['generation']}")
        print(f"re-placed {repl['models']} on {repl['on']} in "
              f"{repl['replace_ms']:.1f} ms (warm={repl['warm']}, "
              f"fresh_compiles={repl['fresh_compiles']})")
        assert repl["fresh_compiles"] == 0 and repl["warm"]

        gen_before = router.generation
        print(f"--- relaunching {victim} under the same host_id ---")
        relaunched = _spawn(victim, port, td)    # no chaos this time
        while victim not in router.hosts():
            time.sleep(0.05)
        y = router.output("m", rng.randn(2, N_IN).astype(np.float32),
                          deadline_ms=8000.0)
        assert y.shape == (2, N_OUT)
        print(f"{victim} re-admitted: generation {gen_before} -> "
              f"{router.generation}, hosts={router.hosts()}")

        open(os.path.join(td, "stop"), "w").close()
        outputs = {victim: procs.pop(victim).communicate()[0]}
        outputs[f"{victim}'"] = relaunched.communicate()[0]
        outputs.update({h: p.communicate()[0] for h, p in procs.items()})
        for label in sorted(outputs):
            tail = [ln for ln in outputs[label].strip().splitlines()
                    if ":" in ln][-2:]
            for ln in tail:
                print(f"    [{label}] {ln}")
        router.shutdown()
        print("\n=> federation survived a hard host kill with zero lost "
              "requests, a compile-free warm re-placement, and a "
              "generation-fenced re-admission")


if __name__ == "__main__":
    if len(sys.argv) >= 4:
        worker(sys.argv[1], int(sys.argv[2]), sys.argv[3],
               int(sys.argv[4]) if len(sys.argv) > 4 else -1)
    else:
        supervisor()
