"""Unified telemetry — one registry across training, pipeline and serving.

Runs an instrumented end-to-end slice of the framework (docs/observability.md):

1. train an MLP through the async `DevicePrefetchIterator` pipeline —
   step timing, compile events, prefetch depth and producer wait record
   into the process-wide `monitor.MetricsRegistry` as a side effect;
2. serve the trained net from a `ModelServer` — its `ServingMetrics` is a
   view over the SAME registry, labeled `server="sN"`;
3. wrap a custom section in `span(...)` (nested spans record as
   "parent/child" and forward into `jax.profiler.TraceAnnotation`);
4. start the `UIServer` and scrape `GET /metrics` — the Prometheus text a
   real scraper would ingest — then print the interesting series.

Backend-agnostic; run on CPU with `JAX_PLATFORMS=cpu python
examples/telemetry.py`.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# honor JAX_PLATFORMS even where a site plugin overrides jax's own env
# handling (e.g. remote-TPU shims): mirror it into the config
import os                                                  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax                                             # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import urllib.request                                      # noqa: E402

import numpy as np                                         # noqa: E402

from deeplearning4j_tpu.data import DataSet                # noqa: E402
from deeplearning4j_tpu.data.iterators import (            # noqa: E402
    ListDataSetIterator)
from deeplearning4j_tpu.data.pipeline import (             # noqa: E402
    DevicePrefetchIterator)
from deeplearning4j_tpu.monitor import registry, span      # noqa: E402
from deeplearning4j_tpu.nn import (DenseLayer, InputType,  # noqa: E402
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import ModelServer         # noqa: E402
from deeplearning4j_tpu.ui.server import UIServer          # noqa: E402


def main():
    rng = np.random.RandomState(0)

    # -- 1. instrumented training through the prefetch pipeline ----------
    conf = (NeuralNetConfiguration.builder().seed(7)
            .list([DenseLayer(n_out=32, activation="relu"),
                   OutputLayer(n_out=3, loss="mcxent",
                               activation="softmax")])
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    batches = [DataSet(rng.rand(16, 8).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
               for _ in range(16)]

    with span("example", section="train"):     # custom nested span
        pf = DevicePrefetchIterator(ListDataSetIterator(batches), depth=2)
        try:
            net.fit(pf, epochs=3)              # fit wraps each epoch in
        finally:                               # span("fit_epoch") itself
            pf.close()

    # -- 2. serving against the same registry ----------------------------
    server = ModelServer(max_batch=16, batch_timeout_ms=2.0)
    ui = UIServer()
    try:
        server.deploy("mlp", net)
        for _ in range(20):
            server.output("mlp", rng.rand(4, 8).astype(np.float32))

        # -- 3. scrape /metrics like Prometheus would ---------------------
        port = ui.start(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
    finally:
        ui.stop()
        server.shutdown()

    print("== /metrics (selected series) ==")
    for line in text.splitlines():
        if line.startswith(("training_", "pipeline_", "serving_latency",
                            "serving_queue", "span_ms")) \
                and "quantile" not in line:
            print(" ", line)

    # -- 4. the same numbers, host-side ----------------------------------
    snap = registry().snapshot()
    lbl = {"model": "MultiLayerNetwork"}
    steps = registry().get("training_steps_total", lbl)
    compiles = registry().get("training_compiles_total", lbl)
    print(f"\nsteps trained: {steps.value}")
    print(f"compiles: {compiles.value}")
    span_keys = [k for k in snap["histograms"] if k.startswith("span_ms")]
    print(f"span series: {span_keys}")


if __name__ == "__main__":
    main()
