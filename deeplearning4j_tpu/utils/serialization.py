"""ModelSerializer — checkpoint read/write.

Reference: `deeplearning4j-nn/.../util/ModelSerializer.java` — a zip holding
`configuration.json` + `coefficients.bin` (flat param buffer) + updater
state (+ optional normalizer).  The format here keeps those exact semantics
(exact-resume: updater state incl. iteration/epoch counters round-trips) with
the same member names, so tooling expectations carry over; tensor payloads
are raw little-endian buffers with a JSON manifest of shapes/dtypes.

For sharded multi-host checkpoints see parallel/ (orbax-backed); this module
is the single-process contract used by CheckpointListener and save/load.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

CONFIG_JSON = "configuration.json"
COEFFICIENTS_BIN = "coefficients.bin"
UPDATER_BIN = "updaterState.bin"
STATE_BIN = "layerState.bin"
MANIFEST_JSON = "manifest.json"
NORMALIZER_BIN = "normalizer.bin"


def _to_host(leaf) -> np.ndarray:
    """Device array -> host numpy, including multi-process global arrays:
    a replicated array spans non-addressable (remote) devices, but every
    process holds a complete local copy — read that shard.  Partition-
    sharded leaves must be all-gathered first (parallel.multihost
    .allgather_params), same contract as the reference's Spark
    driver-side param sync before ModelSerializer."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        shard = leaf.addressable_data(0)
        if shard.shape != leaf.shape:
            raise ValueError(
                "Cannot checkpoint a partition-sharded array from one "
                "process — gather it first (multihost.allgather_params)")
        return np.asarray(shard)
    return np.asarray(leaf)


def _tree_to_flat(tree: Any):
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return b"", []
    host = [_to_host(l) for l in leaves]
    manifest = [{"shape": list(l.shape), "dtype": str(l.dtype)}
                for l in host]
    buf = b"".join(np.ascontiguousarray(l).tobytes() for l in host)
    return buf, manifest


def _flat_to_tree(template: Any, buf: bytes, manifest):
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, off = [], 0
    for tmpl, m in zip(leaves, manifest):
        dt = np.dtype(m["dtype"])
        n = int(np.prod(m["shape"])) if m["shape"] else 1
        arr = np.frombuffer(buf, dt, count=n, offset=off).reshape(m["shape"])
        off += n * dt.itemsize
        out.append(jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


def write_model(net, path: str, save_updater: bool = True,
                normalizer=None) -> None:
    params_buf, params_manifest = _tree_to_flat(net.params_)
    state_buf, state_manifest = _tree_to_flat(net.state_)
    manifest = {
        "format": "deeplearning4j_tpu.model.v1",
        "iteration": net.iteration,
        "epoch": net.epoch,
        "params": params_manifest,
        "state": state_manifest,
    }
    upd_buf = b""
    if save_updater and net.opt_state_ is not None:
        upd_buf, upd_manifest = _tree_to_flat(net.opt_state_)
        manifest["updater"] = upd_manifest
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(CONFIG_JSON, net.conf.to_json())
        z.writestr(MANIFEST_JSON, json.dumps(manifest))
        z.writestr(COEFFICIENTS_BIN, params_buf)
        z.writestr(STATE_BIN, state_buf)
        if upd_buf:
            z.writestr(UPDATER_BIN, upd_buf)
        if normalizer is not None:
            z.writestr(NORMALIZER_BIN, normalizer.to_bytes())


def read_model(path: str, load_updater: bool = True):
    """Restore either model class; dispatch on the config `format` tag (the
    reference's ModelSerializer likewise restores MultiLayerNetwork or
    ComputationGraph from one zip format)."""
    from deeplearning4j_tpu.nn.multilayer import (
        MultiLayerConfiguration, MultiLayerNetwork)
    from deeplearning4j_tpu.nn.graph import (
        ComputationGraph, ComputationGraphConfiguration)
    with zipfile.ZipFile(path, "r") as z:
        conf_json = z.read(CONFIG_JSON).decode()
        manifest = json.loads(z.read(MANIFEST_JSON).decode())
        if "ComputationGraphConfiguration" in json.loads(conf_json).get("format", ""):
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(conf_json)).init()
        else:
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(conf_json)).init()
        net.params_ = _flat_to_tree(net.params_, z.read(COEFFICIENTS_BIN),
                                    manifest["params"])
        net.state_ = _flat_to_tree(net.state_, z.read(STATE_BIN),
                                   manifest["state"])
        net.iteration = manifest["iteration"]
        net.epoch = manifest["epoch"]
        if load_updater and UPDATER_BIN in z.namelist() and "updater" in manifest:
            net.opt_state_ = _flat_to_tree(net.opt_state_, z.read(UPDATER_BIN),
                                           manifest["updater"])
    return net


def read_normalizer(path: str, cls) -> Optional[Any]:
    with zipfile.ZipFile(path, "r") as z:
        if NORMALIZER_BIN not in z.namelist():
            return None
        return cls.from_bytes(z.read(NORMALIZER_BIN))
