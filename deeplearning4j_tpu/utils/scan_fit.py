"""Fused multi-step training dispatch (shared by MultiLayerNetwork,
ComputationGraph).

The TPU-native form of the reference's `fit(DataSetIterator)` hot loop
(`MultiLayerNetwork.fit(DataSetIterator)` upstream): per-step host
dispatch costs ~3 ms/step through a remote PJRT link (measured,
bench_artifacts/PERF_ANALYSIS.md round 5), so steady-state training
scans a compiled step over a device-resident `[k, batch, ...]` block —
one host dispatch per k steps, with params/updater-state/rng/iteration
flowing step-to-step as scan carries.
"""
import numpy as np

import jax
import jax.numpy as jnp


def blocks_of(iterator, k: int):
    """Group consecutive same-shape DataSets from `iterator` into lists of
    exactly `k` (ready for one fused `fit_steps` dispatch).  Batches that
    don't fill a block — the epoch tail, or a shape change mid-stream —
    are yielded as single-element lists so the caller takes the per-step
    path instead of compiling a new scan executable for a one-off k."""
    def shapes(x):
        if x is None:
            return None
        if isinstance(x, (list, tuple)):            # multi-input/-output
            return tuple(np.shape(e) for e in x)
        if isinstance(x, dict):
            return tuple(sorted((k, np.shape(v)) for k, v in x.items()))
        return np.shape(x)

    def first_attr(ds, *names):
        # NOT `a or b`: truthiness of a multi-element ndarray mask raises
        for n in names:
            v = getattr(ds, n, None)
            if v is not None:
                return v
        return None

    def key(ds):
        return (shapes(ds.features), shapes(ds.labels),
                shapes(first_attr(ds, "features_mask", "features_masks")),
                shapes(first_attr(ds, "labels_mask", "labels_masks")))

    buf, buf_key = [], None
    for ds in iterator:
        dk = key(ds)
        if buf and dk != buf_key:
            for b in buf:
                yield [b]
            buf = []
        buf.append(ds)
        buf_key = dk
        if len(buf) == k:
            yield buf
            buf = []
    for b in buf:
        yield [b]


def check_steps_axes(named_arrays):
    """Validate that every non-None array shares one leading steps axis.

    `named_arrays` is an iterable of (name, array-or-None); returns k.
    Raising here (with the offending name) beats the opaque
    'different leading axis sizes' error lax.scan gives after tracing."""
    k, ref = None, None
    for name, a in named_arrays:
        if a is None:
            continue
        if k is None:
            k, ref = a.shape[0], name
        elif a.shape[0] != k:
            raise ValueError(
                f"steps axis mismatch: '{name}' has {a.shape[0]} steps but "
                f"'{ref}' has {k} — every array needs the same leading "
                f"[k, batch, ...] steps axis")
    if k is None:
        raise ValueError("fit_steps needs at least one array input")
    return k


def make_scan_step(tick, key_base=None, cache=None, donate: bool = True):
    """Wrap a per-class `tick` adapter into the jitted k-step scan.

    `tick(carry, epoch, batch) -> (carry, loss)` adapts one class's step
    body to a scan carry (each class carries a different tuple: MLN/CG
    `(params, state, opt, rng, it)`, SameDiff `(vars, opt, rng, it)`,
    BERT `(params, opt, it)`).  The returned function is
    `step(carry, epoch, batches) -> (carry, losses)`; the whole carry is
    donated (every element is replaced from the return by the callers —
    `advance()` for the counter, attribute reassignment for the rest).
    `epoch` is NOT donated: `device_counters` caches it across calls.

    With `cache` + `key_base` (a `compile.PersistentExecutableCache` and a
    zero-arg disk-key-parts callable) the scan compiles through the
    persistent tier like the single-step builders — a restarted fused-fit
    loop deserializes instead of recompiling.  The batch block is the only
    dynamic argument (argnum 2)."""
    def many(carry, epoch, batches):
        if (isinstance(batches, (list, tuple)) and len(batches)
                and isinstance(batches[0], (list, tuple))):
            # streaming form: k per-step batch tuples (the device-staged
            # prefetch path).  Stack INSIDE the compiled region — one
            # dispatch instead of one eager jnp.stack per leaf, and XLA
            # folds the concatenate into the scan's per-step slicing
            # rather than materializing a second copy of the block.
            batches = jax.tree.map(lambda *ls: jnp.stack(ls), *batches)
        carry, losses = jax.lax.scan(
            lambda c, b: tick(c, epoch, b), carry, batches)
        # the final-step loss is sliced INSIDE the compiled program: an
        # eager `losses[-1]` after the call would upload a fresh gather
        # index every dispatch (a per-block H2D the sync-free loop bans —
        # tests/test_input_pipeline.py runs fit_steps under
        # transfer_guard("disallow"))
        return carry, losses, losses[-1]

    from deeplearning4j_tpu.compile import step_function
    return step_function(many, donate_argnums=(0,) if donate else (),
                         key_base=key_base, cache=cache,
                         dynamic_argnums=(2,))
