"""Fused multi-step training dispatch (shared by MultiLayerNetwork,
ComputationGraph).

The TPU-native form of the reference's `fit(DataSetIterator)` hot loop
(`MultiLayerNetwork.fit(DataSetIterator)` upstream): per-step host
dispatch costs ~3 ms/step through a remote PJRT link (measured,
bench_artifacts/PERF_ANALYSIS.md round 5), so steady-state training
scans a compiled step over a device-resident `[k, batch, ...]` block —
one host dispatch per k steps, with params/updater-state/rng/iteration
flowing step-to-step as scan carries.
"""
import numpy as np

import jax


def blocks_of(iterator, k: int):
    """Group consecutive same-shape DataSets from `iterator` into lists of
    exactly `k` (ready for one fused `fit_steps` dispatch).  Batches that
    don't fill a block — the epoch tail, or a shape change mid-stream —
    are yielded as single-element lists so the caller takes the per-step
    path instead of compiling a new scan executable for a one-off k."""
    def key(ds):
        fm = getattr(ds, "features_mask", None)
        lm = getattr(ds, "labels_mask", None)
        return (np.shape(ds.features), np.shape(ds.labels),
                None if fm is None else np.shape(fm),
                None if lm is None else np.shape(lm))

    buf, buf_key = [], None
    for ds in iterator:
        dk = key(ds)
        if buf and dk != buf_key:
            for b in buf:
                yield [b]
            buf = []
        buf.append(ds)
        buf_key = dk
        if len(buf) == k:
            yield buf
            buf = []
    for b in buf:
        yield [b]


def check_steps_axes(named_arrays):
    """Validate that every non-None array shares one leading steps axis.

    `named_arrays` is an iterable of (name, array-or-None); returns k.
    Raising here (with the offending name) beats the opaque
    'different leading axis sizes' error lax.scan gives after tracing."""
    k, ref = None, None
    for name, a in named_arrays:
        if a is None:
            continue
        if k is None:
            k, ref = a.shape[0], name
        elif a.shape[0] != k:
            raise ValueError(
                f"steps axis mismatch: '{name}' has {a.shape[0]} steps but "
                f"'{ref}' has {k} — every array needs the same leading "
                f"[k, batch, ...] steps axis")
    if k is None:
        raise ValueError("fit_steps needs at least one array input")
    return k


def make_scan_step(body):
    """Wrap a train-step `body` into a jitted k-step scan.

    `body(params, state, opt_state, *batch, rng, iteration, epoch)` must
    return `(params, state, opt_state, loss, rng, iteration + 1)` — the
    contract of `_build_step_body` in both network classes.  The returned
    function takes `batches`, a tuple whose array leaves carry a leading
    steps axis, and returns the final carry plus the per-step losses.
    """
    def many(params, state, opt_state, batches, rng, iteration, epoch):
        def tick(carry, batch):
            p, s, o, r, it = carry
            p, s, o, loss, r, it = body(p, s, o, *batch, r, it, epoch)
            return (p, s, o, r, it), loss

        (params, state, opt_state, rng, iteration), losses = \
            jax.lax.scan(tick, (params, state, opt_state, rng, iteration),
                         batches)
        return params, state, opt_state, losses, rng, iteration

    return jax.jit(many, donate_argnums=(0, 1, 2))
