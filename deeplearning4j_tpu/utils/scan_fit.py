"""Fused multi-step training dispatch (shared by MultiLayerNetwork,
ComputationGraph).

The TPU-native form of the reference's `fit(DataSetIterator)` hot loop
(`MultiLayerNetwork.fit(DataSetIterator)` upstream): per-step host
dispatch costs ~3 ms/step through a remote PJRT link (measured,
bench_artifacts/PERF_ANALYSIS.md round 5), so steady-state training
scans a compiled step over a device-resident `[k, batch, ...]` block —
one host dispatch per k steps, with params/updater-state/rng/iteration
flowing step-to-step as scan carries.
"""
import jax


def make_scan_step(body):
    """Wrap a train-step `body` into a jitted k-step scan.

    `body(params, state, opt_state, *batch, rng, iteration, epoch)` must
    return `(params, state, opt_state, loss, rng, iteration + 1)` — the
    contract of `_build_step_body` in both network classes.  The returned
    function takes `batches`, a tuple whose array leaves carry a leading
    steps axis, and returns the final carry plus the per-step losses.
    """
    def many(params, state, opt_state, batches, rng, iteration, epoch):
        def tick(carry, batch):
            p, s, o, r, it = carry
            p, s, o, loss, r, it = body(p, s, o, *batch, r, it, epoch)
            return (p, s, o, r, it), loss

        (params, state, opt_state, rng, iteration), losses = \
            jax.lax.scan(tick, (params, state, opt_state, rng, iteration),
                         batches)
        return params, state, opt_state, losses, rng, iteration

    return jax.jit(many, donate_argnums=(0, 1, 2))
