"""Global framework configuration.

TPU-native replacement for the reference's three config tiers (SURVEY.md §5.6):
`org/nd4j/config/ND4JSystemProperties.java` / `ND4JEnvironmentVars.java`
(JVM system properties + env vars) and libnd4j's `Environment` singleton
(`libnd4j/include/system/Environment.h`).  One typed config object with env
overrides; model-level config stays JSON (the NeuralNetConfiguration
equivalent, a public contract used by checkpoints).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax.numpy as jnp

_TRUTHY = {"1", "true", "yes", "on"}


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    return default if v is None else v.strip().lower() in _TRUTHY


@dataclasses.dataclass
class Config:
    """Framework-wide runtime configuration.

    Attributes mirror the reference's env toggles where a TPU equivalent
    exists: `debug`/`verbose` (libnd4j Environment::setDebug/setVerbose),
    `nan_panic` (OpExecutioner NAN_PANIC profiling mode), default dtypes
    (ND4J `Nd4j.setDefaultDataTypes`).
    """

    # Default floating dtype for parameters (reference default: float32).
    default_dtype: jnp.dtype = jnp.float32
    # Compute dtype for matmul/conv-heavy paths; bf16 feeds the MXU natively.
    compute_dtype: jnp.dtype = jnp.float32
    # NAN_PANIC / INF_PANIC equivalent: enable jax debug_nans.
    nan_panic: bool = False
    debug: bool = False
    verbose: bool = False
    # Profiling (OpProfiler equivalent -> jax profiler traces).
    profiling_enabled: bool = False
    profile_dir: str = "/tmp/dl4j_tpu_profile"

    @staticmethod
    def from_env() -> "Config":
        cfg = Config()
        cfg.nan_panic = _env_bool("DL4J_TPU_NAN_PANIC", False)
        cfg.debug = _env_bool("DL4J_TPU_DEBUG", False)
        cfg.verbose = _env_bool("DL4J_TPU_VERBOSE", False)
        cfg.profiling_enabled = _env_bool("DL4J_TPU_PROFILE", False)
        cfg.profile_dir = os.environ.get("DL4J_TPU_PROFILE_DIR", cfg.profile_dir)
        dt = os.environ.get("DL4J_TPU_DTYPE")
        if dt:
            cfg.default_dtype = jnp.dtype(dt)
        cdt = os.environ.get("DL4J_TPU_COMPUTE_DTYPE")
        if cdt:
            cfg.compute_dtype = jnp.dtype(cdt)
        if cfg.nan_panic:
            import jax

            jax.config.update("jax_debug_nans", True)
        return cfg


_CONFIG: Optional[Config] = None


def get_config() -> Config:
    global _CONFIG
    if _CONFIG is None:
        _CONFIG = Config.from_env()
    return _CONFIG


def set_config(cfg: Config) -> None:
    global _CONFIG
    _CONFIG = cfg
