from deeplearning4j_tpu.utils.config import Config, get_config, set_config  # noqa: F401
from deeplearning4j_tpu.utils.sanitize import (  # noqa: F401
    BufferValidationError, assert_disjoint, assert_live, validate_network)
