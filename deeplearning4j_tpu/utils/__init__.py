from deeplearning4j_tpu.utils.config import Config, get_config, set_config  # noqa: F401
