"""Device-resident schedule counters shared by every compiled train step.

The updaters' LR schedules consume `iteration`/`epoch` scalars inside the
jitted step; transferring fresh host ints every step costs one H2D per
counter per step through the (slow, remote) dispatch path.  Instead the
step carries a device int32 forward (`iteration + 1` is a step output) and
this helper only re-uploads when the host-side counter was changed
externally (checkpoint restore, manual reset) — detected via a sync
shadow.  Used by MultiLayerNetwork, ComputationGraph, SameDiff and
BertModel.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax.numpy as jnp


def device_counters(model):
    """Return (iteration_dev, epoch_dev) int32 scalars for `model`, cached
    against its host `iteration`/`epoch` attributes.  After the step, the
    caller assigns the step's returned counter via `advance(model, it)` —
    in the steady-state loop this function performs ZERO transfers (the
    cached device scalar flows step→step; `counter_uploads` below counts
    the fresh H2D uploads so the no-round-trip invariant is testable)."""
    if getattr(model, "_iter_dev", None) is None \
            or getattr(model, "_iter_sync", None) != model.iteration:
        model._iter_dev = jnp.asarray(model.iteration, jnp.int32)
        model._iter_sync = model.iteration
        counter_uploads.inc()
    if getattr(model, "_epoch_sync", None) != model.epoch:
        model._epoch_dev = jnp.asarray(model.epoch, jnp.int32)
        model._epoch_sync = model.epoch
        counter_uploads.inc()
    return model._iter_dev, model._epoch_dev


def advance(model, new_iter_dev, steps: int = 1) -> None:
    """Record `steps` completed steps: store the device-side counter
    returned by the compiled step and advance the host shadow in lockstep.
    Never blocks and never transfers — the returned counter is a device
    array (possibly still being computed) and the host shadow is plain int
    arithmetic, so per-iteration bookkeeping costs no device round-trip."""
    model._iter_dev = new_iter_dev
    model.iteration += steps
    model._iter_sync = model.iteration


# ---------------------------------------------------------------------------
# Host-side event counters (serving / cache instrumentation)
# ---------------------------------------------------------------------------

class StatCounter:
    """Thread-safe monotonically increasing host counter.  Unlike the
    device counters above these never touch the accelerator — they count
    host-side events (cache hits, rejected requests, dispatches) read by
    the metrics/UI layer from arbitrary threads."""

    def __init__(self, name: str = "counter"):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"StatCounter({self.name}={self.value})"


class HitMissCounters:
    """Paired hit/miss counters for a cache (serving compile cache &c.)."""

    def __init__(self, name: str = "cache"):
        self.name = name
        self.hits = StatCounter(f"{name}.hits")
        self.misses = StatCounter(f"{name}.misses")

    def hit(self) -> None:
        self.hits.inc()

    def miss(self) -> None:
        self.misses.inc()

    @property
    def hit_rate(self) -> float:
        h, m = self.hits.value, self.misses.value
        return h / (h + m) if h + m else 0.0

    def snapshot(self) -> Dict[str, float]:
        h, m = self.hits.value, self.misses.value
        return {"hits": h, "misses": m,
                "hit_rate": h / (h + m) if h + m else 0.0}

    def reset(self) -> None:
        self.hits.reset()
        self.misses.reset()


# Process-wide diagnostic: fresh H2D schedule-counter uploads.  A sync-free
# steady-state loop uploads once per model (+ once per epoch bump) and then
# stays flat — tests/test_input_pipeline.py pins this invariant.
counter_uploads = StatCounter("device_counter_uploads")
