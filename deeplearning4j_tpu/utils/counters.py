"""Device-resident schedule counters shared by every compiled train step.

The updaters' LR schedules consume `iteration`/`epoch` scalars inside the
jitted step; transferring fresh host ints every step costs one H2D per
counter per step through the (slow, remote) dispatch path.  Instead the
step carries a device int32 forward (`iteration + 1` is a step output) and
this helper only re-uploads when the host-side counter was changed
externally (checkpoint restore, manual reset) — detected via a sync
shadow.  Used by MultiLayerNetwork, ComputationGraph, SameDiff and
BertModel.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.monitor.registry import Counter, registry


def device_counters(model):
    """Return (iteration_dev, epoch_dev) int32 scalars for `model`, cached
    against its host `iteration`/`epoch` attributes.  After the step, the
    caller assigns the step's returned counter via `advance(model, it)` —
    in the steady-state loop this function performs ZERO transfers (the
    cached device scalar flows step→step; `counter_uploads` below counts
    the fresh H2D uploads so the no-round-trip invariant is testable)."""
    if getattr(model, "_iter_dev", None) is None \
            or getattr(model, "_iter_sync", None) != model.iteration:
        model._iter_dev = jnp.asarray(model.iteration, jnp.int32)
        model._iter_sync = model.iteration
        counter_uploads.inc()
    if getattr(model, "_epoch_sync", None) != model.epoch:
        model._epoch_dev = jnp.asarray(model.epoch, jnp.int32)
        model._epoch_sync = model.epoch
        counter_uploads.inc()
    return model._iter_dev, model._epoch_dev


def advance(model, new_iter_dev, steps: int = 1) -> None:
    """Record `steps` completed steps: store the device-side counter
    returned by the compiled step and advance the host shadow in lockstep.
    Never blocks and never transfers — the returned counter is a device
    array (possibly still being computed) and the host shadow is plain int
    arithmetic, so per-iteration bookkeeping costs no device round-trip."""
    model._iter_dev = new_iter_dev
    model.iteration += steps
    model._iter_sync = model.iteration


# ---------------------------------------------------------------------------
# Host-side event counters (serving / cache instrumentation)
# ---------------------------------------------------------------------------

class StatCounter(Counter):
    """Thread-safe monotonically increasing host counter.  Unlike the
    device counters above these never touch the accelerator — they count
    host-side events (cache hits, rejected requests, dispatches) read by
    the metrics/UI layer from arbitrary threads.

    Now a thin alias of `monitor.Counter`, so ad-hoc counters and
    registry-managed series share ONE implementation (and one source of
    truth: a StatCounter obtained from `monitor.registry()` IS the series
    `/metrics` exposes)."""

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"StatCounter({self.name}={self.value})"


class HitMissCounters:
    """Paired hit/miss counters for a cache (serving compile cache &c.).
    Pass pre-built counters (e.g. registry children with a `server` label)
    to make the pair a view over the shared MetricsRegistry."""

    def __init__(self, name: str = "cache", hits: Optional[Counter] = None,
                 misses: Optional[Counter] = None):
        self.name = name
        self.hits = hits if hits is not None else StatCounter(f"{name}.hits")
        self.misses = misses if misses is not None \
            else StatCounter(f"{name}.misses")

    def hit(self) -> None:
        self.hits.inc()

    def miss(self) -> None:
        self.misses.inc()

    @property
    def hit_rate(self) -> float:
        h, m = self.hits.value, self.misses.value
        return h / (h + m) if h + m else 0.0

    def snapshot(self) -> Dict[str, float]:
        h, m = self.hits.value, self.misses.value
        return {"hits": h, "misses": m,
                "hit_rate": h / (h + m) if h + m else 0.0}

    def reset(self) -> None:
        self.hits.reset()
        self.misses.reset()


# Process-wide diagnostic: fresh H2D schedule-counter uploads.  A sync-free
# steady-state loop uploads once per model (+ once per epoch bump) and then
# stays flat — tests/test_input_pipeline.py pins this invariant.  Lives in
# the shared MetricsRegistry, so the same count the invariant test reads is
# what `GET /metrics` exposes (one source of truth).
counter_uploads = registry().counter(
    "device_counter_uploads_total",
    help="fresh H2D schedule-counter uploads (sync-free loops stay flat)")
