"""Device-resident schedule counters shared by every compiled train step.

The updaters' LR schedules consume `iteration`/`epoch` scalars inside the
jitted step; transferring fresh host ints every step costs one H2D per
counter per step through the (slow, remote) dispatch path.  Instead the
step carries a device int32 forward (`iteration + 1` is a step output) and
this helper only re-uploads when the host-side counter was changed
externally (checkpoint restore, manual reset) — detected via a sync
shadow.  Used by MultiLayerNetwork, ComputationGraph, SameDiff and
BertModel.
"""
from __future__ import annotations

import jax.numpy as jnp


def device_counters(model):
    """Return (iteration_dev, epoch_dev) int32 scalars for `model`, cached
    against its host `iteration`/`epoch` attributes.  After the step, the
    caller assigns the step's returned counter via `advance(model, it)`."""
    if getattr(model, "_iter_dev", None) is None \
            or getattr(model, "_iter_sync", None) != model.iteration:
        model._iter_dev = jnp.asarray(model.iteration, jnp.int32)
        model._iter_sync = model.iteration
    if getattr(model, "_epoch_sync", None) != model.epoch:
        model._epoch_dev = jnp.asarray(model.epoch, jnp.int32)
        model._epoch_sync = model.epoch
    return model._iter_dev, model._epoch_dev


def advance(model, new_iter_dev, steps: int = 1) -> None:
    """Record `steps` completed steps: store the device-side counter
    returned by the compiled step and advance the host shadow in lockstep
    (no sync forced)."""
    model._iter_dev = new_iter_dev
    model.iteration += steps
    model._iter_sync = model.iteration
