"""Profiling / tracing / panic modes.

Reference (SURVEY.md §5.1): `OpProfiler` (per-op timing), `ProfilerConfig`
NAN_PANIC/INF_PANIC modes checking outputs after every op,
`PerformanceTracker`, libnd4j `Environment::setDebug/Verbose`.

TPU translation: per-op host timing is meaningless under whole-graph XLA
compilation — the equivalents are (a) the XLA/XProf device trace
(`trace()` -> TensorBoard), (b) `jax_debug_nans` which re-runs the failing
jitted computation op-by-op and reports the exact primitive (strictly
better than the reference's post-op scan), (c) jaxpr-level op statistics
(`op_profile`) replacing OpProfiler's op-census role, and (d) a host-side
`PerformanceTracker` for step timing/throughput.
"""
from __future__ import annotations

import contextlib
import time
from collections import Counter
from typing import Any, Callable, Dict, List, Optional

import jax


def set_nan_panic(enabled: bool = True):
    """Reference `ProfilerConfig.nanPanic`: fail loudly on NaN (jax re-runs
    the jitted fn un-jitted to localize the op)."""
    jax.config.update("jax_debug_nans", enabled)


def set_inf_panic(enabled: bool = True):
    jax.config.update("jax_debug_infs", enabled)


@contextlib.contextmanager
def trace(log_dir: str):
    """Device trace for TensorBoard/XProf (the OpProfiler timing role,
    measured on-device where the time actually goes)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def op_profile(fn: Callable, *args, **kwargs) -> Dict[str, int]:
    """Primitive census of a traced function (OpProfiler's op-count role):
    returns {primitive_name: count} from the closed jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    counts: Counter = Counter()

    def walk(jxp):
        for eqn in jxp.eqns:
            counts[eqn.primitive.name] += 1
            for v in eqn.params.values():
                if hasattr(v, "eqns"):          # sub-jaxpr
                    walk(v)
                elif hasattr(v, "jaxpr"):
                    walk(v.jaxpr)

    walk(jaxpr.jaxpr)
    return dict(counts)


class PerformanceTracker:
    """Step timing + throughput aggregation (reference
    `PerformanceTracker`/`PerformanceListener` role for ad-hoc loops)."""

    def __init__(self):
        self.steps: List[float] = []
        self._t0: Optional[float] = None

    @contextlib.contextmanager
    def step(self, result: Any = None):
        """Times one step; pass the step's output pytree so the timer
        blocks on device completion (dispatch is async)."""
        t0 = time.perf_counter()
        holder = {}

        def done(r):
            holder["r"] = r
        yield done
        if "r" in holder:
            jax.block_until_ready(holder["r"])
        self.steps.append(time.perf_counter() - t0)

    def mean_step_time(self) -> float:
        return sum(self.steps) / max(len(self.steps), 1)

    def throughput(self, items_per_step: int) -> float:
        mt = self.mean_step_time()
        return items_per_step / mt if mt else float("nan")

    def summary(self) -> str:
        n = len(self.steps)
        if not n:
            return "no steps recorded"
        return (f"{n} steps, mean {1000 * self.mean_step_time():.2f}ms, "
                f"min {1000 * min(self.steps):.2f}ms, "
                f"max {1000 * max(self.steps):.2f}ms")
