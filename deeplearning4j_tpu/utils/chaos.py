"""Deterministic fault injection for resilience testing.

Every injector here is reproducible (fires at a fixed step/batch, flips a
byte at a deterministic offset) and counts itself in the metrics registry
(``chaos_faults_injected_total{kind=...}``), so a chaos run's blast
radius is observable next to the recovery counters it should trigger.

    KillSwitch           kill-at-step-N hook for FaultTolerantTrainer
                         (SIGTERM / hard-kill / in-process exception)
    PeerKiller           kill/hang/partition/slow ONE gang rank at step N
                         (elastic-gang detection/reformation scenarios)
    corrupt_checkpoint   flip payload bytes, tear or truncate the manifest
    FlakyIterator        data producer that raises at batch K (N times)
    SlowIterator         data producer with a fixed per-batch stall
    FlakyDispatch        serving dispatch_fn that raises N times
    ReplicaChaos         kill/hang/slow/flaky ONE live fleet replica
                         (serving self-healing / failover scenarios)
    HostChaos            kill/partition/hang/slow an ENTIRE host agent
                         (cross-host federation failure domains)

None of this is imported by production code paths — tests (and operators
running game days) compose it in explicitly.
"""
from __future__ import annotations

import json
import os
import signal
import time
from typing import Optional

from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.monitor.registry import registry


class ChaosError(RuntimeError):
    """The injected failure (so tests can distinguish chaos from real
    bugs)."""


def _count(kind: str) -> None:
    registry().counter(
        "chaos_faults_injected_total",
        help="faults injected by utils.chaos, by kind",
        labels={"kind": kind}).inc()


class KillSwitch:
    """Step hook: kill the process (or raise) once `model.iteration`
    reaches `at_step`.

    `mode`:
      * ``"sigterm"`` — `os.kill(os.getpid(), SIGTERM)`: exercises the
        trainer's preemption checkpoint-and-exit path;
      * ``"kill"``    — `os._exit(9)`: a hard kill, no cleanup, no final
        checkpoint — resume must come from the last *committed* save;
      * ``"exception"`` — raise :class:`ChaosError` in-process.

    `marker` (a file path) makes the switch one-shot across relaunches:
    the first firing writes the marker, later runs see it and stay
    disarmed — the standard shape for kill-and-resume tests."""

    def __init__(self, at_step: int, mode: str = "sigterm",
                 marker: Optional[str] = None):
        if mode not in ("sigterm", "kill", "exception"):
            raise ValueError(f"unknown KillSwitch mode {mode!r}")
        self.at_step = int(at_step)
        self.mode = mode
        self.marker = marker
        self.fired = False

    def armed(self) -> bool:
        if self.fired:
            return False
        return self.marker is None or not os.path.exists(self.marker)

    def __call__(self, trainer) -> None:
        model = getattr(trainer, "model", trainer)
        if not self.armed() or int(model.iteration) < self.at_step:
            return
        self.fired = True
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(str(int(model.iteration)))
        _count(self.mode)
        if self.mode == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.mode == "kill":
            os._exit(9)
        else:
            raise ChaosError(
                f"KillSwitch fired at iteration {model.iteration}")


class PeerKiller:
    """Step hook that injects a GANG-LEVEL fault into one chosen rank.

    Runs as an `ElasticTrainer` hook on EVERY worker; only the worker
    whose elastic mesh currently holds `rank` fires (the rank is read
    live from the model's gradient sharing, so reformations that remap
    ranks are honored).  `mode`:

      * ``"kill"``      — `os._exit(9)`: the coordinator sees EOF and
        reforms with cause ``crash``;
      * ``"hang"``      — sleep `duration_s` WITHOUT heartbeating pause
        (the HB thread keeps running): the peer stays live but ships no
        data, so the coordinator reforms with cause ``straggler``;
      * ``"partition"`` — pause the mesh's heartbeat thread and sleep
        `duration_s`: full silence on a healthy socket, the coordinator
        reforms with cause ``partition`` and the victim — if it wakes —
        finds itself evicted (:class:`GangEvictedError`);
      * ``"slow"``      — sleep `delay_s` once (bounded, below the
        failure deadline): NO reformation may occur — the
        detection-threshold negative control.

    `marker` (file path) makes it one-shot across relaunches, exactly
    like :class:`KillSwitch` — a relaunched replacement of the killed
    rank must not re-fire."""

    def __init__(self, rank: int, at_step: int, mode: str = "kill",
                 duration_s: float = 5.0, delay_s: float = 0.2,
                 marker: Optional[str] = None):
        if mode not in ("kill", "hang", "partition", "slow"):
            raise ValueError(f"unknown PeerKiller mode {mode!r}")
        self.rank = int(rank)
        self.at_step = int(at_step)
        self.mode = mode
        self.duration_s = float(duration_s)
        self.delay_s = float(delay_s)
        self.marker = marker
        self.fired = False

    def armed(self) -> bool:
        if self.fired:
            return False
        return self.marker is None or not os.path.exists(self.marker)

    @staticmethod
    def _mesh_of(trainer):
        model = getattr(trainer, "model", trainer)
        sharing = getattr(model, "_grad_sharing", None)
        return getattr(sharing, "mesh", None) if sharing is not None \
            else None

    def __call__(self, trainer) -> None:
        model = getattr(trainer, "model", trainer)
        mesh = self._mesh_of(trainer)
        rank = mesh.rank if mesh is not None else 0
        if not self.armed() or rank != self.rank \
                or int(model.iteration) < self.at_step:
            return
        self.fired = True
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(str(int(model.iteration)))
        _count(f"peer-{self.mode}")
        if self.mode == "kill":
            os._exit(9)
        elif self.mode == "hang":
            time.sleep(self.duration_s)
        elif self.mode == "partition":
            if mesh is not None and hasattr(mesh, "pause_heartbeats"):
                mesh.pause_heartbeats(True)
            time.sleep(self.duration_s)
            if mesh is not None and hasattr(mesh, "pause_heartbeats"):
                mesh.pause_heartbeats(False)
        else:                       # "slow": bounded, below the deadline
            time.sleep(self.delay_s)


def corrupt_checkpoint(directory: str, what: str = "payload") -> str:
    """Deterministically damage a committed checkpoint directory.

    `what`:
      * ``"payload"``       — flip one byte in the middle of the first
        ``shards-*.npz`` (caught by the per-chunk crc32 on restore);
      * ``"manifest"``      — overwrite ``manifest.json`` with truncated
        (torn-write) JSON;
      * ``"uncommit"``      — delete the manifest, turning the checkpoint
        back into an uncommitted torn directory.

    Returns the path of the file damaged."""
    if what == "uncommit":
        target = os.path.join(directory, "manifest.json")
        os.remove(target)
        _count("uncommit")
        return target
    if what == "manifest":
        target = os.path.join(directory, "manifest.json")
        with open(target) as f:
            text = f.read()
        with open(target, "w") as f:
            f.write(text[: max(1, len(text) // 2)])
        _count("manifest")
        return target
    if what != "payload":
        raise ValueError(f"unknown corruption kind {what!r}")
    shards = sorted(n for n in os.listdir(directory)
                    if n.startswith("shards-") and n.endswith(".npz"))
    if not shards:
        raise FileNotFoundError(f"{directory}: no shards-*.npz to corrupt")
    target = os.path.join(directory, shards[0])
    with open(target, "rb") as f:
        data = bytearray(f.read())
    data[len(data) // 2] ^= 0xFF
    with open(target, "wb") as f:
        f.write(bytes(data))
    _count("payload")
    return target


class FlakyIterator(DataSetIterator):
    """Wraps a DataSetIterator; raises `exc_type` when batch `fail_at`
    would be produced, `times` times total (across epochs/resets), then
    behaves normally — the transient-producer-failure shape the
    pipeline's `retries=` recovers from."""

    def __init__(self, underlying: DataSetIterator, fail_at: int = 0,
                 times: int = 1, exc_type=ChaosError):
        self.underlying = underlying
        self.fail_at = int(fail_at)
        self.failures_left = int(times)
        self.exc_type = exc_type

    def __iter__(self):
        for i, ds in enumerate(self.underlying):
            if i == self.fail_at and self.failures_left > 0:
                self.failures_left -= 1
                _count("producer")
                raise self.exc_type(
                    f"injected producer failure at batch {i}")
            yield ds

    def reset(self):
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def __len__(self):
        return len(self.underlying)


class SlowIterator(DataSetIterator):
    """Wraps a DataSetIterator with a fixed `delay_s` sleep per batch —
    for backpressure / stuck-pipeline readiness scenarios."""

    def __init__(self, underlying: DataSetIterator, delay_s: float = 0.05):
        self.underlying = underlying
        self.delay_s = float(delay_s)

    def __iter__(self):
        for ds in self.underlying:
            time.sleep(self.delay_s)
            yield ds

    def reset(self):
        self.underlying.reset()

    def batch_size(self) -> int:
        return self.underlying.batch_size()

    def __len__(self):
        return len(self.underlying)


class FlakyDispatch:
    """Wraps a serving `dispatch_fn` (or any callable): raises `exc_type`
    for the first `times` calls, then delegates — the transient dispatch
    error `ModelServer._dispatch`'s retry absorbs."""

    def __init__(self, fn, times: int = 1, exc_type=ChaosError):
        self.fn = fn
        self.failures_left = int(times)
        self.exc_type = exc_type
        self.calls = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.failures_left > 0:
            self.failures_left -= 1
            _count("dispatch")
            raise self.exc_type("injected dispatch failure")
        return self.fn(*args, **kwargs)


class ReplicaChaos:
    """Injects a REPLICA-LEVEL fault into one live fleet replica, the
    serving mirror of :class:`PeerKiller`.  `arm(replica)` wraps the
    replica server's compiled-run entry point (`server.cache.run`) so
    the fault fires inside the dispatch path, exactly where a real
    device failure surfaces.  `mode`:

      * ``"kill"``  — from dispatch `at_dispatch` onward EVERY run
        raises :class:`serving.resilience.ReplicaKilledError` (a dead
        device stays dead): the request fails over, the replica is
        poisoned, and the controller respawns it — the respawned
        replica gets a fresh server + cache, so the wrap does not
        survive the heal;
      * ``"hang"``  — dispatch `at_dispatch` sleeps `duration_s`
        INSIDE the run (the batcher worker is stuck; `inflight_age_s`
        grows): hedges cover the stuck requests, the controller
        declares the replica hung and respawns it;
      * ``"slow"``  — every dispatch sleeps `delay_s` (bounded, below
        any failure deadline): the hedge-latency negative control — no
        respawn may occur;
      * ``"flaky"`` — raise :class:`ChaosError` for `times` dispatches
        starting at `at_dispatch`, then behave: the breaker opens and
        a half-open probe re-admits the replica, no respawn.

    `marker` (file path) makes the injector one-shot across re-arms,
    exactly like :class:`PeerKiller`.  `restore()` unwraps."""

    def __init__(self, mode: str = "kill", at_dispatch: int = 0,
                 duration_s: float = 2.0, delay_s: float = 0.05,
                 times: int = 3, marker: Optional[str] = None):
        if mode not in ("kill", "hang", "slow", "flaky"):
            raise ValueError(f"unknown ReplicaChaos mode {mode!r}")
        self.mode = mode
        self.at_dispatch = int(at_dispatch)
        self.duration_s = float(duration_s)
        self.delay_s = float(delay_s)
        self.times = int(times)
        self.marker = marker
        self.fired = False
        self.calls = 0
        self._cache = None
        self._orig = None
        self._hung = False
        self._flaked = 0

    def armed(self) -> bool:
        if self.fired and self.mode in ("kill", "hang"):
            return False
        return self.marker is None or not os.path.exists(self.marker)

    def arm(self, replica):
        """Wrap one live replica's compiled-run entry point.  Accepts a
        fleet `Replica` (or anything with `.server.cache.run`)."""
        if self._cache is not None:
            raise RuntimeError("ReplicaChaos is already armed")
        self._cache = replica.server.cache
        self._orig = self._cache.run
        self._cache.run = self._run
        return replica

    def restore(self) -> None:
        if self._cache is not None and self._orig is not None:
            self._cache.run = self._orig
        self._cache = self._orig = None

    def _fire(self) -> None:
        self.fired = True
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(f"{self.mode}@{self.calls}")
        _count(f"replica-{self.mode}")

    def _run(self, *args, **kwargs):
        self.calls += 1
        armed = self.armed()
        if self.mode == "kill":
            if self.fired or (armed and self.calls > self.at_dispatch):
                if not self.fired:
                    self._fire()
                # lazy import: chaos must not drag serving into every
                # training-side test that imports utils.chaos
                from deeplearning4j_tpu.serving.resilience import \
                    ReplicaKilledError
                raise ReplicaKilledError(
                    f"injected replica kill at dispatch {self.calls}")
        elif self.mode == "hang":
            if armed and self.calls > self.at_dispatch:
                self._fire()
                time.sleep(self.duration_s)
        elif self.mode == "slow":
            if armed and self.calls > self.at_dispatch:
                if not self.fired:
                    self._fire()
                time.sleep(self.delay_s)
        else:                       # "flaky"
            if armed and self.calls > self.at_dispatch \
                    and self._flaked < self.times:
                if not self.fired:
                    self._fire()
                self._flaked += 1
                raise ChaosError(
                    f"injected flaky dispatch {self._flaked}/{self.times}")
        return self._orig(*args, **kwargs)


class HostChaos:
    """Injects a HOST-LEVEL fault into one live federation `HostAgent` —
    a whole failure domain at once, where :class:`ReplicaChaos` takes
    out a single replica.  `arm(agent)` wraps the agent's dispatch
    handler so the fault fires at dispatch `at_dispatch` (or call
    `fire(agent)` to trigger it manually).  `mode`:

      * ``"kill"``      — the agent drops its connection without a
        goodbye (`agent.crash()`); the router sees EOF and evicts the
        host with cause ``crash``.  `os_kill=True` hard-kills the whole
        worker process (`os._exit(9)`) instead — the multi-process
        form;
      * ``"partition"`` — both directions go silent for `duration_s`
        (`agent.partition`): the router evicts on the heartbeat
        deadline (cause ``partition``), and the replies the host flushes
        on heal arrive stale — the router fences and counts every one;
      * ``"hang"``      — heartbeats keep flowing but dispatch replies
        are withheld for `duration_s` (`agent.hang`): only the router's
        straggler detector can see this (cause ``straggler``);
      * ``"slow"``      — every dispatch is delayed `delay_s` (bounded,
        below every failure deadline): the negative control — no
        eviction may occur.

    `marker` (file path) makes the injector one-shot across re-arms and
    process relaunches, exactly like :class:`PeerKiller`.  `restore()`
    unwraps and clears the slow-mode delay."""

    def __init__(self, mode: str = "kill", at_dispatch: int = 0,
                 duration_s: float = 2.0, delay_s: float = 0.05,
                 marker: Optional[str] = None, os_kill: bool = False):
        if mode not in ("kill", "partition", "hang", "slow"):
            raise ValueError(f"unknown HostChaos mode {mode!r}")
        self.mode = mode
        self.at_dispatch = int(at_dispatch)
        self.duration_s = float(duration_s)
        self.delay_s = float(delay_s)
        self.marker = marker
        self.os_kill = bool(os_kill)
        self.fired = False
        self.calls = 0
        self._agent = None
        self._orig = None

    def armed(self) -> bool:
        if self.fired:
            return False
        return self.marker is None or not os.path.exists(self.marker)

    def arm(self, agent):
        """Wrap one live HostAgent's dispatch handler."""
        if self._agent is not None:
            raise RuntimeError("HostChaos is already armed")
        self._agent = agent
        self._orig = agent._on_request
        agent._on_request = self._on_request
        return agent

    def restore(self) -> None:
        if self._agent is not None and self._orig is not None:
            self._agent._on_request = self._orig
            if self.mode == "slow":
                self._agent.slow(0.0)
        self._agent = self._orig = None

    def fire(self, agent=None) -> None:
        """Trigger the fault on `agent` (default: the armed one) now."""
        agent = agent if agent is not None else self._agent
        if agent is None:
            raise RuntimeError("HostChaos: no agent to fire on")
        self.fired = True
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(f"{self.mode}@{self.calls}")
        _count(f"host_{self.mode}")
        if self.mode == "kill":
            if self.os_kill:
                os._exit(9)
            agent.crash()
        elif self.mode == "partition":
            agent.partition(True)
            import threading
            t = threading.Timer(self.duration_s, agent.partition, [False])
            t.daemon = True
            t.start()
        elif self.mode == "hang":
            agent.hang(self.duration_s)
        else:                       # "slow"
            agent.slow(self.delay_s)

    def _on_request(self, gen, msg, raw):
        self.calls += 1
        if self.armed() and self.calls > self.at_dispatch:
            self.fire()
            if self.mode == "kill":     # a dead host serves nothing
                return None
        return self._orig(gen, msg, raw)


class HandoffChaos:
    """Injects ONE fault into a pod-arbiter slice handoff, at the exact
    point that makes the journal-recovery claim falsifiable.  Three
    targets, one shot each (`marker`-gated across relaunches, like
    :class:`PeerKiller`):

      * ``target="arbiter"`` — hook this object as ``arbiter.chaos``;
        the arbiter calls :meth:`on_journal` RIGHT AFTER each journal
        commit, so ``at_phase="shrink"`` kills the arbiter process
        (``mode="kill"`` → `os._exit(9)`) with the phase-1 intent
        durable but zero side effects executed — the canonical
        between-phases crash a relaunch must replay;
      * ``target="gang"`` — pass as an `ElasticTrainer` hook (or call
        :meth:`step_hook` directly from a step loop); it fires
        (kill/hang) the first step a ``shrink-request.json`` naming this
        worker's LIVE rank sits in `control_dir` — the gang rank dying
        mid-shrink-window, which must compose with the coordinator's
        ``GangReformed`` eviction;
      * ``target="replica"`` — ``arm(replica)`` wraps the replica's
        compiled-run entry point to sleep `duration_s` on every call
        (a replica hung mid-drain: `release_slice`'s drain deadline
        must expire and release the slice anyway).

    Counts ``chaos_faults_injected_total{kind="handoff-<target>-<mode>"}``.
    """

    def __init__(self, target: str = "arbiter", mode: str = "kill",
                 at_phase: str = "shrink", direction: Optional[str] = None,
                 rank: Optional[int] = None, duration_s: float = 30.0,
                 control_dir: Optional[str] = None,
                 marker: Optional[str] = None):
        if target not in ("arbiter", "gang", "replica"):
            raise ValueError(f"unknown HandoffChaos target {target!r}")
        if mode not in ("kill", "hang"):
            raise ValueError(f"unknown HandoffChaos mode {mode!r}")
        self.target = target
        self.mode = mode
        self.at_phase = at_phase
        self.direction = direction
        self.rank = rank
        self.duration_s = float(duration_s)
        self.control_dir = control_dir
        self.marker = marker
        self.fired = False
        self._orig = None
        self._cache = None

    def armed(self) -> bool:
        if self.fired:
            return False
        return self.marker is None or not os.path.exists(self.marker)

    def _fire(self) -> None:
        self.fired = True
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(f"{self.target}-{self.mode}@{self.at_phase}")
        _count(f"handoff-{self.target}-{self.mode}")
        if self.mode == "kill":
            os._exit(9)
        time.sleep(self.duration_s)

    # ---- target="arbiter": SliceArbiter.chaos hook ----
    def on_journal(self, direction: str, phase: str) -> None:
        """Called by the arbiter immediately after each journal commit
        (the record for `phase` is durable, its effects are not)."""
        if self.target != "arbiter" or not self.armed():
            return
        if phase != self.at_phase:
            return
        if self.direction is not None and direction != self.direction:
            return
        self._fire()

    # ---- target="gang": victim-rank step hook ----
    def __call__(self, trainer) -> None:
        """`ElasticTrainer` hook form of :meth:`step_hook`: reads the
        live gang rank off the trainer (reformations remap ranks) and
        the control dir from `control_dir` or the trainer itself."""
        mesh = PeerKiller._mesh_of(trainer)
        rank = mesh.rank if mesh is not None else 0
        control_dir = self.control_dir \
            if self.control_dir is not None \
            else getattr(trainer, "control_dir", None)
        if control_dir is not None:
            self.step_hook(control_dir, rank)

    def step_hook(self, control_dir: str, rank: int) -> None:
        """Call once per training step on every worker; fires on the
        worker whose rank a pending shrink request names."""
        if self.target != "gang" or not self.armed():
            return
        want = self.rank if self.rank is not None else rank
        if rank != want:
            return
        path = os.path.join(control_dir, "shrink-request.json")
        try:
            with open(path) as f:
                req = json.load(f)
        except (OSError, ValueError):
            return
        if int(req.get("rank", -1)) != rank:
            return
        self._fire()

    # ---- target="replica": hang the compiled-run entry point ----
    def arm(self, replica):
        """Wrap `replica.server.cache.run` to hang every dispatch — a
        replica that will never finish draining."""
        if self.target != "replica":
            raise ValueError("arm() is for target='replica'")
        if self._cache is not None:
            raise RuntimeError("HandoffChaos is already armed")
        self._cache = replica.server.cache
        self._orig = self._cache.run
        self._cache.run = self._run
        return replica

    def restore(self) -> None:
        if self._cache is not None and self._orig is not None:
            self._cache.run = self._orig
        self._cache = self._orig = None

    def _run(self, *args, **kwargs):
        if self.armed():
            self._fire()
        elif self.mode == "hang" and self.fired:
            time.sleep(self.duration_s)     # keep hanging: every dispatch
        return self._orig(*args, **kwargs)
