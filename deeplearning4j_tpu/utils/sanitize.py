"""Buffer-lifetime sanitizers — the TPU equivalent of the reference's
workspace-misuse validation (SURVEY.md §5.2: `LayerWorkspaceMgr` asserts
arrays come from the expected workspace; `NotReleasedWorkspaceException`).

Under XLA the corresponding failure class is *donation misuse*: every
compiled train step donates its params/state/opt-state buffers
(`donate_argnums`), so any alias of those arrays held elsewhere — a second
network sharing transplanted params, a stored "best model" snapshot, a
listener keeping a reference — becomes a deleted buffer after the next
`fit()`.  jax's own error ("Array has been deleted") carries no context
about *which* model/leaf was hit or why.  These helpers give the named,
early error the reference's workspace validation gave.

Used by transfer learning and early stopping (the two donation-aliasing
bug sites fixed in round 2, ADVICE.md r1) and available as a public guard.
"""
from __future__ import annotations

from typing import Any, Iterable, Tuple

import jax


class BufferValidationError(RuntimeError):
    """Raised when a pytree holds deleted (donated-away) or cross-shared
    device buffers (reference analogue: NotReleasedWorkspaceException)."""


def _leaves_with_paths(tree: Any) -> Iterable[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def assert_live(tree: Any, context: str = "pytree") -> None:
    """Raise BufferValidationError naming every deleted leaf in `tree`.

    A leaf is deleted when a jitted step donated its buffer (XLA reused the
    HBM) while this reference survived — the use-after-donation race the
    reference guards against with workspace validation.
    """
    dead = [p for p, leaf in _leaves_with_paths(tree)
            if isinstance(leaf, jax.Array) and leaf.is_deleted()]
    if dead:
        raise BufferValidationError(
            f"{context}: {len(dead)} leaf buffer(s) were donated to a "
            f"compiled step and deleted: {dead[:5]}"
            f"{' …' if len(dead) > 5 else ''}. Copy leaves before sharing "
            "them across networks (jax.tree_util.tree_map(jnp.copy, ...)) "
            "or re-load from a checkpoint.")


def _buffer_ids(tree: Any) -> dict:
    out = {}
    for p, leaf in _leaves_with_paths(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_deleted():
            try:
                out[leaf.unsafe_buffer_pointer()] = p
            except Exception:   # sharded/committed arrays: fall back to id
                out[id(leaf)] = p
    return out


def assert_disjoint(tree_a: Any, tree_b: Any,
                    context: str = "trees") -> None:
    """Raise if two pytrees share any device buffer.

    Donation makes silent sharing fatal: when one network's step donates a
    buffer the other network still references, the second network dies on
    its next use.  Transfer learning / model-saver code paths must deep-copy
    (the ADVICE.md round-1 bug class); this guard catches regressions.
    """
    ids_a = _buffer_ids(tree_a)
    shared = [(pa, ids_a[ptr]) for ptr, pa in _buffer_ids(tree_b).items()
              if ptr in ids_a]
    if shared:
        pairs = ", ".join(f"{b}≡{a}" for b, a in shared[:5])
        raise BufferValidationError(
            f"{context}: {len(shared)} device buffer(s) shared between the "
            f"two trees ({pairs}{' …' if len(shared) > 5 else ''}); a "
            "donating train step on either side will delete the other's "
            "params. Deep-copy on transplant.")


def validate_network(net: Any, context: str = None) -> None:
    """Check a MultiLayerNetwork / ComputationGraph / SameDiff-like object's
    device state (params_, state_, opt_state_ / variables_) for deleted
    buffers."""
    name = context or type(net).__name__
    for attr in ("params_", "state_", "opt_state_", "variables_"):
        tree = getattr(net, attr, None)
        if tree is not None:
            assert_live(tree, f"{name}.{attr}")
