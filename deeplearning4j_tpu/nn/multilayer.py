"""MultiLayerNetwork: sequential-stack model with a compiled train step.

Reference: `deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java` (~4k
LoC) plus the config DSL `NeuralNetConfiguration.Builder` ->
`MultiLayerConfiguration` (`nn/conf/**`) and the optimize loop
`Solver`/`StochasticGradientDescent`/`BaseOptimizer`
(`optimize/solvers/**`).

Architectural inversion (SURVEY.md §7): the reference runs layer-by-layer
`activate()`/`backpropGradient()` with hand-choreographed workspaces and an
in-place flattened `gradientView`; here `fit()` traces ONE pure function
(forward + loss + `jax.grad` + updater) and `jax.jit` compiles it, donating
params/updater-state buffers so XLA reuses HBM in place.  Parameter-averaging
/ gradient-sharing DP becomes a sharding annotation on the same step
(see parallel/).
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.monitor.instrument import TrainingInstruments
from deeplearning4j_tpu.monitor.spans import span
from deeplearning4j_tpu.nn.core import InputType, Layer, PyTree
from deeplearning4j_tpu.train.updaters import (
    IUpdater, Sgd, apply_gradient_normalization)

Params = Dict[str, PyTree]


def _masked_leaves(params, mask):
    """Yield param leaves where the layer's regularizable_mask is True
    (mask may mark whole subtrees)."""
    if isinstance(mask, dict):
        for k, m in mask.items():
            yield from _masked_leaves(params[k], m)
    elif mask:
        yield from jax.tree_util.tree_leaves(params)


def _add_scaled_where(upd, params, mask, scale):
    """upd += scale * params wherever mask is True (decoupled weight decay)."""
    if isinstance(mask, dict):
        return {k: _add_scaled_where(upd[k], params[k], mask[k], scale)
                for k in upd}
    if mask:
        return jax.tree_util.tree_map(lambda u, p: u + scale * p, upd, params)
    return upd


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MultiLayerConfiguration:
    """Sequential config (reference `MultiLayerConfiguration`): ordered layer
    configs + global defaults. JSON round-trip is a public contract
    (checkpoints embed it, `MultiLayerConfiguration.toJson/fromJson`)."""

    layers: List[Layer]
    input_type: InputType
    seed: int = 0
    updater: IUpdater = dataclasses.field(default_factory=lambda: Sgd(1e-2))
    weight_init: str = "XAVIER"
    activation: Any = "identity"
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dtype: str = "float32"
    # bf16 compute path: master params/updater state stay `dtype` (f32);
    # activations + layer params are cast to compute_dtype inside the
    # forward, losses/BN-statistics compute in f32 (the TPU mixed-precision
    # recipe — MXU runs bf16, accumulation stays f32)
    compute_dtype: Optional[str] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    # gradient checkpointing (jax.checkpoint per layer): trades ~1 extra
    # forward of FLOPs for O(sqrt)-ish activation memory — the HBM lever
    # for deep models; a capability-exceeding TPU addition (the reference
    # has no rematerialization story)
    remat: bool = False

    def layer_name(self, i: int) -> str:
        return self.layers[i].name or f"layer_{i}"

    def to_json(self) -> str:
        return json.dumps({
            "format": "deeplearning4j_tpu.MultiLayerConfiguration.v1",
            "layers": [l.to_json() for l in self.layers],
            "input_type": self.input_type.to_json(),
            "seed": self.seed,
            "updater": self.updater.to_json(),
            "weight_init": self.weight_init,
            "activation": self.activation if isinstance(self.activation, str)
                          else getattr(self.activation, "__name__", "identity"),
            "l1": self.l1, "l2": self.l2, "weight_decay": self.weight_decay,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
            "remat": self.remat,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        return MultiLayerConfiguration(
            layers=[Layer.from_json(l) for l in d["layers"]],
            input_type=InputType.from_json(d["input_type"]),
            seed=d["seed"],
            updater=IUpdater.from_json(d["updater"]),
            weight_init=d["weight_init"],
            activation=d["activation"],
            l1=d["l1"], l2=d["l2"], weight_decay=d.get("weight_decay", 0.0),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
            remat=d.get("remat", False),
        )


class NeuralNetConfiguration:
    """Fluent builder mirroring `NeuralNetConfiguration.Builder` ->
    `.list()` -> `.build()`."""

    class Builder:
        def __init__(self):
            self._seed = 0
            self._updater: IUpdater = Sgd(1e-2)
            self._weight_init = "XAVIER"
            self._activation: Any = "identity"
            self._l1 = 0.0
            self._l2 = 0.0
            self._weight_decay = 0.0
            self._dtype = "float32"
            self._compute_dtype = None
            self._grad_norm = None
            self._grad_norm_threshold = 1.0
            self._input_type: Optional[InputType] = None
            self._remat = False

        def seed(self, s: int):
            self._seed = int(s); return self

        def updater(self, u: IUpdater):
            self._updater = u; return self

        def weight_init(self, w: str):
            self._weight_init = w; return self

        def activation(self, a):
            self._activation = a; return self

        def l1(self, v: float):
            self._l1 = float(v); return self

        def l2(self, v: float):
            self._l2 = float(v); return self

        def weight_decay(self, v: float):
            self._weight_decay = float(v); return self

        def dtype(self, dt: str):
            self._dtype = dt; return self

        def compute_dtype(self, dt: str):
            self._compute_dtype = dt; return self

        def gradient_normalization(self, mode: str, threshold: float = 1.0):
            self._grad_norm = mode; self._grad_norm_threshold = threshold; return self

        def gradient_checkpointing(self, on: bool = True):
            """Rematerialize each layer's activations in the backward pass
            (jax.checkpoint) — HBM for FLOPs on deep models."""
            self._remat = bool(on); return self

        def set_input_type(self, it: InputType):
            self._input_type = it; return self

        def list(self, layers: Sequence[Layer]) -> "NeuralNetConfiguration.ListBuilder":
            return NeuralNetConfiguration.ListBuilder(self, list(layers))

    class ListBuilder:
        def __init__(self, parent: "NeuralNetConfiguration.Builder", layers: List[Layer]):
            self.parent = parent
            self.layers = layers

        def set_input_type(self, it: InputType):
            self.parent._input_type = it; return self

        def build(self) -> MultiLayerConfiguration:
            p = self.parent
            if p._input_type is None:
                raise ValueError("set_input_type(...) is required (shape inference)")
            return MultiLayerConfiguration(
                layers=self.layers, input_type=p._input_type, seed=p._seed,
                updater=p._updater, weight_init=p._weight_init,
                activation=p._activation, l1=p._l1, l2=p._l2,
                weight_decay=p._weight_decay, dtype=p._dtype,
                compute_dtype=p._compute_dtype,
                gradient_normalization=p._grad_norm,
                gradient_normalization_threshold=p._grad_norm_threshold,
                remat=p._remat,
            )

    @staticmethod
    def builder() -> "NeuralNetConfiguration.Builder":
        return NeuralNetConfiguration.Builder()


# ---------------------------------------------------------------------------
# Network
# ---------------------------------------------------------------------------

class MultiLayerNetwork:
    """Sequential network (reference `MultiLayerNetwork`).

    Public surface parity: `init`, `fit(x, y | iterator)`, `output`,
    `feed_forward`, `score`, `evaluate`, `params`/`set_params` (flat-buffer
    view semantics at the API/checkpoint boundary only), `gradient_for`
    (gradient-check hook), `save`/`load` via utils.serialization.
    """

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params_: Optional[Params] = None
        self.state_: Optional[Params] = None      # BN running stats etc.
        self.opt_state_: Optional[PyTree] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._rng = jax.random.PRNGKey(conf.seed)
        self._train_step = None
        self._scan_step = None
        self._grad_step = None    # hierarchical-sharing split: grad half
        self._apply_step = None   # hierarchical-sharing split: apply half
        self._grad_sharing = None  # parallel.hierarchical.HierarchicalAllReduce
        self._output_fn = None
        self._step_transform = None   # ZeRO-1 weight update (parallel/zero)
        self._layer_types: List[InputType] = []
        self._device_norm = None   # on-device normalizer prologue (pipeline)
        self._instr: Optional[TrainingInstruments] = None
        self._exec_cache_override = None  # compile.PersistentExecutableCache
        self._schedule = None             # compile.Schedule (autotuner)

    def _instruments(self) -> TrainingInstruments:
        """Lazy telemetry handles (monitor registry series labeled by
        model kind) — created on first dispatch, shared series thereafter."""
        if self._instr is None:
            self._instr = TrainingInstruments(type(self).__name__)
        return self._instr

    # ---- init ----
    def init(self) -> "MultiLayerNetwork":
        dtype = jnp.dtype(self.conf.dtype)
        it = self.conf.input_type
        params: Params = {}
        state: Params = {}
        key = jax.random.PRNGKey(self.conf.seed)
        self._layer_types = [it]
        for i, layer in enumerate(self.conf.layers):
            key, sub = jax.random.split(key)
            if layer.weight_init is None:
                layer.weight_init = self.conf.weight_init
            if layer.activation is None and not hasattr(layer, "loss"):
                layer.activation = self.conf.activation
            p, s, it = layer.initialize(sub, it, dtype)
            params[self.conf.layer_name(i)] = p
            state[self.conf.layer_name(i)] = s
            self._layer_types.append(it)
        self.params_ = params
        self.state_ = state
        self.opt_state_ = self._init_opt_state(params)
        return self

    def _updater_for(self, i: int) -> IUpdater:
        layer = self.conf.layers[i]
        return layer.updater if layer.updater is not None else self.conf.updater

    def _init_opt_state(self, params: Params) -> PyTree:
        return {
            self.conf.layer_name(i): self._updater_for(i).init_state(
                params[self.conf.layer_name(i)])
            for i in range(len(self.conf.layers))
        }

    # ---- forward ----
    def _cast_compute(self, params: Params, x):
        """Mixed precision: cast activations + params to compute_dtype;
        gradients flow back through the casts to f32 master params."""
        cd = self.conf.compute_dtype
        if cd is None:
            return params, x
        dt = jnp.dtype(cd)
        cast = lambda a: a.astype(dt) if jnp.issubdtype(a.dtype,
                                                        jnp.floating) else a
        return (jax.tree_util.tree_map(cast, params),
                x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x)

    def _forward(self, params: Params, state: Params, x, *, train: bool,
                 rng: Optional[jax.Array], mask=None,
                 upto: Optional[int] = None) -> Tuple[jnp.ndarray, Params]:
        params, x = self._cast_compute(params, x)
        new_state = dict(state)
        n = len(self.conf.layers) if upto is None else upto
        for i in range(n):
            layer = self.conf.layers[i]
            name = self.conf.layer_name(i)
            lrng = None
            if rng is not None and layer.STOCHASTIC:
                rng, lrng = jax.random.split(rng)
            if self.conf.remat and train:
                # train only: inference is never differentiated, and
                # jax.checkpoint's CSE barrier would just slow it down
                def _apply(p_, s_, x_, r_, m_, _layer=layer, _train=train):
                    return _layer.apply(p_, s_, x_, train=_train, rng=r_,
                                        mask=m_)
                x, s = jax.checkpoint(_apply)(params[name], state[name], x,
                                              lrng, mask)
            else:
                x, s = layer.apply(params[name], state[name], x, train=train,
                                   rng=lrng, mask=mask)
            new_state[name] = s
            if mask is not None and self._layer_types:
                # Mask propagation (the reference's feedForwardMaskArray):
                # once a layer leaves sequence space or changes the sequence
                # length, the [B,T] mask no longer applies downstream.
                t_in, t_out = self._layer_types[i], self._layer_types[i + 1]
                # None (dynamic T) vs a fixed length counts as a change:
                # e.g. LearnedSelfAttention emits n_queries steps regardless
                # of input length, so the [B,T] mask is stale either way.
                if (t_out.kind != "recurrent"
                        or (t_in.kind == "recurrent"
                            and t_in.shape[0] != t_out.shape[0])):
                    mask = None
        return x, new_state

    def _loss(self, params: Params, state: Params, x, y, rng,
              features_mask=None, labels_mask=None, train: bool = True
              ) -> Tuple[jnp.ndarray, Params]:
        """Score = data loss (+ l1/l2 penalties, matching the reference's
        `calcRegularizationScore` contribution to `score()`).

        features_mask feeds the forward pass (sequence padding masks for
        pooling/rnn layers); labels_mask feeds the loss reduction — the same
        split the reference makes in `MultiLayerNetwork.setLayerMaskArrays`.
        """
        out_idx = len(self.conf.layers) - 1
        head = self.conf.layers[out_idx]
        if not hasattr(head, "compute_loss"):
            raise ValueError("Last layer must be an OutputLayer/LossLayer")
        h, new_state = self._forward(params, state, x, train=train, rng=rng,
                                     mask=features_mask, upto=out_idx)
        name = self.conf.layer_name(out_idx)
        hrng = None if rng is None else jax.random.fold_in(rng, out_idx)
        hp, h = self._cast_compute(params[name], h)  # head matmul bf16 too
        loss = head.compute_loss(hp, state[name], h, y, train=train,
                                 rng=hrng, mask=labels_mask)
        loss = loss + self._reg_penalty(params)
        return loss, new_state

    def _reg_penalty(self, params: Params):
        penalty = 0.0
        for i, layer in enumerate(self.conf.layers):
            name = self.conf.layer_name(i)
            l1 = layer.l1 if layer.l1 is not None else self.conf.l1
            l2 = layer.l2 if layer.l2 is not None else self.conf.l2
            if l1 == 0.0 and l2 == 0.0:
                continue
            rmask = layer.regularizable_mask(params[name])
            for w in _masked_leaves(params[name], rmask):
                if l1:
                    penalty = penalty + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    # reference L2Regularization: 0.5 * coeff * ||w||^2
                    penalty = penalty + 0.5 * l2 * jnp.sum(w * w)
        return penalty

    # ---- compiled step ----
    def _exec_cache(self):
        """The persistent executable cache in play: the per-model override
        (`set_executable_cache`), else the process default — None keeps
        the plain jax.jit path."""
        if self._exec_cache_override is not None:
            return self._exec_cache_override
        from deeplearning4j_tpu.compile import default_cache
        return default_cache()

    def set_executable_cache(self, cache) -> "MultiLayerNetwork":
        """Route this model's train-step compilation through a
        `compile.PersistentExecutableCache` (or a directory path), so a
        restarted process deserializes the step instead of recompiling it.
        None reverts to the process default ($DL4J_TPU_EXEC_CACHE /
        `compile.set_default_cache`).  Triggers a step rebuild."""
        if isinstance(cache, str):
            from deeplearning4j_tpu.compile import PersistentExecutableCache
            cache = PersistentExecutableCache(cache)
        self._exec_cache_override = cache
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        return self

    def apply_schedule(self, schedule) -> "MultiLayerNetwork":
        """Install an autotuned `compile.Schedule`: the iterator form of
        `fit()` defaults its `fused_steps` to the schedule's and the step
        builders honor `schedule.donation`.  (`zero1` is a wrapper-level
        knob — `parallel.ParallelWrapper.apply_schedule` handles it and
        delegates the rest here.)  Triggers a step rebuild."""
        self._schedule = schedule
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        return self

    def _donate_argnums(self) -> tuple:
        if self._schedule is not None and not self._schedule.donation:
            return ()
        return (0, 1, 2)

    def _aot_key_parts(self) -> dict:
        """Disk-key parts for the persistent tier: model architecture (not
        weights — restarts and same-arch rolls share the executable) plus
        the step-shaping config the body closes over."""
        from deeplearning4j_tpu.compile import (model_fingerprint,
                                                transform_fingerprint)
        return {"kind": "mln_train_step",
                "model": model_fingerprint(self),
                "transform": transform_fingerprint(self._step_transform)}

    def _build_train_step(self):
        from deeplearning4j_tpu.compile import step_function
        return step_function(self._build_step_body(),
                             donate_argnums=self._donate_argnums(),
                             key_base=self._aot_key_parts,
                             cache=self._exec_cache(),
                             dynamic_argnums=(3, 4, 5, 6))

    def _build_step_body(self):
        conf = self.conf
        zt = self._step_transform   # ZeRO-1 sharded weight update, or None

        def step(params, state, opt_state, x, y, fmask, lmask, rng,
                 iteration, epoch):
            # split inside the compiled step: keeps the per-step host work at
            # zero device round-trips (the carry key + iteration counter live
            # on device and flow step→step without fresh H2D transfers)
            if self._device_norm is not None:
                # on-device normalizer prologue: stats are executable
                # constants, the apply fuses into the forward — raw batches
                # stream to device with zero host ETL (data.pipeline)
                x = self._device_norm.apply_features(x)
                y = self._device_norm.apply_labels(y)
            rng, srng = jax.random.split(rng)
            master = params
            if zt is not None:
                # all-gather the data-axis-sharded master params once;
                # forward/backward run on the gathered (or TP) layout
                params = zt.gather_all(params)

            def loss_fn(p):
                loss, new_state = self._loss(p, state, x, y, srng, fmask,
                                             lmask)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)

            new_params = {}
            new_opt = {}
            for i, layer in enumerate(conf.layers):
                name = conf.layer_name(i)
                if layer.frozen:
                    # FrozenLayer semantics (reference `nn/layers/FrozenLayer`):
                    # no update applied, updater state untouched.
                    new_params[name] = master[name]
                    new_opt[name] = opt_state[name]
                    continue
                g = grads[name]
                gn = (layer.gradient_normalization
                      if layer.gradient_normalization is not None
                      else conf.gradient_normalization)
                if gn:
                    thr = (layer.gradient_normalization_threshold
                           if layer.gradient_normalization is not None
                           else conf.gradient_normalization_threshold)
                    g = apply_gradient_normalization(g, gn, thr)
                if zt is None:
                    p_upd = params[name]
                else:
                    # reduce-scatter the (already normalized) grads and run
                    # the updater on this device's shard of params/moments
                    g = zt.scatter(name, g)
                    p_upd = zt.update_view(name, master[name])
                upd_cfg = self._updater_for(i)
                upd, new_o = upd_cfg.apply(opt_state[name], g,
                                           iteration, epoch,
                                           params=p_upd)
                # decoupled weight decay (reference WeightDecay regularization,
                # applyLR=true): update += lr * coeff * w for regularizable params
                wd = (layer.weight_decay if layer.weight_decay is not None
                      else conf.weight_decay)
                if wd:
                    lr = upd_cfg.lr_at(iteration, epoch)
                    upd = _add_scaled_where(
                        upd, p_upd,
                        layer.regularizable_mask(p_upd), lr * wd)
                new_p = jax.tree_util.tree_map(
                    lambda p_, u_: p_ - u_, p_upd, upd)
                if zt is not None:
                    new_p = zt.restore(name, new_p)
                    new_o = zt.constrain_opt(name, new_o)
                new_params[name] = new_p
                new_opt[name] = new_o
            return new_params, new_state, new_opt, loss, rng, iteration + 1

        return step

    def _get_train_step(self):
        if self._train_step is None:
            self._train_step = self._build_train_step()
        return self._train_step

    # ---- hierarchical gradient sharing (parallel.hierarchical) ----
    def set_gradient_sharing(self, sharing) -> "MultiLayerNetwork":
        """Enable/disable hierarchical compressed cross-host gradient
        sharing.  Accepts a `HierarchicalGradientSharing` config (the
        runtime is built here), a prebuilt `HierarchicalAllReduce`, or
        None to clear.  Active sharing splits the compiled step in two —
        a grad half (forward/backward + ICI reduce, emits the local
        gradient tree) and an apply half (updater loop on the DCN-combined
        gradient) — with the host-side compressed exchange between them."""
        from deeplearning4j_tpu.parallel.hierarchical import (
            HierarchicalAllReduce, HierarchicalGradientSharing)
        if sharing is None:
            if self._grad_sharing is not None:
                self._grad_sharing.close()
            self._grad_sharing = None
        elif isinstance(sharing, HierarchicalGradientSharing):
            self._grad_sharing = HierarchicalAllReduce(sharing)
        elif isinstance(sharing, HierarchicalAllReduce):
            self._grad_sharing = sharing
        else:
            raise TypeError(
                "set_gradient_sharing expects HierarchicalGradientSharing, "
                f"HierarchicalAllReduce or None, got {type(sharing).__name__}")
        self._grad_step = None
        self._apply_step = None
        return self

    @property
    def gradient_sharing(self):
        """The installed `HierarchicalAllReduce`, or None."""
        return self._grad_sharing

    def _build_grad_body(self):
        """Grad half of the split step: forward/backward on the local
        mesh (ICI all-reduce via SPMD, reduce-scatter under ZeRO-1), NO
        update.  Params are NOT donated — the apply half needs them."""
        conf = self.conf
        zt = self._step_transform

        def grad_step(params, state, x, y, fmask, lmask, rng):
            if self._device_norm is not None:
                x = self._device_norm.apply_features(x)
                y = self._device_norm.apply_labels(y)
            rng, srng = jax.random.split(rng)
            fwd_params = params if zt is None else zt.gather_all(params)

            def loss_fn(p):
                loss, new_state = self._loss(p, state, x, y, srng, fmask,
                                             lmask)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(fwd_params)
            if zt is not None:
                # ship the reduce-scattered (padded, update-layout) shard —
                # compress the shard, not the gathered tree (ISSUE: ZeRO-1
                # composition); the apply half re-pins the wire grads with
                # constrain_update instead of re-padding
                grads = {conf.layer_name(i): zt.scatter(conf.layer_name(i),
                                                        grads[conf.layer_name(i)])
                         for i in range(len(conf.layers))}
            return grads, new_state, loss, rng

        return grad_step

    def _build_apply_body(self):
        """Apply half: updater loop on the DCN-combined gradient.
        Gradient normalization runs HERE, on the cross-host-combined
        gradient — the same quantity the single-mesh step normalizes
        (zero pads under ZeRO-1 don't perturb L2 norms)."""
        conf = self.conf
        zt = self._step_transform

        def apply_step(params, opt_state, grads, iteration, epoch):
            new_params = {}
            new_opt = {}
            for i, layer in enumerate(conf.layers):
                name = conf.layer_name(i)
                if layer.frozen:
                    new_params[name] = params[name]
                    new_opt[name] = opt_state[name]
                    continue
                g = grads[name]
                if zt is not None:
                    g = zt.constrain_update(name, g)
                gn = (layer.gradient_normalization
                      if layer.gradient_normalization is not None
                      else conf.gradient_normalization)
                if gn:
                    thr = (layer.gradient_normalization_threshold
                           if layer.gradient_normalization is not None
                           else conf.gradient_normalization_threshold)
                    g = apply_gradient_normalization(g, gn, thr)
                p_upd = (params[name] if zt is None
                         else zt.update_view(name, params[name]))
                upd_cfg = self._updater_for(i)
                upd, new_o = upd_cfg.apply(opt_state[name], g,
                                           iteration, epoch,
                                           params=p_upd)
                wd = (layer.weight_decay if layer.weight_decay is not None
                      else conf.weight_decay)
                if wd:
                    lr = upd_cfg.lr_at(iteration, epoch)
                    upd = _add_scaled_where(
                        upd, p_upd,
                        layer.regularizable_mask(p_upd), lr * wd)
                new_p = jax.tree_util.tree_map(
                    lambda p_, u_: p_ - u_, p_upd, upd)
                if zt is not None:
                    new_p = zt.restore(name, new_p)
                    new_o = zt.constrain_opt(name, new_o)
                new_params[name] = new_p
                new_opt[name] = new_o
            return new_params, new_opt, iteration + 1

        return apply_step

    def _get_grad_step(self):
        if self._grad_step is None:
            from deeplearning4j_tpu.compile import step_function
            self._grad_step = step_function(
                self._build_grad_body(),
                donate_argnums=(1,),        # state only: params feed the
                key_base=lambda: dict(      # apply half next
                    self._aot_key_parts(), kind="mln_grad_step"),
                cache=self._exec_cache(),
                dynamic_argnums=(2, 3, 4, 5))
        return self._grad_step

    def _get_apply_step(self):
        if self._apply_step is None:
            from deeplearning4j_tpu.compile import step_function
            self._apply_step = step_function(
                self._build_apply_body(),
                donate_argnums=(0, 1),
                key_base=lambda: dict(
                    self._aot_key_parts(), kind="mln_apply_step"),
                cache=self._exec_cache(),
                dynamic_argnums=())
        return self._apply_step

    def _fit_batch_shared(self, x, y, fmask=None, lmask=None):
        """One training step through the hierarchical path: compiled grad
        half → host-side DCN exchange → compiled apply half."""
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        t0 = time.perf_counter()
        gstep = self._get_grad_step()
        grads, self.state_, loss, self._rng = gstep(
            self.params_, self.state_, x, y, fmask, lmask, self._rng)
        combined = self._grad_sharing.exchange(grads)
        astep = self._get_apply_step()
        it_dev, ep_dev = device_counters(self)
        self.params_, self.opt_state_, new_it = astep(
            self.params_, self.opt_state_, combined, it_dev, ep_dev)
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0)
        ins.check_compile(gstep, self)
        ins.check_compile(astep, self)
        self._score = loss
        self._last_batch_size = int(x.shape[0])
        advance(self, new_it)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)

    def _get_scan_step(self):
        if self._scan_step is None:
            from deeplearning4j_tpu.utils.scan_fit import make_scan_step
            body = self._build_step_body()

            def tick(carry, epoch, batch):
                p, s, o, r, it = carry
                p, s, o, loss, r, it = body(p, s, o, *batch, r, it, epoch)
                return (p, s, o, r, it), loss

            self._scan_step = make_scan_step(
                tick,
                key_base=lambda: dict(self._aot_key_parts(),
                                      kind="mln_scan_step"),
                cache=self._exec_cache(),
                donate=(self._schedule is None or self._schedule.donation))
        return self._scan_step

    def fit_steps(self, xs, ys, features_masks=None, labels_masks=None):
        """Run `k` training steps in one device dispatch.

        Two input forms: stacked `[k, batch, ...]` arrays with a leading
        steps axis, or lists of `k` per-step `[batch, ...]` arrays (the
        streaming prefetch path) — the latter are stacked *inside* the
        compiled dispatch, so pre-staged device batches fuse into the scan
        without an eager host- or device-side stack copy.  Equivalent to
        `k` sequential `fit(x, y)` calls (same math, same updater/iteration
        semantics) but compiled as a single `lax.scan`, eliminating
        per-step host→device dispatch latency.  Listeners fire once per
        block with the final loss; per-step losses are returned as a
        length-k array."""
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        from deeplearning4j_tpu.utils.scan_fit import check_steps_axes
        if self._grad_sharing is not None:
            # a host-side exchange cannot run mid-lax.scan: degrade to a
            # per-step two-phase loop — exact same math, the fused-dispatch
            # latency win is traded for the DCN bytes win (documented in
            # docs/performance.md §6)
            return self._fit_steps_shared(xs, ys, features_masks,
                                          labels_masks)
        if isinstance(xs, (list, tuple)):
            k = len(xs)
            if not (isinstance(ys, (list, tuple)) and len(ys) == k):
                raise ValueError("list-form fit_steps needs xs and ys as "
                                 f"equal-length lists, got {k} xs / "
                                 f"{'non-list' if not isinstance(ys, (list, tuple)) else len(ys)} ys")
            fms = features_masks if features_masks is not None else [None] * k
            lms = labels_masks if labels_masks is not None else [None] * k
            batches = tuple(
                (jnp.asarray(xs[i]), jnp.asarray(ys[i]),
                 None if fms[i] is None else jnp.asarray(fms[i]),
                 None if lms[i] is None else jnp.asarray(lms[i]))
                for i in range(k))
            batch_n = int(batches[0][0].shape[0])
        else:
            xs = jnp.asarray(xs)
            ys = jnp.asarray(ys)
            fm = None if features_masks is None else \
                jnp.asarray(features_masks)
            lm = None if labels_masks is None else jnp.asarray(labels_masks)
            check_steps_axes([("xs", xs), ("ys", ys), ("features_masks", fm),
                              ("labels_masks", lm)])
            batches = (xs, ys, fm, lm)
            k = int(xs.shape[0])
            batch_n = int(xs.shape[1])
        step = self._get_scan_step()
        it_dev, ep_dev = device_counters(self)
        t0 = time.perf_counter()
        ((self.params_, self.state_, self.opt_state_, self._rng, new_it),
         losses, last_loss) = step((self.params_, self.state_,
                                    self.opt_state_, self._rng, it_dev),
                                   ep_dev, batches)
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0, steps=k)
        ins.check_compile(step, self)
        self._score = last_loss
        self._last_batch_size = batch_n
        advance(self, new_it, steps=k)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)
        return losses

    # ---- public API ----
    def fit(self, data, labels=None, *, epochs: int = 1, features_mask=None,
            labels_mask=None, fused_steps: Optional[int] = None):
        """fit(x, y) for one batch, or fit(iterator, epochs=N)
        (reference `fit(INDArray, INDArray)` / `fit(DataSetIterator, int)`).

        `fused_steps=k` stacks k consecutive batches and trains them in a
        single compiled dispatch (`fit_steps`), hiding per-step host
        dispatch latency; odd-sized tail batches (and any batch whose
        shape differs from its block) fall back to the per-step path, so
        results are identical to `fused_steps=1` up to listener cadence.
        Unset, it defaults to the installed schedule's (`apply_schedule`),
        else 1."""
        if labels is not None:
            if fused_steps not in (None, 1):
                raise ValueError(
                    "fused_steps applies to the iterator form only; for a "
                    "pre-stacked [k, batch, ...] block call fit_steps(xs, ys)")
            self._fit_batch(jnp.asarray(data), jnp.asarray(labels),
                            features_mask, labels_mask)
            return self
        if fused_steps is None:
            fused_steps = (self._schedule.fused_steps
                           if self._schedule is not None else 1)
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            with span("fit_epoch", model=type(self).__name__):
                if fused_steps > 1:
                    self._fit_epoch_fused(data, fused_steps)
                else:
                    for ds in data:
                        self._fit_dataset(ds)
            self.epoch += 1
            self._instruments().record_epoch()
            for lst in self.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self)
        return self

    def _fit_dataset(self, ds):
        fm = getattr(ds, "features_mask", None)
        lm = getattr(ds, "labels_mask", None)
        self._fit_batch(jnp.asarray(ds.features), jnp.asarray(ds.labels),
                        None if fm is None else jnp.asarray(fm),
                        None if lm is None else jnp.asarray(lm))

    def _fit_epoch_fused(self, iterator, k: int):
        # streaming fused epoch: device_blocks yields per-step staged
        # arrays and fit_steps stacks them INSIDE the compiled dispatch —
        # no per-block host np.stack copy and no eager device stack;
        # prefetched (already-device) batches fuse without any H2D.
        # Mixed-mask blocks degrade to the per-step path instead of
        # silently dropping later batches' masks.
        from deeplearning4j_tpu.data.pipeline import device_blocks
        for kind, payload in device_blocks(iterator, k):
            if kind == "single":
                self._fit_dataset(payload)
            else:
                self.fit_steps(*payload)

    def _fit_steps_shared(self, xs, ys, features_masks=None,
                          labels_masks=None):
        """Per-step loop replacement for `fit_steps` when hierarchical
        sharing is active (host exchange can't run inside a scan)."""
        if isinstance(xs, (list, tuple)):
            k = len(xs)
            fms = features_masks if features_masks is not None else [None] * k
            lms = labels_masks if labels_masks is not None else [None] * k
            steps = [(jnp.asarray(xs[i]), jnp.asarray(ys[i]),
                      None if fms[i] is None else jnp.asarray(fms[i]),
                      None if lms[i] is None else jnp.asarray(lms[i]))
                     for i in range(k)]
        else:
            xs, ys = jnp.asarray(xs), jnp.asarray(ys)
            k = int(xs.shape[0])
            steps = [(xs[i], ys[i],
                      None if features_masks is None
                      else jnp.asarray(features_masks)[i],
                      None if labels_masks is None
                      else jnp.asarray(labels_masks)[i])
                     for i in range(k)]
        losses = []
        for x, y, fm, lm in steps:
            self._fit_batch_shared(x, y, fm, lm)
            losses.append(self._score)
        return jnp.stack(losses)

    def _fit_batch(self, x, y, fmask=None, lmask=None):
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        if self._grad_sharing is not None:
            return self._fit_batch_shared(x, y, fmask, lmask)
        step = self._get_train_step()
        it_dev, ep_dev = device_counters(self)
        t0 = time.perf_counter()
        (self.params_, self.state_, self.opt_state_, loss, self._rng,
         new_it) = step(
            self.params_, self.state_, self.opt_state_, x, y, fmask, lmask,
            self._rng, it_dev, ep_dev)
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0)
        ins.check_compile(step, self)
        self._score = loss
        self._last_batch_size = int(x.shape[0])
        advance(self, new_it)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)

    def score(self) -> float:
        """Loss of the most recent minibatch (reference `score()`).  This
        is the BLOCKING read: coercing to float waits for the step to
        complete.  Steady-state loops should prefer `score_array()`."""
        s = getattr(self, "_score", None)
        return float(s) if s is not None else float("nan")

    def score_array(self):
        """Loss of the most recent minibatch as a device array (or None
        before the first step).  Never syncs: the array may still be in
        flight — the async-dispatch window stays open until the caller
        coerces it (float/np.asarray), so listeners can record scores
        without stalling the step pipeline."""
        return getattr(self, "_score", None)

    def set_normalizer(self, normalizer) -> "MultiLayerNetwork":
        """Fold a fitted normalizer (NormalizerStandardize / MinMaxScaler /
        ImagePreProcessingScaler, or a DeviceNormalizer) into the compiled
        train step and output fn as an on-device prologue, replacing
        host-side `set_pre_processor` ETL.  Pass None to clear.  Triggers
        a re-trace on the next step (stats are executable constants)."""
        from deeplearning4j_tpu.data.pipeline import DeviceNormalizer
        self._device_norm = (None if normalizer is None
                             else DeviceNormalizer.from_host(normalizer))
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        self._output_fn = None
        return self

    def score_for(self, x, y, features_mask=None, labels_mask=None) -> float:
        """Score on given data without updating (reference `score(DataSet)`):
        eval mode — no dropout, BN uses running statistics."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        if self._device_norm is not None:
            x = self._device_norm.apply_features(x)
            y = self._device_norm.apply_labels(y)
        loss, _ = self._loss(self.params_, self.state_, x,
                             y, None, features_mask, labels_mask,
                             train=False)
        return float(loss)

    def output(self, x, train: bool = False) -> jnp.ndarray:
        """Inference forward pass (reference `output(INDArray)`), jitted.
        An attached on-device normalizer (`set_normalizer`) applies here
        too, so inference sees the same prologue as training."""
        if self._output_fn is None:
            def fwd(p, s, x_):
                if self._device_norm is not None:
                    x_ = self._device_norm.apply_features(x_)
                return self._forward(p, s, x_, train=False, rng=None)[0]
            self._output_fn = jax.jit(fwd)
        return self._output_fn(self.params_, self.state_, jnp.asarray(x))

    def feed_forward(self, x, train: bool = False) -> List[jnp.ndarray]:
        """All layer activations (reference `feedForward()`)."""
        acts = [jnp.asarray(x)]
        h = acts[0]
        state = self.state_
        for i in range(len(self.conf.layers)):
            name = self.conf.layer_name(i)
            h, _ = self.conf.layers[i].apply(
                self.params_[name], state[name], h, train=train, rng=None)
            acts.append(h)
        return acts

    def evaluate(self, iterator, evaluation=None):
        """Classification eval over an iterator (reference
        `evaluate(DataSetIterator)`)."""
        from deeplearning4j_tpu.train.evaluation import Evaluation
        ev = evaluation or Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(np.asarray(ds.labels), np.asarray(out))
        return ev

    # ---- flat-param view (checkpoint/API contract) ----
    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params_))

    def params(self) -> np.ndarray:
        """Single flat parameter vector — the reference's flattened-view
        `params()` contract, preserved at the boundary only (internally
        params live as a sharded pytree)."""
        leaves = jax.tree_util.tree_leaves(self.params_)
        return np.concatenate([np.asarray(l).ravel() for l in leaves]) if leaves \
            else np.zeros((0,), np.float32)

    def set_params(self, flat: np.ndarray):
        leaves, treedef = jax.tree_util.tree_flatten(self.params_)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(flat[off:off + n], l.dtype).reshape(l.shape))
            off += n
        if off != flat.size:
            raise ValueError(f"Param count mismatch: {flat.size} vs {off}")
        self.params_ = jax.tree_util.tree_unflatten(treedef, out)

    # ---- gradient-check hook ----
    def gradient_for(self, x, y, features_mask=None, labels_mask=None) -> Params:
        """Analytic gradients of the score wrt params (no update) — the
        `computeGradientAndScore` half used by GradientCheckUtil.  Eval mode,
        consistent with `score_for` finite differences (BN running stats,
        no dropout)."""
        x, y = jnp.asarray(x), jnp.asarray(y)
        if self._device_norm is not None:   # same prologue as score_for
            x = self._device_norm.apply_features(x)
            y = self._device_norm.apply_labels(y)

        def loss_fn(p):
            return self._loss(p, self.state_, x, y,
                              None, features_mask, labels_mask,
                              train=False)[0]
        return jax.grad(loss_fn)(self.params_)

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    # ---- persistence (delegates to ModelSerializer) ----
    def save(self, path: str, save_updater: bool = True):
        from deeplearning4j_tpu.utils.serialization import write_model
        write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "MultiLayerNetwork":
        from deeplearning4j_tpu.utils.serialization import read_model
        return read_model(path, load_updater=load_updater)
