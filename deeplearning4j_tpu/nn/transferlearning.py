"""Transfer learning (reference `deeplearning4j-nn/.../nn/transferlearning/
{TransferLearning,TransferLearningHelper,FineTuneConfiguration}.java`).

`TransferLearning.Builder` edits a trained MultiLayerNetwork's config —
freeze a feature-extractor prefix, swap the output head, append layers —
and builds a new network that keeps the retained layers' parameters.
`TransferLearningHelper` featurizes data through the frozen prefix once so
repeated fine-tune epochs skip the frozen compute entirely (the reference's
`featurize`/`fitFeaturized` flow; on TPU this also shrinks the compiled
step to the trainable suffix).

`TransferLearning.GraphBuilder` is the ComputationGraph counterpart
(reference `TransferLearning.GraphBuilder`): freeze an ancestor subgraph,
remove/splice/add vertices, resize heads — retained vertices keep their
trained parameters.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.core import InputType, Layer
from deeplearning4j_tpu.nn.multilayer import (MultiLayerConfiguration,
                                              MultiLayerNetwork)
from deeplearning4j_tpu.train.updaters import IUpdater


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global-hyperparameter overrides for the fine-tune phase (reference
    `FineTuneConfiguration`)."""

    updater: Optional[IUpdater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    seed: Optional[int] = None

    def apply(self, conf: MultiLayerConfiguration):
        if self.updater is not None:
            conf.updater = self.updater
        if self.l1 is not None:
            conf.l1 = self.l1
        if self.l2 is not None:
            conf.l2 = self.l2
        if self.weight_decay is not None:
            conf.weight_decay = self.weight_decay
        if self.seed is not None:
            conf.seed = self.seed


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._conf = copy.deepcopy(net.conf)
            self._freeze_upto: Optional[int] = None
            self._removed_from: Optional[int] = None  # layers >= idx dropped
            self._added: List[Layer] = []
            self._reinit: set = set()                 # layer indices to re-init
            self._fine_tune: Optional[FineTuneConfiguration] = None

        def fine_tune_configuration(self, ft: FineTuneConfiguration):
            self._fine_tune = ft
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0, layer_index] (reference
            `setFeatureExtractor`)."""
            self._freeze_upto = layer_index
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            keep = len(self._conf.layers) - n
            if keep < 0:
                raise ValueError(f"Cannot remove {n} of "
                                 f"{len(self._conf.layers)} layers")
            self._removed_from = keep
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: Optional[str] = None):
            """Resize a layer's output (reference `nOutReplace`): that layer
            AND the next one re-initialize (the next layer's n_in changes)."""
            layer = copy.deepcopy(self._conf.layers[layer_index])
            if not hasattr(layer, "n_out"):
                raise ValueError(f"Layer {layer_index} has no n_out")
            layer.n_out = n_out
            if weight_init:
                layer.weight_init = weight_init
            self._conf.layers[layer_index] = layer
            self._reinit.add(layer_index)
            if layer_index + 1 < len(self._conf.layers):
                self._reinit.add(layer_index + 1)
            return self

        def add_layer(self, layer: Layer):
            self._added.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            conf = self._conf
            old_names = [conf.layer_name(i) for i in range(len(conf.layers))]
            n_keep = (self._removed_from if self._removed_from is not None
                      else len(conf.layers))
            conf.layers = conf.layers[:n_keep] + self._added
            if self._fine_tune:
                self._fine_tune.apply(conf)
            if self._freeze_upto is not None:
                for i in range(min(self._freeze_upto + 1, len(conf.layers))):
                    layer = copy.deepcopy(conf.layers[i])
                    layer.frozen = True
                    conf.layers[i] = layer
            net = MultiLayerNetwork(conf).init()
            # carry over parameters for retained, un-reinitialized layers
            for i in range(min(n_keep, len(conf.layers))):
                if i in self._reinit:
                    continue
                old = old_names[i]
                new = conf.layer_name(i)
                if old in self._net.params_:
                    # deep-copy leaves: the jitted train step donates its
                    # param buffers, so sharing arrays between the source and
                    # derived networks would delete the source's buffers on
                    # the derived net's first fit()
                    net.params_[new] = jax.tree_util.tree_map(
                        jnp.copy, self._net.params_[old])
                    net.state_[new] = jax.tree_util.tree_map(
                        jnp.copy, self._net.state_[old])
            return net

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)

    class GraphBuilder:
        """ComputationGraph transfer learning (reference
        `TransferLearning.GraphBuilder`): freeze a feature-extractor
        subgraph, remove/replace vertices, swap heads — retained vertices
        keep their trained parameters."""

        def __init__(self, net):
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            if not isinstance(net, ComputationGraph):
                raise TypeError("GraphBuilder wraps a ComputationGraph; use "
                                "TransferLearning.builder for MLNs")
            self._net = net
            self._conf = copy.deepcopy(net.conf)
            self._frozen_at: List[str] = []
            self._reinit: set = set()
            self._removed: set = set()
            self._fine_tune: Optional[FineTuneConfiguration] = None

        def fine_tune_configuration(self, ft: FineTuneConfiguration):
            self._fine_tune = ft
            return self

        def set_feature_extractor(self, *vertex_names: str):
            """Freeze the named vertices and every ancestor (reference
            `setFeatureExtractor(String...)`)."""
            for n in vertex_names:
                if n not in self._conf.vertices:
                    raise ValueError(f"Unknown vertex '{n}'")
            self._frozen_at = list(vertex_names)
            return self

        def remove_vertex_and_connections(self, name: str):
            """Drop a vertex and its edges; consumers must be re-wired via
            add_layer/add_vertex before build (reference
            `removeVertexAndConnections` leaves the same obligation)."""
            self._conf.vertices.pop(name)
            self._conf.vertex_inputs.pop(name, None)
            self._conf.network_outputs = [
                o for o in self._conf.network_outputs if o != name]
            self._removed.add(name)
            return self

        def remove_vertex_keep_connections(self, name: str):
            """Splice a single-input vertex out of the DAG, re-pointing its
            consumers at its input."""
            ins = self._conf.vertex_inputs.get(name, [])
            if len(ins) != 1:
                raise ValueError(
                    f"remove_vertex_keep_connections needs exactly one "
                    f"input edge on '{name}', found {len(ins)}")
            (src,) = ins
            self._conf.vertices.pop(name)
            self._conf.vertex_inputs.pop(name)
            for v, vins in self._conf.vertex_inputs.items():
                self._conf.vertex_inputs[v] = [src if i == name else i
                                               for i in vins]
            self._conf.network_outputs = [
                src if o == name else o for o in self._conf.network_outputs]
            self._removed.add(name)
            return self

        def add_layer(self, name: str, layer: Layer, *inputs: str):
            from deeplearning4j_tpu.nn.graph import LayerVertex
            return self.add_vertex(name, LayerVertex(name=name, layer=layer),
                                   *inputs)

        def add_vertex(self, name: str, vertex, *inputs: str):
            if name in self._conf.vertices:
                raise ValueError(f"Vertex '{name}' already exists")
            self._conf.vertices[name] = vertex
            self._conf.vertex_inputs[name] = list(inputs)
            self._reinit.add(name)
            return self

        def set_outputs(self, *names: str):
            self._conf.network_outputs = list(names)
            return self

        def n_out_replace(self, layer_name: str, n_out: int,
                          weight_init: Optional[str] = None):
            """Resize a layer vertex's n_out; it and its direct consumers
            re-initialize (reference `nOutReplace`)."""
            from deeplearning4j_tpu.nn.graph import LayerVertex
            v = self._conf.vertices[layer_name]
            if not isinstance(v, LayerVertex) or not hasattr(v.layer,
                                                             "n_out"):
                raise ValueError(f"'{layer_name}' is not a resizable layer")
            v.layer.n_out = n_out
            if weight_init:
                v.layer.weight_init = weight_init
            self._reinit.add(layer_name)
            # the width change propagates until absorbed by a layer that
            # SETS its own output width (has n_out: Dense/Conv/Output/...);
            # everything else — Merge/ElementWise/Activation/BatchNorm/
            # pooling — passes the width through and re-initializes
            frontier = [layer_name]
            while frontier:
                src = frontier.pop()
                for consumer, ins in self._conf.vertex_inputs.items():
                    if src in ins and consumer not in self._reinit:
                        self._reinit.add(consumer)
                        c_layer = getattr(self._conf.vertices[consumer],
                                          "layer", None)
                        if c_layer is None or not hasattr(c_layer, "n_out"):
                            frontier.append(consumer)
            return self

        def _ancestors_of(self, roots: List[str]) -> set:
            closed = set()
            stack = list(roots)
            while stack:
                n = stack.pop()
                if n in closed or n not in self._conf.vertices:
                    continue
                closed.add(n)
                stack.extend(self._conf.vertex_inputs.get(n, []))
            return closed

        def build(self):
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            conf = self._conf
            if self._fine_tune:
                self._fine_tune.apply(conf)
            frozen = self._ancestors_of(self._frozen_at)
            for n in frozen:
                layer = getattr(conf.vertices[n], "layer", None)
                if layer is not None:
                    layer.frozen = True
            net = ComputationGraph(conf).init()
            # carry trained params into retained, un-reinitialized vertices;
            # copy leaves — the jitted step donates param buffers, so the
            # source and derived nets must never share arrays
            for name in conf.vertices:
                if name in self._reinit or name in self._removed:
                    continue
                if name in self._net.params_:
                    old = self._net.params_[name]
                    shapes_match = all(
                        np.shape(a) == np.shape(b)
                        for a, b in zip(jax.tree_util.tree_leaves(old),
                                        jax.tree_util.tree_leaves(
                                            net.params_[name])))
                    if not shapes_match:
                        raise ValueError(
                            f"Cannot transplant params into '{name}': its "
                            "expected shapes changed (an upstream edit "
                            "resized it) — mark it for re-init via "
                            "n_out_replace or rebuild it explicitly")
                    net.params_[name] = jax.tree_util.tree_map(
                        jnp.copy, old)
                    net.state_[name] = jax.tree_util.tree_map(
                        jnp.copy, self._net.state_[name])
            return net

    @staticmethod
    def graph_builder(net) -> "TransferLearning.GraphBuilder":
        return TransferLearning.GraphBuilder(net)


class TransferLearningHelper:
    """Featurize-through-frozen-prefix fine-tuning (reference
    `TransferLearningHelper`)."""

    def __init__(self, net: MultiLayerNetwork,
                 frozen_till: Optional[int] = None):
        if frozen_till is None:
            frozen = [i for i, l in enumerate(net.conf.layers) if l.frozen]
            frozen_till = max(frozen) if frozen else -1
        self.frozen_till = frozen_till
        self.full_net = net
        self._boundary = frozen_till + 1
        # the trainable suffix as its own network (compiled step excludes
        # the frozen prefix entirely)
        suffix_conf = copy.deepcopy(net.conf)
        suffix_conf.layers = [copy.deepcopy(l)
                              for l in net.conf.layers[self._boundary:]]
        for l in suffix_conf.layers:
            l.frozen = False
        suffix_conf.input_type = net._layer_types[self._boundary]
        self.unfrozen_net = MultiLayerNetwork(suffix_conf).init()
        for j in range(len(suffix_conf.layers)):
            old = net.conf.layer_name(self._boundary + j)
            new = suffix_conf.layer_name(j)
            # copy leaves — donated buffers must not be shared across nets
            self.unfrozen_net.params_[new] = jax.tree_util.tree_map(
                jnp.copy, net.params_[old])
            self.unfrozen_net.state_[new] = jax.tree_util.tree_map(
                jnp.copy, net.state_[old])

    def featurize(self, ds: DataSet) -> DataSet:
        """Run the frozen prefix once (reference `featurize`)."""
        h = ds.features
        state = self.full_net.state_
        for i in range(self._boundary):
            name = self.full_net.conf.layer_name(i)
            h, _ = self.full_net.conf.layers[i].apply(
                self.full_net.params_[name], state[name], h,
                train=False, rng=None)
        return DataSet(np.asarray(h), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def fit_featurized(self, ds: DataSet):
        self.unfrozen_net.fit(ds.features, ds.labels)
        return self

    def output_from_featurized(self, features):
        return self.unfrozen_net.output(features)

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.unfrozen_net

    def sync_to_full(self):
        """Copy trained suffix params back into the full network."""
        for j in range(len(self.unfrozen_net.conf.layers)):
            old = self.full_net.conf.layer_name(self._boundary + j)
            new = self.unfrozen_net.conf.layer_name(j)
            self.full_net.params_[old] = jax.tree_util.tree_map(
                jnp.copy, self.unfrozen_net.params_[new])
            self.full_net.state_[old] = jax.tree_util.tree_map(
                jnp.copy, self.unfrozen_net.state_[new])
        return self.full_net
