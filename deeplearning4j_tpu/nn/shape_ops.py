"""Shape-manipulation layers + the TimeDistributed wrapper.

Reference analogs: the Keras-import preprocessors
(`deeplearning4j-modelimport/.../keras/layers/core/KerasReshape.java`,
`KerasPermute.java`, `KerasRepeatVector.java`) and the wrapper layer
`keras/layers/wrappers/KerasTimeDistributed.java` — the reference realises
these as InputPreProcessors attached to neighbouring layers; here they are
first-class (param-free) layers, which keeps the MLN/CG topology explicit
and JSON-round-trippable.

All are pure reshapes/transposes — XLA folds them into neighbouring
fusions, so they cost nothing on TPU.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.core import InputType, Layer


def input_type_from_shape(shape: Sequence[int]) -> InputType:
    """Batch-less shape tuple -> InputType (the Keras-import convention:
    rank 1 = feed-forward, 2 = recurrent [T, F], 3 = NHWC, 4 = NDHWC)."""
    shape = tuple(int(s) for s in shape)
    if len(shape) == 1:
        return InputType.feed_forward(shape[0])
    if len(shape) == 2:
        return InputType.recurrent(shape[1], shape[0])
    if len(shape) == 3:
        return InputType.convolutional(*shape)
    if len(shape) == 4:
        return InputType.convolutional3d(*shape)
    raise ValueError(f"Unsupported target rank {len(shape)}")


@dataclasses.dataclass(kw_only=True)
class ReshapeLayer(Layer):
    """Reshape non-batch dims to `target_shape` (Keras `Reshape` /
    reference `KerasReshape` preprocessor)."""

    target_shape: Tuple[int, ...] = ()
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        out = input_type_from_shape(self.target_shape)
        if input_type.flat_size() != out.flat_size():
            raise ValueError(
                f"Reshape: {input_type.shape} has {input_type.flat_size()} "
                f"elements, target {tuple(self.target_shape)} has "
                f"{out.flat_size()}")
        return {}, {}, out

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape((x.shape[0],) + tuple(self.target_shape)), state


@dataclasses.dataclass(kw_only=True)
class PermuteLayer(Layer):
    """Transpose non-batch dims by `dims` (1-indexed, batch excluded —
    Keras `Permute` semantics / reference `KerasPermute`)."""

    dims: Tuple[int, ...] = ()
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if sorted(self.dims) != list(range(1, len(input_type.shape) + 1)):
            raise ValueError(f"Permute dims {self.dims} must be a "
                             f"permutation of 1..{len(input_type.shape)}")
        out_shape = tuple(input_type.shape[d - 1] for d in self.dims)
        return {}, {}, input_type_from_shape(out_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.transpose(x, (0,) + tuple(d for d in self.dims)), state


@dataclasses.dataclass(kw_only=True)
class RepeatVectorLayer(Layer):
    """[B, F] -> [B, n, F] (Keras `RepeatVector` / reference
    `KerasRepeatVector`): feed-forward activation repeated into a
    sequence."""

    n: int = 0
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if input_type.kind != "feedforward":
            raise ValueError("RepeatVector requires feed-forward input, "
                             f"got {input_type.kind}")
        return {}, {}, InputType.recurrent(input_type.shape[0], self.n)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state


@dataclasses.dataclass(kw_only=True)
class TimeDistributed(Layer):
    """Applies a feed-forward inner layer independently at every timestep
    of a [B, T, ...] input (Keras `TimeDistributed` / reference
    `KerasTimeDistributed` wrapper): folds time into batch, applies,
    unfolds.  XLA sees one big batched matmul — the TPU-preferred form."""

    underlying: Optional[Layer] = None
    STOCHASTIC: bool = True
    REGULARIZABLE: Tuple[str, ...] = ()

    def __post_init__(self):
        if self.underlying is None:
            raise ValueError("TimeDistributed requires underlying=...")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if input_type.kind != "recurrent":
            raise ValueError("TimeDistributed requires recurrent input "
                             f"[T, F], got {input_type.kind}")
        T, F = input_type.shape
        if self.underlying.weight_init is None:
            self.underlying.weight_init = self.weight_init
        p, s, ot = self.underlying.initialize(
            rng, InputType.feed_forward(F), dtype)
        if ot.kind != "feedforward":
            raise ValueError("TimeDistributed inner layer must map "
                             "feed-forward -> feed-forward")
        return p, s, InputType.recurrent(ot.shape[0], T)

    def regularizable_mask(self, params):
        return self.underlying.regularizable_mask(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        r0 = None
        if rng is not None:
            r0, rng = jax.random.split(rng)
        x = self.maybe_input_dropout(x, train, r0)
        B, T = x.shape[0], x.shape[1]
        flat = x.reshape((B * T,) + x.shape[2:])
        y, s = self.underlying.apply(params, state, flat, train=train,
                                     rng=rng, mask=None)
        return y.reshape((B, T) + y.shape[1:]), s


@dataclasses.dataclass(kw_only=True)
class FlattenLayer(Layer):
    """Flatten all non-batch dims to a feed-forward vector (Keras
    `Flatten`; the reference realises this as CnnToFeedForward /
    RnnToFeedForward preprocessors)."""

    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, InputType.feed_forward(input_type.flat_size())

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape(x.shape[0], -1), state
