"""Feed-forward and convolutional layer zoo.

Covers the reference's `deeplearning4j-nn/.../nn/conf/layers/*.java` configs
and `nn/layers/**` implementations: Dense, Output, Loss, Activation, Dropout,
Embedding(+Sequence), Convolution2D (+1D/Depthwise/Separable/Deconv),
Subsampling (pooling), BatchNormalization, LocalResponseNormalization,
GlobalPooling, Upsampling, ZeroPadding, ElementWiseMultiplication.

TPU notes: convs run NHWC/HWIO via `lax.conv_general_dilated` so XLA tiles
them directly onto the MXU; pooling is `lax.reduce_window`; batch-norm in
training mode computes batch statistics inline (XLA fuses the whole
normalize-scale-shift chain into neighbouring ops — the role cuDNN's fused
batchnorm plays in the reference's platform helpers,
`libnd4j/include/ops/declarable/platform/cudnn/batchnorm.cu`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.core import InputType, Layer, PyTree
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import get_loss


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


# ---------------------------------------------------------------------------
# Dense / Output / Loss
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class DenseLayer(Layer):
    """Fully-connected layer (reference `DenseLayer` /
    `nn/layers/feedforward/dense/DenseLayer.java`).  Non-2D inputs are
    auto-flattened, subsuming `CnnToFeedForwardPreProcessor`."""

    n_out: int = 0
    has_bias: bool = True
    STOCHASTIC: bool = True  # input dropout

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in = input_type.flat_size() if input_type.kind != "recurrent" else input_type.shape[-1]
        params = {"W": init_weights(rng, (n_in, self.n_out), self.winit(), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        out_type = (InputType.recurrent(self.n_out, input_type.shape[0])
                    if input_type.kind == "recurrent"
                    else InputType.feed_forward(self.n_out))
        return params, {}, out_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        if x.ndim > 2 and not self._is_recurrent_input(x):
            x = x.reshape(x.shape[0], -1)
        y = self._fused_dense(x, params)
        if y is not None:
            return y, state
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state

    def _fused_dense(self, x, params):
        """Route through the Pallas fused bias+activation tile when the
        kernel tier takes the call (TPU/GPU, or forced mode); None keeps
        the plain XLA lowering — the CPU/tier-1 path, bit-identical to
        before the tier existed."""
        act = self.activation if self.activation is not None else "identity"
        if not isinstance(act, str):
            return None
        try:
            from deeplearning4j_tpu.ops import pallas as tier
            b = params.get("b") if self.has_bias else None
            if tier.dispatch.resolve("fused_dense", x, params["W"], bias=b,
                                     activation=act) != "pallas":
                return None
            rows = 1
            for d in x.shape[:-1]:
                rows *= int(d)
            sc = tier.shape_class(m=rows, k=int(x.shape[-1]),
                                  n=int(params["W"].shape[-1]))
            return tier.matmul.fused_dense(
                x, params["W"], bias=b, activation=act,
                tile=tier.dispatch.get_tile("fused_dense", sc),
                interpret=tier.dispatch.interpret_mode())
        except Exception:
            return None

    def _is_recurrent_input(self, x):
        # [batch, time, features] passes through time-distributed.
        return x.ndim == 3


@dataclasses.dataclass(kw_only=True)
class OutputLayer(DenseLayer):
    """Dense + loss head (reference `OutputLayer`).  The loss consumes raw
    pre-activations for logit-fused losses (MCXENT/XENT) — the stable path —
    while `activate()` still applies the configured activation for
    `output()` calls."""

    loss: Any = "mcxent"

    def loss_fn(self):
        return get_loss(self.loss)

    def compute_loss(self, params, state, x, labels, *, train=True, rng=None,
                     mask=None):
        from deeplearning4j_tpu.ops.losses import apply_loss
        x = self.maybe_input_dropout(x, train, rng)
        if x.ndim > 2 and not self._is_recurrent_input(x):
            x = x.reshape(x.shape[0], -1)
        pre = x @ params["W"]
        if self.has_bias:
            pre = pre + params["b"]
        # loss math (softmax/log) in >= f32: upcasts bf16 logits, leaves
        # f64 gradient-check nets untouched
        pre = pre.astype(jnp.promote_types(pre.dtype, jnp.float32))
        return apply_loss(self.loss, self.act_fn(), pre, labels, mask)


@dataclasses.dataclass(kw_only=True)
class LossLayer(Layer):
    """Loss-only head, no params (reference `LossLayer`)."""

    loss: Any = "mcxent"
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.act_fn()(x), state

    def compute_loss(self, params, state, x, labels, *, train=True, rng=None,
                     mask=None):
        from deeplearning4j_tpu.ops.losses import apply_loss
        return apply_loss(self.loss, self.act_fn(), x, labels, mask)


@dataclasses.dataclass(kw_only=True)
class ActivationLayer(Layer):
    """Standalone activation (reference `ActivationLayer`).

    `activation_args` parameterizes named activations (e.g. leakyrelu's
    alpha) while keeping the config JSON-serializable — the IActivation-
    with-hyperparameters case that a bare name can't carry."""

    activation_args: Optional[Dict[str, Any]] = None
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        fn = self.act_fn()
        if self.activation_args:
            return fn(x, **self.activation_args), state
        return fn(x), state


@dataclasses.dataclass(kw_only=True)
class DropoutLayer(Layer):
    """Standalone dropout (reference `DropoutLayer`); `dropout` is the
    RETAIN probability per reference semantics."""

    dropout: Optional[float] = 0.5
    REGULARIZABLE: Tuple[str, ...] = ()
    STOCHASTIC: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.maybe_input_dropout(x, train, rng), state


@dataclasses.dataclass(kw_only=True)
class ElementWiseMultiplicationLayer(Layer):
    """Per-feature learned scale + bias (reference
    `ElementWiseMultiplicationLayer`)."""

    STOCHASTIC: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n = input_type.flat_size()
        params = {"W": jnp.ones((n,), dtype), "b": jnp.full((n,), self.bias_init, dtype)}
        return params, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        return self.act_fn()(x * params["W"] + params["b"]), state


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class EmbeddingLayer(Layer):
    """Index -> vector lookup (reference `EmbeddingLayer`): input is a
    [batch] or [batch, 1] int array.  On TPU this is a gather — XLA lowers it
    natively, replacing the reference's embedding-as-onehot-matmul fallback."""

    n_in: int = 0   # vocab size
    n_out: int = 0
    has_bias: bool = False

    def initialize(self, rng, input_type, dtype=jnp.float32):
        params = {"W": init_weights(rng, (self.n_in, self.n_out), self.winit(), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}, InputType.feed_forward(self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class EmbeddingSequenceLayer(Layer):
    """Sequence of indices -> [batch, time, n_out] (reference
    `EmbeddingSequenceLayer`)."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = False

    def initialize(self, rng, input_type, dtype=jnp.float32):
        params = {"W": init_weights(rng, (self.n_in, self.n_out), self.winit(), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        t = input_type.shape[0] if input_type.kind == "recurrent" else None
        return params, {}, InputType.recurrent(self.n_out, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y = params["W"][x.astype(jnp.int32)]
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


# ---------------------------------------------------------------------------
# Convolutions (NHWC / HWIO)
# ---------------------------------------------------------------------------

def _padding_2d(mode: str, padding) -> Any:
    """ConvolutionMode (Same|Truncate|Strict) + explicit padding -> the lax
    padding argument. Shared by every 2-D conv/pool layer."""
    if (mode or "Truncate").lower() == "same":
        return "SAME"
    ph, pw = _pair(padding)
    return [(ph, ph), (pw, pw)]


@dataclasses.dataclass(kw_only=True)
class ConvolutionLayer(Layer):
    """2-D convolution (reference `ConvolutionLayer` → libnd4j conv2d op +
    cuDNN platform helper).  NHWC input, HWIO kernel — the layout XLA maps
    straight onto the MXU."""

    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    dilation: Any = (1, 1)
    convolution_mode: str = "Truncate"  # Same | Truncate | Strict
    has_bias: bool = True

    def _spatial(self, in_hw):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        ph, pw = _pair(self.padding)
        if self.convolution_mode.lower() == "same":
            oh = -(-in_hw[0] // sh)
            ow = -(-in_hw[1] // sw)
        else:
            eff_kh = (kh - 1) * dh + 1
            eff_kw = (kw - 1) * dw + 1
            oh = (in_hw[0] + 2 * ph - eff_kh) // sh + 1
            ow = (in_hw[1] + 2 * pw - eff_kw) // sw + 1
        return oh, ow

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel_size)
        params = {"W": init_weights(rng, (kh, kw, c, self.n_out), self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        oh, ow = self._spatial((h, w))
        return params, {}, InputType.convolutional(oh, ow, self.n_out)

    def _padding_arg(self):
        return _padding_2d(self.convolution_mode, self.padding)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        pad = self._padding_arg()
        from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_eligible,
                                                         conv3x3_same)
        # Pallas conv-backward adoption hook (default off; bias is added
        # AFTER the conv here, so the conv itself qualifies) — see
        # ops/conv_kernels.CONV_BWD_PALLAS + playbook stage 8
        if conv3x3_eligible(x.shape, params["W"].shape, None,
                            _pair(self.stride), pad,
                            _pair(self.dilation)):
            y = conv3x3_same(x, params["W"])
        else:
            y = lax.conv_general_dilated(
                x, params["W"],
                window_strides=_pair(self.stride),
                padding=pad,
                rhs_dilation=_pair(self.dilation),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class Convolution1DLayer(Layer):
    """1-D conv over [batch, time, features] (reference `Convolution1DLayer`)."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "Same"
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        f = input_type.shape[-1]
        k = int(self.kernel_size)
        params = {"W": init_weights(rng, (k, f, self.n_out), self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        t = input_type.shape[0]
        if t is not None:
            if self.convolution_mode.lower() == "same":
                t = -(-t // int(self.stride))
            else:
                eff_k = (k - 1) * int(self.dilation) + 1
                t = (t + 2 * int(self.padding) - eff_k) // int(self.stride) + 1
        return params, {}, InputType.recurrent(self.n_out, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        pad = ("SAME" if self.convolution_mode.lower() == "same"
               else [(int(self.padding),) * 2])
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=(int(self.stride),),
            padding=pad,
            rhs_dilation=(int(self.dilation),),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class DepthwiseConvolution2DLayer(Layer):
    """Depthwise conv (reference `DepthwiseConvolution2D`)."""

    depth_multiplier: int = 1
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    convolution_mode: str = "Truncate"
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel_size)
        n_out = c * self.depth_multiplier
        params = {"W": init_weights(rng, (kh, kw, 1, n_out), self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((n_out,), self.bias_init, dtype)
        helper = ConvolutionLayer(n_out=n_out, kernel_size=self.kernel_size,
                                  stride=self.stride, padding=self.padding,
                                  convolution_mode=self.convolution_mode)
        oh, ow = helper._spatial((h, w))
        return params, {}, InputType.convolutional(oh, ow, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        c = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_padding_2d(self.convolution_mode, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class SeparableConvolution2DLayer(Layer):
    """Depthwise-separable conv (reference `SeparableConvolution2D`)."""

    n_out: int = 0
    depth_multiplier: int = 1
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    padding: Any = (0, 0)
    convolution_mode: str = "Truncate"
    has_bias: bool = True
    REGULARIZABLE: Tuple[str, ...] = ("W_depth", "W_point")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel_size)
        k1, k2 = jax.random.split(rng)
        mid = c * self.depth_multiplier
        params = {
            "W_depth": init_weights(k1, (kh, kw, 1, mid), self.winit("RELU"), dtype),
            "W_point": init_weights(k2, (1, 1, mid, self.n_out), self.winit("RELU"), dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        helper = ConvolutionLayer(n_out=self.n_out, kernel_size=self.kernel_size,
                                  stride=self.stride, padding=self.padding,
                                  convolution_mode=self.convolution_mode)
        oh, ow = helper._spatial((h, w))
        return params, {}, InputType.convolutional(oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        c = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["W_depth"], window_strides=_pair(self.stride),
            padding=_padding_2d(self.convolution_mode, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)
        y = lax.conv_general_dilated(
            y, params["W_point"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class Deconvolution2DLayer(Layer):
    """Transposed conv (reference `Deconvolution2D`)."""

    n_out: int = 0
    kernel_size: Any = (2, 2)
    stride: Any = (2, 2)
    padding: Any = (0, 0)
    convolution_mode: str = "Truncate"
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        params = {"W": init_weights(rng, (kh, kw, c, self.n_out), self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        if self.convolution_mode.lower() == "same":
            oh, ow = h * sh, w * sw
        else:
            oh = sh * (h - 1) + kh - 2 * ph
            ow = sw * (w - 1) + kw - 2 * pw
        return params, {}, InputType.convolutional(oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        if self.convolution_mode.lower() == "same":
            pad = "SAME"
        else:
            # lax.conv_transpose explicit pads apply to the lhs-dilated
            # input; reference-style deconv padding p maps to (k-1-p) so the
            # output is s*(h-1) + k - 2p, matching the reference shape fn.
            kh, kw = _pair(self.kernel_size)
            ph, pw = _pair(self.padding)
            pad = [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        # gradient-form transposed conv (TF/Keras/reference convention):
        # lax.conv_transpose slides the kernel in correlation orientation,
        # spatially flipped relative to the gradient form — flip here.
        # Without this, Conv2DTranspose imports are spatially mirrored
        # (caught by the op-validation sweep; the old conformance test's
        # deconv fed an avg-pool, which is flip-invariant).
        y = lax.conv_transpose(
            x, jnp.flip(params["W"], (0, 1)), strides=_pair(self.stride),
            padding=pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class SubsamplingLayer(Layer):
    """Spatial pooling (reference `SubsamplingLayer`): MAX | AVG | SUM |
    PNORM over NHWC windows via `lax.reduce_window`."""

    pooling_type: str = "MAX"
    kernel_size: Any = (2, 2)
    stride: Any = (2, 2)
    padding: Any = (0, 0)
    convolution_mode: str = "Truncate"
    pnorm: int = 2
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        helper = ConvolutionLayer(n_out=c, kernel_size=self.kernel_size,
                                  stride=self.stride, padding=self.padding,
                                  convolution_mode=self.convolution_mode)
        oh, ow = helper._spatial((h, w))
        return {}, {}, InputType.convolutional(oh, ow, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        pad2 = _padding_2d(self.convolution_mode, self.padding)
        pad = pad2
        if pad != "SAME":
            pad = ((0, 0), tuple(pad[0]), tuple(pad[1]), (0, 0))
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pt = self.pooling_type.upper()
        if pt == "MAX":
            from deeplearning4j_tpu.ops.pool_kernels import max_pool2d
            p2 = pad2 if isinstance(pad2, str) \
                else (tuple(pad2[0]), tuple(pad2[1]))
            y = max_pool2d(x, (kh, kw), (sh, sw), p2)
        elif pt in ("AVG", "AVERAGE"):
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        elif pt == "SUM":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif pt == "PNORM":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
            y = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type}")
        return y, state


@dataclasses.dataclass(kw_only=True)
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or time dims (reference
    `GlobalPoolingLayer`), with mask support for variable-length sequences."""

    pooling_type: str = "MAX"
    pnorm: int = 2
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if input_type.kind == "convolutional":
            c = input_type.shape[-1]
            return {}, {}, InputType.feed_forward(c)
        if input_type.kind == "recurrent":
            return {}, {}, InputType.feed_forward(input_type.shape[-1])
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        pt = self.pooling_type.upper()
        if mask is not None and x.ndim == 3:
            m = mask[..., None].astype(x.dtype)
            if pt == "MAX":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt in ("AVG", "AVERAGE"):
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            elif pt == "SUM":
                y = jnp.sum(x * m, axis=1)
            else:
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) ** p) * m, axis=1) ** (1.0 / p)
            return y, state
        if pt == "MAX":
            y = jnp.max(x, axis=axes)
        elif pt in ("AVG", "AVERAGE"):
            y = jnp.mean(x, axis=axes)
        elif pt == "SUM":
            y = jnp.sum(x, axis=axes)
        elif pt == "PNORM":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type}")
        return y, state


@dataclasses.dataclass(kw_only=True)
class Upsampling2DLayer(Layer):
    """Nearest-neighbour upsampling (reference `Upsampling2D`)."""

    size: Any = (2, 2)
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        sh, sw = _pair(self.size)
        return {}, {}, InputType.convolutional(h * sh, w * sw, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        y = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return y, state


@dataclasses.dataclass(kw_only=True)
class ZeroPaddingLayer(Layer):
    """Spatial zero padding (reference `ZeroPaddingLayer`).  `padding`
    accepts an int, a symmetric (ph, pw) pair, or per-side
    ((top, bottom), (left, right)) — the Keras ZeroPadding2D forms."""

    padding: Any = (1, 1)
    REGULARIZABLE: Tuple[str, ...] = ()

    def _sides(self):
        ph, pw = _pair(self.padding) if not (
            isinstance(self.padding, (tuple, list))
            and len(self.padding) == 2
            and isinstance(self.padding[0], (tuple, list))) else self.padding
        top, bot = _pair(ph)
        left, right = _pair(pw)
        return (int(top), int(bot)), (int(left), int(right))

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        (t, b), (le, r) = self._sides()
        return {}, {}, InputType.convolutional(h + t + b, w + le + r, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        (t, b), (le, r) = self._sides()
        return jnp.pad(x, ((0, 0), (t, b), (le, r), (0, 0))), state


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class BatchNormalizationLayer(Layer):
    """Batch normalization (reference `BatchNormalization` layer; running
    stats follow the reference's `decay` convention:
    running = decay * running + (1-decay) * batch)."""

    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    REGULARIZABLE: Tuple[str, ...] = ()
    HAS_STATE: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        c = input_type.shape[-1]
        params = {} if self.lock_gamma_beta else {
            "gamma": jnp.ones((c,), dtype), "beta": jnp.zeros((c,), dtype)}
        state = {"mean": jnp.zeros((c,), dtype), "var": jnp.ones((c,), dtype)}
        return params, state, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))
        # statistics in >= f32 even under a bf16 compute_dtype
        # (mixed-precision invariant: normalizer math accumulates f32;
        # f64 nets keep f64); running stats keep their stored dtype so
        # state shapes/dtypes are step-stable
        xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        if train:
            # one-pass moments (E[xs], E[xs^2]): both reductions read the
            # activation once and fuse into a single multi-output kernel —
            # jnp.var's centered form would re-read x after computing the
            # mean, doubling BN's HBM traffic (measured ~5ms/step of
            # reduce_sum on ResNet-50 b64 before this change).  Shifting by
            # the running mean keeps E[xs]^2 << E[xs^2] so the f32
            # subtraction doesn't cancel catastrophically on large-mean
            # activations (shifted-moments trick; the shift is a per-channel
            # constant that fuses into the same kernel).
            shift = state["mean"].astype(xf.dtype)
            xs = xf - shift
            m1 = jnp.mean(xs, axis=axes)
            mean = m1 + shift
            var = jnp.maximum(jnp.mean(xs * xs, axis=axes) - m1 * m1, 0.0)
            new_state = {
                "mean": (self.decay * state["mean"]
                         + (1 - self.decay) * mean.astype(state["mean"].dtype)),
                "var": (self.decay * state["var"]
                        + (1 - self.decay) * var.astype(state["var"].dtype)),
            }
        else:
            mean, var = (state["mean"].astype(jnp.float32),
                         state["var"].astype(jnp.float32))
            new_state = state
        y = ((xf - mean) / jnp.sqrt(var + self.eps)).astype(x.dtype)
        if not self.lock_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        return self.act_fn()(y), new_state


@dataclasses.dataclass(kw_only=True)
class LocalResponseNormalizationLayer(Layer):
    """LRN across channels (reference `LocalResponseNormalization`)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        half = self.n // 2
        sq = x * x
        # sum over a window of `n` adjacent channels (last axis, NHWC)
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        window = sum(
            lax.slice_in_dim(padded, i, i + x.shape[-1], axis=x.ndim - 1)
            for i in range(self.n)
        )
        return x / (self.k + self.alpha * window) ** self.beta, state


@dataclasses.dataclass(kw_only=True)
class LayerNormalizationLayer(Layer):
    """Layer norm over the feature axis (capability-exceeding addition used
    by the BERT/attention stack; the reference only has `LayerNorm` as a
    SameDiff op, `libnd4j .../generic/nn/layer_norm.cpp`)."""

    eps: float = 1e-5
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        c = input_type.shape[-1]
        return {"gamma": jnp.ones((c,), dtype), "beta": jnp.zeros((c,), dtype)}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # measured dispatch: Pallas fused LayerNorm on TPU, jnp otherwise
        from deeplearning4j_tpu.ops.norm_kernels import fused_layer_norm
        return fused_layer_norm(x, params["gamma"], params["beta"],
                                self.eps), state
