"""Recurrent layer zoo: LSTM / GravesLSTM / SimpleRnn / Bidirectional /
LastTimeStep / RnnOutputLayer / RnnLossLayer.

Reference: `deeplearning4j-nn/.../nn/conf/layers/{LSTM,GravesLSTM,SimpleRnn,
RnnOutputLayer,RnnLossLayer}.java`, `nn/conf/layers/recurrent/
{Bidirectional,LastTimeStep}.java`, and the implementations in
`nn/layers/recurrent/**` (`LSTMHelpers.java` holds the canonical cell math;
cuDNN dispatch via `LSTMHelper`).

TPU re-design (SURVEY.md §7 hard part (d)): the reference steps time in Java
with per-step op calls (or hands the whole sequence to cuDNN). Here the
input projection for ALL timesteps is ONE batched matmul `[B,T,F]@[F,4H]`
(tiled straight onto the MXU), and only the recurrent half runs under
`lax.scan` — XLA compiles the scan body once and keeps the carry in
registers/VMEM. Data layout is time-major-in-batch `[B, T, F]` (TPU-native
NWC), not the reference's NCW `[B, F, T]`; importers transpose at the
boundary.

Gate ordering follows the reference's `LSTMParamInitializer`: weights are
`[n_in, 4*n_out]` with gate blocks ordered **[input, forget, output, gate]**
(IFOG) — kept bit-identical so flat-param checkpoints round-trip.
Forget-gate bias init defaults to 1.0 (`forgetGateBiasInit`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.core import InputType, Layer
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.activations import get_activation


def _mask_bt(mask, x):
    """Broadcast a [B,T] mask against [B,T,H]."""
    if mask is None:
        return None
    m = jnp.asarray(mask)
    while m.ndim < x.ndim:
        m = m[..., None]
    return m


# ---------------------------------------------------------------------------
# Base recurrent
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class BaseRecurrentLayer(Layer):
    """Common config for recurrent layers (reference
    `BaseRecurrentLayer.java`): n_out units, sequence in/sequence out."""

    n_out: int = 0
    STOCHASTIC: bool = True

    def _in_size(self, input_type: InputType) -> int:
        if input_type.kind != "recurrent":
            raise ValueError(
                f"{type(self).__name__} needs recurrent input, got {input_type}")
        return int(input_type.shape[-1])

    def _out_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.shape[0])


# ---------------------------------------------------------------------------
# SimpleRnn
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} R + b) (reference
    `SimpleRnn.java` / `nn/layers/recurrent/SimpleRnn.java`)."""

    REGULARIZABLE: Tuple[str, ...] = ("W", "RW")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in = self._in_size(input_type)
        k1, k2 = jax.random.split(rng)
        params = {
            "W": init_weights(k1, (n_in, self.n_out), self.winit(), dtype),
            "RW": init_weights(k2, (self.n_out, self.n_out), self.winit(), dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }
        return params, {}, self._out_type(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        act = self.act_fn("tanh")
        xp = x @ params["W"] + params["b"]          # [B,T,H] one MXU matmul
        m = _mask_bt(mask, xp)

        def cell(h, inp):
            xt, mt = inp
            h_new = act(xt + h @ params["RW"])
            if mt is not None:
                h_new = jnp.where(mt, h_new, h)     # hold state at padded steps
            return h_new, h_new

        h0 = jnp.zeros((x.shape[0], self.n_out), xp.dtype)
        xs = (jnp.swapaxes(xp, 0, 1),
              None if m is None else jnp.swapaxes(m, 0, 1))
        _, hs = lax.scan(cell, h0, xs)
        out = jnp.swapaxes(hs, 0, 1)
        if m is not None:
            out = out * m.astype(out.dtype)
        return out, state


# ---------------------------------------------------------------------------
# LSTM
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class LSTM(BaseRecurrentLayer):
    """LSTM without peepholes (reference `LSTM.java`; cell math
    `LSTMHelpers.activateHelper`). IFOG gate blocks, forget bias 1.0."""

    forget_gate_bias_init: float = 1.0
    gate_activation: Any = "sigmoid"
    REGULARIZABLE: Tuple[str, ...] = ("W", "RW")
    PEEPHOLE: bool = False

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in, H = self._in_size(input_type), self.n_out
        k1, k2, k3 = jax.random.split(rng, 3)
        b = jnp.full((4 * H,), self.bias_init, dtype)
        # forget-gate block is the second quarter (IFOG)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        params = {
            "W": init_weights(k1, (n_in, 4 * H), self.winit(), dtype),
            "RW": init_weights(k2, (H, 4 * H), self.winit(), dtype),
            "b": b,
        }
        if self.PEEPHOLE:
            # Graves-style peepholes: one vector per i/f/o gate
            params["pW"] = init_weights(k3, (3, H), "UNIFORM", dtype)
        return params, {}, self._out_type(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        H = self.n_out
        act = self.act_fn("tanh")
        gate = get_activation(self.gate_activation)
        xp = x @ params["W"] + params["b"]          # [B,T,4H] one MXU matmul
        m = _mask_bt(mask, x[..., :1])
        peep = params.get("pW")

        def cell(carry, inp):
            h, c = carry
            xt, mt = inp
            z = xt + h @ params["RW"]
            zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
            if peep is not None:
                zi = zi + c * peep[0]
                zf = zf + c * peep[1]
            i, f, g = gate(zi), gate(zf), act(zg)
            c_new = f * c + i * g
            if peep is not None:
                zo = zo + c_new * peep[2]
            o = gate(zo)
            h_new = o * act(c_new)
            if mt is not None:
                h_new = jnp.where(mt, h_new, h)
                c_new = jnp.where(mt, c_new, c)
            return (h_new, c_new), h_new

        B = x.shape[0]
        h0 = jnp.zeros((B, H), xp.dtype)
        c0 = jnp.zeros((B, H), xp.dtype)
        xs = (jnp.swapaxes(xp, 0, 1),
              None if m is None else jnp.swapaxes(m, 0, 1))
        _, hs = lax.scan(cell, (h0, c0), xs)
        out = jnp.swapaxes(hs, 0, 1)
        if m is not None:
            out = out * m.astype(out.dtype)
        return out, state


@dataclasses.dataclass(kw_only=True)
class GRU(BaseRecurrentLayer):
    """GRU, keras `reset_after=True` form (r gates the already-linear
    recurrent term) — the same cell semantics as the registry `gru_cell`
    and ONNX `linear_before_reset=1`.  Gate blocks ordered (r, z, n);
    separate input/recurrent biases preserve exact keras numerics.
    (Upstream DL4J has no GRU layer — this exceeds the reference.)"""

    gate_activation: Any = "sigmoid"
    REGULARIZABLE: Tuple[str, ...] = ("W", "RW")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in, H = self._in_size(input_type), self.n_out
        k1, k2 = jax.random.split(rng)
        params = {
            "W": init_weights(k1, (n_in, 3 * H), self.winit(), dtype),
            "RW": init_weights(k2, (H, 3 * H), self.winit(), dtype),
            "b": jnp.full((3 * H,), self.bias_init, dtype),
            "rb": jnp.zeros((3 * H,), dtype),
        }
        return params, {}, self._out_type(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        H = self.n_out
        act = self.act_fn("tanh")
        gate = get_activation(self.gate_activation)
        xp = x @ params["W"] + params["b"]          # [B,T,3H] one matmul
        m = _mask_bt(mask, x[..., :1])

        def cell(h, inp):
            xt, mt = inp
            gh = h @ params["RW"] + params["rb"]
            r = gate(xt[..., :H] + gh[..., :H])
            z = gate(xt[..., H:2 * H] + gh[..., H:2 * H])
            n = act(xt[..., 2 * H:] + r * gh[..., 2 * H:])
            h_new = (1 - z) * n + z * h
            if mt is not None:
                h_new = jnp.where(mt, h_new, h)
            return h_new, h_new

        h0 = jnp.zeros((x.shape[0], H), xp.dtype)
        xs = (jnp.swapaxes(xp, 0, 1),
              None if m is None else jnp.swapaxes(m, 0, 1))
        _, hs = lax.scan(cell, h0, xs)
        out = jnp.swapaxes(hs, 0, 1)
        if m is not None:
            out = out * m.astype(out.dtype)
        return out, state


@dataclasses.dataclass(kw_only=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference `GravesLSTM.java`, Graves
    2013 formulation)."""

    PEEPHOLE: bool = True
    # peephole vectors are weights (packed with recurrent weights in the
    # reference's param layout) — regularized alongside W/RW
    REGULARIZABLE: Tuple[str, ...] = ("W", "RW", "pW")


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class Bidirectional(Layer):
    """Runs an inner recurrent layer forward + backward over time and merges
    (reference `nn/conf/layers/recurrent/Bidirectional.java`; modes ADD,
    MUL, AVERAGE, CONCAT).

    ``return_last=True`` gives Keras `Bidirectional(return_sequences=False)`
    semantics: merge(fwd output at the LAST step, bwd output after
    consuming the WHOLE sequence — i.e. at original position 0), as a
    feed-forward activation.  (A plain `LastTimeStep` wrapper would wrongly
    take the bwd output at t=T-1, where it has seen one element.)"""

    fwd: Optional[Layer] = None
    mode: str = "CONCAT"
    return_last: bool = False
    REGULARIZABLE: Tuple[str, ...] = ()
    STOCHASTIC: bool = True

    def __post_init__(self):
        if self.fwd is None:
            raise ValueError("Bidirectional requires an inner layer (fwd=...)")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        import copy
        self._bwd = copy.deepcopy(self.fwd)
        k1, k2 = jax.random.split(rng)
        if self.fwd.weight_init is None:
            self.fwd.weight_init = self.weight_init
        if self._bwd.weight_init is None:
            self._bwd.weight_init = self.weight_init
        pf, sf, of = self.fwd.initialize(k1, input_type, dtype)
        pb, sb, _ = self._bwd.initialize(k2, input_type, dtype)
        n_out = (2 * of.shape[-1] if self.mode == "CONCAT"
                 else of.shape[-1])
        if self.return_last:
            out = InputType.feed_forward(n_out)
        else:
            out = InputType.recurrent(n_out, of.shape[0])
        return {"fwd": pf, "bwd": pb}, {"fwd": sf, "bwd": sb}, out

    def regularizable_mask(self, params):
        inner = self.fwd.regularizable_mask
        return {"fwd": inner(params["fwd"]), "bwd": inner(params["bwd"])}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        r0 = r1 = r2 = None
        if rng is not None:
            r0, r1, r2 = jax.random.split(rng, 3)
        x = self.maybe_input_dropout(x, train, r0)
        yf, sf = self.fwd.apply(params["fwd"], state["fwd"], x, train=train,
                                rng=r1, mask=mask)
        # reverse time, run, reverse back; mask stays aligned by flipping too
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(jnp.asarray(mask), axis=1)
        yb, sb = self._bwd.apply(params["bwd"], state["bwd"], xr, train=train,
                                 rng=r2, mask=mr)
        yb = jnp.flip(yb, axis=1)
        if self.return_last:
            # fwd: last (valid) step; bwd: full-consumption output, which
            # after flipping back sits at original position 0
            if mask is None:
                yf = yf[:, -1]
                yb = yb[:, 0]
            else:
                m = jnp.asarray(mask)
                T = m.shape[1]
                idx = (T - 1 - jnp.argmax(jnp.flip(m, axis=1), axis=1)
                       .astype(jnp.int32))
                yf = jnp.take_along_axis(yf, idx[:, None, None],
                                         axis=1)[:, 0]
                yb = yb[:, 0]
                # an all-padding row has no valid step: emit zeros, not
                # the garbage at the argmax fallback index
                valid = jnp.any(m > 0, axis=1)[:, None]
                yf = jnp.where(valid, yf, 0.0)
                yb = jnp.where(valid, yb, 0.0)
        if self.mode == "CONCAT":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "ADD":
            y = yf + yb
        elif self.mode == "MUL":
            y = yf * yb
        elif self.mode == "AVERAGE":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"Unknown Bidirectional mode {self.mode}")
        return y, {"fwd": sf, "bwd": sb}


@dataclasses.dataclass(kw_only=True)
class LastTimeStep(Layer):
    """Wraps a recurrent layer, returning only the last (valid) timestep as
    a feed-forward activation (reference `recurrent/LastTimeStep.java` +
    `LastTimeStepVertex`): with a mask, picks the last unmasked step per
    example."""

    underlying: Optional[Layer] = None
    REGULARIZABLE: Tuple[str, ...] = ()
    STOCHASTIC: bool = True

    def __post_init__(self):
        if self.underlying is None:
            raise ValueError("LastTimeStep requires underlying=...")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if self.underlying.weight_init is None:
            self.underlying.weight_init = self.weight_init
        p, s, ot = self.underlying.initialize(rng, input_type, dtype)
        return p, s, InputType.feed_forward(ot.shape[-1])

    def regularizable_mask(self, params):
        return self.underlying.regularizable_mask(params)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        r0 = None
        if rng is not None:
            r0, rng = jax.random.split(rng)
        x = self.maybe_input_dropout(x, train, r0)
        y, s = self.underlying.apply(params, state, x, train=train, rng=rng,
                                     mask=mask)
        if mask is None:
            return y[:, -1, :], s
        # last NONZERO mask index (reference TimeSeriesUtils.pullLastTimeSteps
        # semantics — robust to non-contiguous masks)
        m = jnp.asarray(mask)
        T = m.shape[1]
        idx = T - 1 - jnp.argmax(jnp.flip(m, axis=1), axis=1).astype(jnp.int32)
        return jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0, :], s


# ---------------------------------------------------------------------------
# Recurrent output heads
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class RnnOutputLayer(Layer):
    """Time-distributed dense + per-step loss (reference
    `RnnOutputLayer.java`): labels `[B,T,C]`, optional label mask `[B,T]`
    excludes padded steps from the loss mean — same normalization as the
    reference's `LossFunction.computeScore` with mask."""

    n_out: int = 0
    loss: Any = "mcxent"
    has_bias: bool = True
    STOCHASTIC: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in = input_type.shape[-1]
        params = {"W": init_weights(rng, (n_in, self.n_out), self.winit(), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}, InputType.recurrent(self.n_out, input_type.shape[0])

    def _pre(self, params, x):
        y = x @ params["W"]
        return y + params["b"] if self.has_bias else y

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        return self.act_fn("softmax")(self._pre(params, x)), state

    def compute_loss(self, params, state, x, labels, *, train=True, rng=None,
                     mask=None):
        from deeplearning4j_tpu.ops.losses import apply_loss
        x = self.maybe_input_dropout(x, train, rng)
        # losses handle [B,T,C] outputs + [B,T] masks natively
        return apply_loss(self.loss, self.act_fn("softmax"),
                          self._pre(params, x), jnp.asarray(labels),
                          None if mask is None else jnp.asarray(mask))


@dataclasses.dataclass(kw_only=True)
class RnnLossLayer(Layer):
    """Parameter-free per-step loss head (reference `RnnLossLayer.java`)."""

    loss: Any = "mcxent"
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.act_fn()(x), state

    def compute_loss(self, params, state, x, labels, *, train=True, rng=None,
                     mask=None):
        from deeplearning4j_tpu.ops.losses import apply_loss
        return apply_loss(self.loss, self.act_fn(), x, jnp.asarray(labels),
                          None if mask is None else jnp.asarray(mask))
