"""Object detection: YOLOv2 output layer + box utilities.

Reference: `deeplearning4j-nn/.../layers/objdetect/{Yolo2OutputLayer,
YoloUtils}.java` and `conf/layers/objdetect/Yolo2OutputLayer.java` —
anchor-based single-shot detection loss (Redmon & Farhadi 2016) plus
decode/NMS helpers; `conf/layers/SpaceToDepthLayer.java` is the passthrough
reorg used by full YOLOv2.

TPU design notes: the loss is pure elementwise/reduction math over the
[B, H, W, A, 5+C] head tensor — one fused XLA kernel, no per-box host
loop (the reference iterates boxes on the JVM to build its mask tensors;
here masks arrive rasterized in the label tensor).  Decode is jittable;
NMS runs host-side on the few boxes that survive confidence filtering, as
the reference's `YoloUtils.getPredictedObjects` does.

Label format (documented contract, simpler than the reference's
[mb, 4+C, H, W] rasterized boxes but equivalent in content):
`[B, H, W, A, 5 + C]` per anchor slot —
  [0:2] tx, ty   target center offsets within the cell, in (0, 1)
  [2:4] tw, th   log-space size targets: log(box / anchor)
  [4]   objectness indicator (1 where a box is assigned to this anchor)
  [5:]  one-hot class
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.core import InputType, Layer


@dataclasses.dataclass(kw_only=True)
class SpaceToDepthLayer(Layer):
    """[B,H,W,C] -> [B,H/b,W/b,C*b*b] (reference `SpaceToDepthLayer`; the
    YOLOv2 passthrough/reorg)."""

    block_size: int = 2
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        b = self.block_size
        if h % b or w % b:
            raise ValueError(f"SpaceToDepth: {h}x{w} not divisible by {b}")
        return {}, {}, InputType.convolutional(h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.autodiff.ops import _space_to_depth
        return _space_to_depth(x, self.block_size), state


@dataclasses.dataclass(kw_only=True)
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection head loss (reference objdetect `Yolo2OutputLayer`).

    Consumes the conv head's raw [B, H, W, A*(5+C)] activations and the
    rasterized label tensor (module docstring).  Loss terms follow the
    paper/reference: lambda_coord * coord MSE (xy after sigmoid, wh in log
    space), objectness MSE split by lambda_noobj, and per-assigned-anchor
    class cross-entropy."""

    anchors: Sequence[Tuple[float, float]] = ((1.0, 1.0),)
    n_classes: int = 1
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        need = len(self.anchors) * (5 + self.n_classes)
        if c != need:
            raise ValueError(
                f"Yolo2OutputLayer expects {need} channels "
                f"({len(self.anchors)} anchors x (5+{self.n_classes})), "
                f"got {c}")
        return {}, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x, state        # raw head; decode via YoloUtils

    def _split(self, x):
        B, H, W, _ = x.shape
        A = len(self.anchors)
        p = x.reshape(B, H, W, A, 5 + self.n_classes)
        return (jax.nn.sigmoid(p[..., 0:2]), p[..., 2:4],
                jax.nn.sigmoid(p[..., 4]), p[..., 5:])

    def compute_loss(self, params, state, x, labels, *, train=True,
                     rng=None, mask=None):
        x = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        labels = labels.reshape(x.shape[0], x.shape[1], x.shape[2],
                                len(self.anchors), 5 + self.n_classes)
        pxy, pwh, pobj, plogits = self._split(x)
        lxy = labels[..., 0:2]
        lwh = labels[..., 2:4]
        lobj = labels[..., 4]
        lcls = labels[..., 5:]
        B = x.shape[0]

        coord = jnp.sum(lobj[..., None] * ((pxy - lxy) ** 2
                                           + (pwh - lwh) ** 2))
        obj = jnp.sum(lobj * (pobj - 1.0) ** 2) \
            + self.lambda_noobj * jnp.sum((1.0 - lobj) * pobj ** 2)
        logp = jax.nn.log_softmax(plogits, axis=-1)
        cls = -jnp.sum(lobj * jnp.sum(lcls * logp, axis=-1))
        return (self.lambda_coord * coord + obj + cls) / B


class DetectedObject:
    """One decoded detection (reference `DetectedObject`)."""

    def __init__(self, center_x, center_y, width, height, cls, confidence):
        self.center_x = float(center_x)
        self.center_y = float(center_y)
        self.width = float(width)
        self.height = float(height)
        self.predicted_class = int(cls)
        self.confidence = float(confidence)

    def box(self):
        return (self.center_x - self.width / 2,
                self.center_y - self.height / 2,
                self.center_x + self.width / 2,
                self.center_y + self.height / 2)

    def __repr__(self):
        return (f"DetectedObject(cls={self.predicted_class}, "
                f"conf={self.confidence:.3f}, cx={self.center_x:.2f}, "
                f"cy={self.center_y:.2f})")


class YoloUtils:
    """Decode + NMS (reference `YoloUtils`)."""

    @staticmethod
    def decode(head: jnp.ndarray, anchors, n_classes: int):
        """Raw head [B,H,W,A*(5+C)] -> (boxes [B,H,W,A,4] in grid units
        (cx, cy, w, h), confidence [B,H,W,A], class probs [B,H,W,A,C]).
        Jittable."""
        B, H, W, _ = head.shape
        A = len(anchors)
        p = head.reshape(B, H, W, A, 5 + n_classes)
        cy, cx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
        grid = jnp.stack([cx, cy], axis=-1)[None, :, :, None, :]
        anc = jnp.asarray(anchors, jnp.float32)[None, None, None, :, :]
        xy = jax.nn.sigmoid(p[..., 0:2]) + grid
        wh = anc * jnp.exp(p[..., 2:4])
        conf = jax.nn.sigmoid(p[..., 4])
        probs = jax.nn.softmax(p[..., 5:], axis=-1)
        return jnp.concatenate([xy, wh], axis=-1), conf, probs

    @staticmethod
    def iou(a, b) -> float:
        ax1, ay1, ax2, ay2 = a
        bx1, by1, bx2, by2 = b
        iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
        ih = max(0.0, min(ay2, by2) - max(ay1, by1))
        inter = iw * ih
        ua = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
        return inter / ua if ua > 0 else 0.0

    @staticmethod
    def get_predicted_objects(head, anchors, n_classes,
                              conf_threshold: float = 0.5,
                              nms_threshold: float = 0.4
                              ) -> List[List[DetectedObject]]:
        """Confidence-filter, then per-class greedy NMS on the host (the
        device work — decode — stays jitted)."""
        boxes, conf, probs = YoloUtils.decode(jnp.asarray(head), anchors,
                                              n_classes)
        boxes = np.asarray(boxes)
        conf = np.asarray(conf)
        probs = np.asarray(probs)
        out: List[List[DetectedObject]] = []
        for bi in range(boxes.shape[0]):
            cand: List[DetectedObject] = []
            sel = np.argwhere(conf[bi] > conf_threshold)
            for (y, x, a) in sel:
                cx, cy, w, h = boxes[bi, y, x, a]
                cls = int(np.argmax(probs[bi, y, x, a]))
                cand.append(DetectedObject(
                    cx, cy, w, h, cls,
                    conf[bi, y, x, a] * probs[bi, y, x, a, cls]))
            cand.sort(key=lambda d: -d.confidence)
            kept: List[DetectedObject] = []
            for d in cand:
                if all(d.predicted_class != k.predicted_class
                       or YoloUtils.iou(d.box(), k.box()) < nms_threshold
                       for k in kept):
                    kept.append(d)
            out.append(kept)
        return out
