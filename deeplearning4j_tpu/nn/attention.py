"""Attention layers + fused scaled-dot-product attention op.

Reference: `deeplearning4j-nn/.../nn/conf/layers/{SelfAttentionLayer,
LearnedSelfAttentionLayer,RecurrentAttentionLayer}.java` (implemented there
as SameDiff layers over the `dotProductAttention` /
`multiHeadDotProductAttention` declarable ops,
`libnd4j/include/ops/declarable/generic/nn/dot_product_attention.cpp`).

TPU re-design: attention is expressed so XLA fuses QK^T → scale/mask →
softmax → V into an MXU-friendly chain; the long-context path (blockwise /
ring attention) lives in `parallel/ring_attention.py` (SURVEY.md §5.7 —
capability-exceeding addition, the reference has no long-context story).
Layout is `[B, T, F]` with heads split internally to `[B, heads, T, dh]`.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.core import InputType, Layer
from deeplearning4j_tpu.ops.initializers import init_weights


def dot_product_attention(q, k, v, mask=None, scaled: bool = True,
                          dropout_rate: float = 0.0, rng=None):
    """Fused scaled dot-product attention (the `dotProductAttention` op).

    q: [..., Tq, dh], k/v: [..., Tk, dh]; mask: broadcastable to
    [..., Tq, Tk] (1 = keep). Returns [..., Tq, dh].
    """
    dh = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(dh, scores.dtype))
    if mask is not None:
        big_neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
        scores = jnp.where(mask.astype(bool), scores, big_neg)
    w = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", w, v)


def multi_head_attention(x_q, x_kv, params, n_heads, mask=None, rng=None,
                         dropout_rate: float = 0.0):
    """Multi-head attention with packed projections
    (`multiHeadDotProductAttention`): params holds Wq/Wk/Wv `[F, H*dh]` and
    Wo `[H*dv, F_out]`."""
    B, Tq, _ = x_q.shape
    Tk = x_kv.shape[1]

    def split(y):
        return y.reshape(B, -1, n_heads, y.shape[-1] // n_heads).transpose(0, 2, 1, 3)

    q = split(x_q @ params["Wq"])
    k = split(x_kv @ params["Wk"])
    v = split(x_kv @ params["Wv"])
    if dropout_rate > 0.0 and rng is not None:
        # attention-weight dropout needs the materialized probabilities —
        # naive path only (train-time memory, matching the reference)
        o = dot_product_attention(q, k, v,
                                  mask=None if mask is None
                                  else jnp.asarray(mask)[:, None, None, :],
                                  dropout_rate=dropout_rate, rng=rng)
    else:
        # flash/blockwise path: O(T) memory, Pallas kernel on TPU for
        # cleanly tiling shapes (ops/attention_kernels.py)
        from deeplearning4j_tpu.ops.attention_kernels import fused_attention
        o = fused_attention(q, k, v,
                            mask=None if mask is None else jnp.asarray(mask))
    o = o.transpose(0, 2, 1, 3).reshape(B, Tq, -1)
    return o @ params["Wo"]


@dataclasses.dataclass(kw_only=True)
class SelfAttentionLayer(Layer):
    """Multi-head self-attention over a sequence (reference
    `SelfAttentionLayer.java`): queries = keys = values = input. With
    `project_input=True` uses learned Q/K/V/O projections."""

    n_out: int = 0          # output size (projected); 0 = n_in
    n_heads: int = 1
    head_size: int = 0      # 0 = n_out / n_heads
    project_input: bool = True
    REGULARIZABLE: Tuple[str, ...] = ("Wq", "Wk", "Wv", "Wo")
    STOCHASTIC: bool = True

    def _sizes(self, n_in):
        n_out = self.n_out or n_in
        dh = self.head_size or (n_out // self.n_heads)
        return n_out, dh

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in = int(input_type.shape[-1])
        n_out, dh = self._sizes(n_in)
        if not self.project_input:
            if self.n_heads != 1:
                raise ValueError("project_input=False requires n_heads=1")
            return {}, {}, InputType.recurrent(n_in, input_type.shape[0])
        ks = jax.random.split(rng, 4)
        H = self.n_heads
        params = {
            "Wq": init_weights(ks[0], (n_in, H * dh), self.winit(), dtype),
            "Wk": init_weights(ks[1], (n_in, H * dh), self.winit(), dtype),
            "Wv": init_weights(ks[2], (n_in, H * dh), self.winit(), dtype),
            "Wo": init_weights(ks[3], (H * dh, n_out), self.winit(), dtype),
        }
        return params, {}, InputType.recurrent(n_out, input_type.shape[0])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        if not self.project_input:
            m = None if mask is None else jnp.asarray(mask)[:, None, :]
            return dot_product_attention(x, x, x, mask=m), state
        y = multi_head_attention(x, x, params, self.n_heads, mask=mask)
        if mask is not None:
            y = y * jnp.asarray(mask)[..., None].astype(y.dtype)
        return y, state


@dataclasses.dataclass(kw_only=True)
class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """Attention with `n_queries` LEARNED query vectors (reference
    `LearnedSelfAttentionLayer.java`) — output is a fixed-length sequence
    `[B, n_queries, n_out]` regardless of input length."""

    n_queries: int = 1
    # learned queries are a weight matrix: regularized like the projections
    REGULARIZABLE: Tuple[str, ...] = ("Wq", "Wk", "Wv", "Wo", "Q")

    def initialize(self, rng, input_type, dtype=jnp.float32):
        if not self.project_input:
            raise ValueError(
                "LearnedSelfAttentionLayer requires project_input=True "
                "(learned queries only exist alongside Q/K/V projections)")
        n_in = int(input_type.shape[-1])
        n_out, dh = self._sizes(n_in)
        kq, rest = jax.random.split(rng)
        params, state, _ = super().initialize(rest, input_type, dtype)
        params["Q"] = init_weights(kq, (self.n_queries, n_in), self.winit(), dtype)
        return params, state, InputType.recurrent(n_out, self.n_queries)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        q = jnp.broadcast_to(params["Q"], (x.shape[0],) + params["Q"].shape)
        y = multi_head_attention(q, x, params, self.n_heads, mask=mask)
        return y, state


@dataclasses.dataclass(kw_only=True)
class RecurrentAttentionLayer(Layer):
    """Recurrent cell with attention over the full input sequence at each
    step (reference `RecurrentAttentionLayer.java`): h_t = act(x_t W +
    h_{t-1} RW + attn(h_{t-1}, x) + b)."""

    n_out: int = 0
    n_heads: int = 1
    REGULARIZABLE: Tuple[str, ...] = ("W", "RW", "Wq", "Wk", "Wv", "Wo")
    STOCHASTIC: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in = int(input_type.shape[-1])
        H = self.n_out
        ks = jax.random.split(rng, 7)
        params = {
            "W": init_weights(ks[0], (n_in, H), self.winit(), dtype),
            "RW": init_weights(ks[1], (H, H), self.winit(), dtype),
            "b": jnp.full((H,), self.bias_init, dtype),
            "Wq": init_weights(ks[2], (H, H), self.winit(), dtype),
            "Wk": init_weights(ks[3], (n_in, H), self.winit(), dtype),
            "Wv": init_weights(ks[4], (n_in, H), self.winit(), dtype),
            "Wo": init_weights(ks[5], (H, H), self.winit(), dtype),
        }
        return params, {}, InputType.recurrent(H, input_type.shape[0])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from jax import lax
        x = self.maybe_input_dropout(x, train, rng)
        act = self.act_fn("tanh")
        xp = x @ params["W"] + params["b"]               # [B,T,H]
        keys = x @ params["Wk"]                          # [B,T,H]
        vals = x @ params["Wv"]
        kmask = None if mask is None else jnp.asarray(mask)[:, None, :]

        def cell(h, xt):
            q = (h @ params["Wq"])[:, None, :]           # [B,1,H]
            a = dot_product_attention(q, keys, vals, mask=kmask)[:, 0, :]
            h_new = act(xt + h @ params["RW"] + a @ params["Wo"])
            return h_new, h_new

        h0 = jnp.zeros((x.shape[0], self.n_out), xp.dtype)
        _, hs = lax.scan(cell, h0, jnp.swapaxes(xp, 0, 1))
        out = jnp.swapaxes(hs, 0, 1)
        if mask is not None:
            out = out * jnp.asarray(mask)[..., None].astype(out.dtype)
        return out, state
