"""Extended layer zoo: 3-D convolution/pooling, cropping, locally-connected,
PReLU, center-loss head.

Reference configs under `deeplearning4j-nn/.../nn/conf/layers/`:
`Convolution3D`, `Deconvolution3D`, `Subsampling1DLayer`,
`Subsampling3DLayer`, `Cropping1D/2D/3D`, `LocallyConnected1D/2D`,
`PReLULayer`, `CenterLossOutputLayer` (the FaceNet head in
`InceptionResNetV1.java`).

TPU notes: 3-D convs run NDHWC/DHWIO through `lax.conv_general_dilated`
(XLA tiles the contraction onto the MXU exactly as 2-D); locally-connected
layers extract patches once and contract with an unshared [spatial, patch,
out] kernel in a single einsum — no per-position loop.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.core import InputType, Layer
from deeplearning4j_tpu.ops.initializers import init_weights


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1]), int(v[2])
    return (int(v),) * 3


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _out_dim(size, k, s, p, same: bool):
    if size is None:
        return None
    if same:
        return -(-size // s)
    return (size + 2 * p - k) // s + 1


@dataclasses.dataclass(kw_only=True)
class Convolution3DLayer(Layer):
    """3-D convolution over [B, D, H, W, C] (reference `Convolution3D`;
    data_format NDHWC)."""

    n_out: int = 0
    kernel_size: Any = (3, 3, 3)
    stride: Any = (1, 1, 1)
    padding: Any = (0, 0, 0)
    dilation: Any = (1, 1, 1)
    convolution_mode: str = "Truncate"
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        d, h, w, c = input_type.shape
        kd, kh, kw = _triple(self.kernel_size)
        params = {"W": init_weights(rng, (kd, kh, kw, c, self.n_out),
                                    self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        same = self.convolution_mode.lower() == "same"
        sd, sh, sw = _triple(self.stride)
        pd, ph, pw = _triple(self.padding)
        out = InputType.convolutional3d(
            _out_dim(d, kd, sd, pd, same), _out_dim(h, kh, sh, ph, same),
            _out_dim(w, kw, sw, pw, same), self.n_out)
        return params, {}, out

    def _padding_arg(self):
        if self.convolution_mode.lower() == "same":
            return "SAME"
        return [(p, p) for p in _triple(self.padding)]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=_triple(self.stride),
            padding=self._padding_arg(), rhs_dilation=_triple(self.dilation),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class Deconvolution3DLayer(Layer):
    """3-D transpose convolution (reference `Deconvolution3D`)."""

    n_out: int = 0
    kernel_size: Any = (2, 2, 2)
    stride: Any = (2, 2, 2)
    convolution_mode: str = "Truncate"
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        d, h, w, c = input_type.shape
        kd, kh, kw = _triple(self.kernel_size)
        params = {"W": init_weights(rng, (kd, kh, kw, c, self.n_out),
                                    self.winit("RELU"), dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        sd, sh, sw = _triple(self.stride)
        same = self.convolution_mode.lower() == "same"

        def up(size, k, s):
            if size is None:
                return None
            return size * s if same else (size - 1) * s + k
        out = InputType.convolutional3d(up(d, kd, sd), up(h, kh, sh),
                                        up(w, kw, sw), self.n_out)
        return params, {}, out

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        pad = "SAME" if self.convolution_mode.lower() == "same" else "VALID"
        # gradient-form transposed conv — flip the kernel for
        # lax.conv_transpose (see Deconvolution2DLayer.apply)
        y = lax.conv_transpose(
            x, jnp.flip(params["W"], (0, 1, 2)),
            strides=_triple(self.stride), padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


def _pool_nd(x, kind, window, strides, padding):
    if kind.upper() == "MAX":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    s = lax.reduce_window(x, jnp.zeros((), x.dtype), lax.add, window,
                          strides, padding)
    if padding == "VALID":
        denom = 1
        for w in window:
            denom *= w
        return s / denom
    # SAME: divide by the count of VALID elements per window so padded edge
    # windows aren't underscaled (matches the 2-D SubsamplingLayer)
    cnt = lax.reduce_window(jnp.ones_like(x), jnp.zeros((), x.dtype),
                            lax.add, window, strides, padding)
    return s / cnt


@dataclasses.dataclass(kw_only=True)
class Subsampling1DLayer(Layer):
    """1-D pooling over [B, T, F] (reference `Subsampling1DLayer`)."""

    pooling_type: str = "MAX"
    kernel_size: int = 2
    stride: int = 2
    convolution_mode: str = "Truncate"
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        t, f = input_type.shape
        same = self.convolution_mode.lower() == "same"
        t = _out_dim(t, int(self.kernel_size), int(self.stride), 0, same)
        return {}, {}, InputType.recurrent(f, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        pad = "SAME" if self.convolution_mode.lower() == "same" else "VALID"
        y = _pool_nd(x, self.pooling_type,
                     (1, int(self.kernel_size), 1),
                     (1, int(self.stride), 1), pad)
        return y, state


@dataclasses.dataclass(kw_only=True)
class Subsampling3DLayer(Layer):
    """3-D pooling over [B, D, H, W, C] (reference `Subsampling3DLayer`)."""

    pooling_type: str = "MAX"
    kernel_size: Any = (2, 2, 2)
    stride: Any = (2, 2, 2)
    convolution_mode: str = "Truncate"
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        d, h, w, c = input_type.shape
        kd, kh, kw = _triple(self.kernel_size)
        sd, sh, sw = _triple(self.stride)
        same = self.convolution_mode.lower() == "same"
        return {}, {}, InputType.convolutional3d(
            _out_dim(d, kd, sd, 0, same), _out_dim(h, kh, sh, 0, same),
            _out_dim(w, kw, sw, 0, same), c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        pad = "SAME" if self.convolution_mode.lower() == "same" else "VALID"
        y = _pool_nd(x, self.pooling_type,
                     (1,) + _triple(self.kernel_size) + (1,),
                     (1,) + _triple(self.stride) + (1,), pad)
        return y, state


@dataclasses.dataclass(kw_only=True)
class Cropping1DLayer(Layer):
    """Crop timesteps: [B, T, F] -> [B, T-top-bottom, F] (reference
    `Cropping1D`)."""

    cropping: Any = (0, 0)
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        t, f = input_type.shape
        a, b = _pair(self.cropping)
        return {}, {}, InputType.recurrent(
            f, None if t is None else t - a - b)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        a, b = _pair(self.cropping)
        return x[:, a: x.shape[1] - b], state


@dataclasses.dataclass(kw_only=True)
class Cropping2DLayer(Layer):
    """Crop H/W (reference `Cropping2D`): cropping = (top, bottom, left,
    right) or a single symmetric value."""

    cropping: Any = (0, 0, 0, 0)
    REGULARIZABLE: Tuple[str, ...] = ()

    def _crops(self):
        c = self.cropping
        if isinstance(c, int):
            return c, c, c, c
        if len(c) == 2:
            return c[0], c[0], c[1], c[1]
        return tuple(int(v) for v in c)

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, ch = input_type.shape
        t, b, l, r = self._crops()
        return {}, {}, InputType.convolutional(h - t - b, w - l - r, ch)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._crops()
        return x[:, t: x.shape[1] - b, l: x.shape[2] - r], state


@dataclasses.dataclass(kw_only=True)
class Cropping3DLayer(Layer):
    """Crop D/H/W (reference `Cropping3D`): (d0, d1, h0, h1, w0, w1)."""

    cropping: Any = (0, 0, 0, 0, 0, 0)
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        d, h, w, c = input_type.shape
        d0, d1, h0, h1, w0, w1 = (int(v) for v in self.cropping)
        return {}, {}, InputType.convolutional3d(d - d0 - d1, h - h0 - h1,
                                                 w - w0 - w1, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        d0, d1, h0, h1, w0, w1 = (int(v) for v in self.cropping)
        return x[:, d0: x.shape[1] - d1, h0: x.shape[2] - h1,
                 w0: x.shape[3] - w1], state


@dataclasses.dataclass(kw_only=True)
class LocallyConnected2DLayer(Layer):
    """Unshared-weight 2-D conv (reference `LocallyConnected2D`): one
    kernel PER output position.  Patches extracted once, contracted with a
    [OH, OW, KH*KW*C, n_out] kernel in a single einsum."""

    n_out: int = 0
    kernel_size: Any = (3, 3)
    stride: Any = (1, 1)
    has_bias: bool = True

    def _out_hw(self, h, w):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel_size)
        oh, ow = self._out_hw(h, w)
        params = {"W": init_weights(rng, (oh, ow, kh * kw * c, self.n_out),
                                    self.winit("RELU"), dtype)
                  / jnp.sqrt(1.0 * kh * kw)}
        if self.has_bias:
            params["b"] = jnp.full((oh, ow, self.n_out), self.bias_init,
                                   dtype)
        return params, {}, InputType.convolutional(oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        kh, kw = _pair(self.kernel_size)
        patches = lax.conv_general_dilated_patches(
            x, (kh, kw), _pair(self.stride), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.einsum("bhwp,hwpo->bhwo", patches, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class LocallyConnected1DLayer(Layer):
    """Unshared-weight 1-D conv over [B, T, F] (reference
    `LocallyConnected1D`)."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    has_bias: bool = True

    def initialize(self, rng, input_type, dtype=jnp.float32):
        t, f = input_type.shape
        k, s = int(self.kernel_size), int(self.stride)
        if t is None:
            raise ValueError("LocallyConnected1D needs a static sequence "
                             "length (unshared weights are per-position)")
        ot = (t - k) // s + 1
        params = {"W": init_weights(rng, (ot, k * f, self.n_out),
                                    self.winit("RELU"), dtype)
                  / jnp.sqrt(1.0 * k)}
        if self.has_bias:
            params["b"] = jnp.full((ot, self.n_out), self.bias_init, dtype)
        return params, {}, InputType.recurrent(self.n_out, ot)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        k, s = int(self.kernel_size), int(self.stride)
        patches = lax.conv_general_dilated_patches(
            x, (k,), (s,), "VALID", dimension_numbers=("NWC", "WIO", "NWC"))
        y = jnp.einsum("btp,tpo->bto", patches, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state


@dataclasses.dataclass(kw_only=True)
class PReLULayer(Layer):
    """Parametric ReLU with a learnable per-feature slope (reference
    `PReLULayer`)."""

    alpha_init: float = 0.25
    shared_axes: Optional[Tuple[int, ...]] = None
    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        # dynamic (None) dims share their slope — broadcastable size 1
        shape = [1 if s is None else s for s in input_type.shape]
        if self.shared_axes:
            for ax in self.shared_axes:      # 1-based over non-batch dims
                shape[ax - 1] = 1
        params = {"alpha": jnp.full(tuple(shape), self.alpha_init, dtype)}
        return params, {}, input_type

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.where(x >= 0, x, params["alpha"] * x), state


@dataclasses.dataclass(kw_only=True)
class CenterLossOutputLayer(Layer):
    """Softmax + center loss head (reference `CenterLossOutputLayer`, the
    InceptionResNetV1/FaceNet pairing; Wen et al. 2016).

    loss = CE(softmax(xW+b), y) + lambda/2 * mean ||f - c_y||^2.

    The class centers are a parameter driven by the SAME gradient step
    (d/dc of the center term = lambda*(c_y - f) per assigned sample) — the
    alpha-EMA of the reference collapses into the updater's learning rate,
    trading its separate schedule for one fused XLA step."""

    n_out: int = 0
    alpha: float = 0.05            # kept for config parity (see docstring)
    lambda_: float = 0.5
    gradient_check: bool = False
    REGULARIZABLE: Tuple[str, ...] = ("W",)

    def initialize(self, rng, input_type, dtype=jnp.float32):
        f = input_type.shape[-1]
        k1, _ = jax.random.split(rng)
        params = {"W": init_weights(k1, (f, self.n_out),
                                    self.winit("XAVIER"), dtype),
                  "b": jnp.zeros((self.n_out,), dtype),
                  "centers": jnp.zeros((self.n_out, f), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        return jax.nn.softmax(x @ params["W"] + params["b"], axis=-1), state

    def compute_loss(self, params, state, x, labels, *, train=True,
                     rng=None, mask=None):
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        f32 = jnp.promote_types(x.dtype, jnp.float32)
        feats = x.astype(f32)
        logits = feats @ params["W"].astype(f32) + params["b"].astype(f32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.mean(jnp.sum(labels * logp, axis=-1))
        assigned = labels @ params["centers"].astype(f32)   # c_{y_i}
        center = 0.5 * jnp.mean(jnp.sum((feats - assigned) ** 2, axis=-1))
        return ce + self.lambda_ * center
