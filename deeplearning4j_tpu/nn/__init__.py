"""NN layer-config API (DL4J-nn equivalent)."""
from deeplearning4j_tpu.nn.core import InputType, Layer  # noqa: F401
from deeplearning4j_tpu.nn.layers import (  # noqa: F401
    ActivationLayer, BatchNormalizationLayer, Convolution1DLayer,
    ConvolutionLayer, Deconvolution2DLayer, DenseLayer,
    DepthwiseConvolution2DLayer, DropoutLayer, ElementWiseMultiplicationLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer,
    LayerNormalizationLayer, LocalResponseNormalizationLayer, LossLayer,
    OutputLayer, SeparableConvolution2DLayer, SubsamplingLayer,
    Upsampling2DLayer, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.recurrent import (  # noqa: F401
    Bidirectional, GravesLSTM, GRU, LastTimeStep, LSTM, RnnLossLayer,
    RnnOutputLayer, SimpleRnn)
from deeplearning4j_tpu.nn.attention import (  # noqa: F401
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, SelfAttentionLayer)
from deeplearning4j_tpu.nn.objdetect import (  # noqa: F401
    DetectedObject, SpaceToDepthLayer, Yolo2OutputLayer, YoloUtils)
from deeplearning4j_tpu.nn.layers_extra import (  # noqa: F401
    CenterLossOutputLayer, Convolution3DLayer, Cropping1DLayer,
    Cropping2DLayer, Cropping3DLayer, Deconvolution3DLayer,
    LocallyConnected1DLayer, LocallyConnected2DLayer, PReLULayer,
    Subsampling1DLayer, Subsampling3DLayer)
from deeplearning4j_tpu.nn.custom import (  # noqa: F401
    CapsuleLayer, CapsuleStrengthLayer, LambdaLayer, PrimaryCapsules,
    SameDiffLayer)
from deeplearning4j_tpu.nn.shape_ops import (  # noqa: F401
    FlattenLayer, PermuteLayer, RepeatVectorLayer, ReshapeLayer,
    TimeDistributed)
from deeplearning4j_tpu.nn.multilayer import (  # noqa: F401
    MultiLayerConfiguration, MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph import (  # noqa: F401
    ComputationGraph, ComputationGraphConfiguration, ElementWiseVertex,
    GraphBuilder, GraphVertex, L2NormalizeVertex, LayerVertex, MergeVertex,
    ReshapeVertex, ScaleVertex, ShiftVertex, StackVertex, SubsetVertex,
    UnstackVertex, register_vertex)

_LAYER_CLASSES = [
    ActivationLayer, BatchNormalizationLayer, Convolution1DLayer,
    ConvolutionLayer, Deconvolution2DLayer, DenseLayer,
    DepthwiseConvolution2DLayer, DropoutLayer, ElementWiseMultiplicationLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer,
    LayerNormalizationLayer, LocalResponseNormalizationLayer, LossLayer,
    OutputLayer, SeparableConvolution2DLayer, SubsamplingLayer,
    Upsampling2DLayer, ZeroPaddingLayer,
    Bidirectional, GravesLSTM, GRU, LastTimeStep, LSTM, RnnLossLayer,
    RnnOutputLayer, SimpleRnn,
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, SelfAttentionLayer,
    SpaceToDepthLayer, Yolo2OutputLayer,
    CenterLossOutputLayer, Convolution3DLayer, Cropping1DLayer,
    Cropping2DLayer, Cropping3DLayer, Deconvolution3DLayer,
    LocallyConnected1DLayer, LocallyConnected2DLayer, PReLULayer,
    Subsampling1DLayer, Subsampling3DLayer,
    CapsuleLayer, CapsuleStrengthLayer, LambdaLayer, PrimaryCapsules,
    FlattenLayer, PermuteLayer, RepeatVectorLayer, ReshapeLayer,
    TimeDistributed,
]

# Name -> class registry for config JSON round-trip (the reference's Jackson
# @JsonTypeInfo role). Recurrent/attention layers register on import.
LAYER_REGISTRY = {c.__name__: c for c in _LAYER_CLASSES}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls
