"""ComputationGraph: arbitrary-DAG model with a compiled train step.

Reference: `deeplearning4j-nn/.../nn/graph/ComputationGraph.java` (~4.5k LoC),
`nn/conf/ComputationGraphConfiguration.java` (GraphBuilder DSL) and the vertex
zoo `nn/graph/vertex/impl/**` (MergeVertex, ElementWiseVertex, SubsetVertex,
L2NormalizeVertex, StackVertex, UnstackVertex, ScaleVertex, ShiftVertex,
ReshapeVertex, PreprocessorVertex).

TPU design: the reference walks `GraphVertex[]` in topological order with
per-vertex workspace choreography (`outputOfLayersDetached`); here the whole
DAG forward + losses + `jax.grad` + updaters trace into ONE function that
`jax.jit` compiles, so XLA owns scheduling and activation memory.  Multi-input
/ multi-output and multiple loss heads (summed, as the reference does in
`computeGradientAndScore`) are plain pytree plumbing.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.monitor.instrument import TrainingInstruments
from deeplearning4j_tpu.monitor.spans import span
from deeplearning4j_tpu.nn.core import InputType, Layer, PyTree
from deeplearning4j_tpu.nn.multilayer import _add_scaled_where, _masked_leaves
from deeplearning4j_tpu.train.updaters import (
    IUpdater, Sgd, apply_gradient_normalization)

Params = Dict[str, PyTree]


# ---------------------------------------------------------------------------
# Graph vertices (reference nn/graph/vertex/impl/**)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class GraphVertex:
    """Non-layer graph node combining/reshaping activations.  Like `Layer`,
    a vertex is a config dataclass; `initialize` infers the output InputType,
    `apply` is the pure forward over its input list."""

    name: Optional[str] = None

    def initialize(self, rng: jax.Array, input_types: List[InputType],
                   dtype=jnp.float32) -> Tuple[PyTree, PyTree, InputType]:
        return {}, {}, self.output_type(input_types)

    def output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def apply(self, params: PyTree, state: PyTree, inputs: List[jnp.ndarray],
              *, train: bool = False, rng: Optional[jax.Array] = None
              ) -> Tuple[jnp.ndarray, PyTree]:
        raise NotImplementedError

    def to_json(self) -> dict:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        d["@vertex"] = type(self).__name__
        return d

    @staticmethod
    def from_json(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@vertex")]
        field_names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in field_names})


@dataclasses.dataclass(kw_only=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (reference `MergeVertex`):
    last axis in NHWC/[B,F]/[B,T,F] — the TPU-native layout's channel dim."""

    def output_type(self, input_types):
        t0 = input_types[0]
        feat = sum(t.shape[-1] for t in input_types)
        return InputType(t0.kind, t0.shape[:-1] + (feat,))

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return jnp.concatenate(inputs, axis=-1), state


@dataclasses.dataclass(kw_only=True)
class ElementWiseVertex(GraphVertex):
    """Pointwise combine (reference `ElementWiseVertex`):
    Add | Subtract | Product | Average | Max.  The residual-connection
    workhorse (ResNet shortcut = Add)."""

    op: str = "Add"

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        op = self.op.lower()
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("ElementWiseVertex Subtract requires exactly "
                                 f"2 inputs, got {len(inputs)}")
            return inputs[0] - inputs[1], state
        acc = inputs[0]
        for x in inputs[1:]:
            if op == "add":
                acc = acc + x
            elif op == "product":
                acc = acc * x
            elif op == "max":
                acc = jnp.maximum(acc, x)
            elif op == "average":
                acc = acc + x
            else:
                raise ValueError(f"Unknown ElementWiseVertex op {self.op}")
        if op == "average":
            acc = acc / len(inputs)
        return acc, state


@dataclasses.dataclass(kw_only=True)
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (reference `SubsetVertex`)."""

    range_from: int = 0
    range_to: int = 0

    def output_type(self, input_types):
        t = input_types[0]
        return InputType(t.kind, t.shape[:-1] + (self.range_to - self.range_from + 1,))

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0][..., self.range_from:self.range_to + 1], state


@dataclasses.dataclass(kw_only=True)
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over non-batch dims (reference `L2NormalizeVertex`)."""

    eps: float = 1e-8

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / jnp.maximum(norm, self.eps), state


@dataclasses.dataclass(kw_only=True)
class ScaleVertex(GraphVertex):
    """x * scale (reference `ScaleVertex`)."""

    scale: float = 1.0

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0] * self.scale, state


@dataclasses.dataclass(kw_only=True)
class ShiftVertex(GraphVertex):
    """x + shift (reference `ShiftVertex`)."""

    shift: float = 0.0

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return inputs[0] + self.shift, state


@dataclasses.dataclass(kw_only=True)
class StackVertex(GraphVertex):
    """Stack along batch axis (reference `StackVertex`)."""

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return jnp.concatenate(inputs, axis=0), state


@dataclasses.dataclass(kw_only=True)
class UnstackVertex(GraphVertex):
    """Inverse of StackVertex: take slice `from_index` of `stack_size` equal
    batch chunks (reference `UnstackVertex`)."""

    from_index: int = 0
    stack_size: int = 1

    def output_type(self, input_types):
        return input_types[0]

    def apply(self, params, state, inputs, *, train=False, rng=None):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_index * n:(self.from_index + 1) * n], state


@dataclasses.dataclass(kw_only=True)
class ReshapeVertex(GraphVertex):
    """Reshape non-batch dims (reference `ReshapeVertex`); `shape` excludes
    the batch dimension."""

    shape: Sequence[int] = ()

    def output_type(self, input_types):
        return InputType("feedforward" if len(self.shape) == 1 else
                         input_types[0].kind, tuple(self.shape))

    def apply(self, params, state, inputs, *, train=False, rng=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape)), state


@dataclasses.dataclass(kw_only=True)
class LayerVertex(GraphVertex):
    """Wraps a `Layer` config as a single-input graph vertex (reference
    `LayerVertex`)."""

    layer: Layer = None

    def initialize(self, rng, input_types, dtype=jnp.float32):
        return self.layer.initialize(rng, input_types[0], dtype)

    def apply(self, params, state, inputs, *, train=False, rng=None):
        return self.layer.apply(params, state, inputs[0], train=train, rng=rng)

    def to_json(self) -> dict:
        return {"@vertex": "LayerVertex", "name": self.name,
                "layer": self.layer.to_json()}


VERTEX_REGISTRY = {c.__name__: c for c in [
    MergeVertex, ElementWiseVertex, SubsetVertex, L2NormalizeVertex,
    ScaleVertex, ShiftVertex, StackVertex, UnstackVertex, ReshapeVertex,
    LayerVertex]}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


# ---------------------------------------------------------------------------
# Configuration + builder
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ComputationGraphConfiguration:
    """DAG config (reference `ComputationGraphConfiguration`): named inputs,
    vertices with their input edges, named outputs; JSON round-trip is the
    checkpoint contract."""

    network_inputs: List[str]
    input_types: Dict[str, InputType]
    vertices: Dict[str, GraphVertex]            # insertion order preserved
    vertex_inputs: Dict[str, List[str]]
    network_outputs: List[str]
    seed: int = 0
    updater: IUpdater = dataclasses.field(default_factory=lambda: Sgd(1e-2))
    weight_init: str = "XAVIER"
    activation: Any = "identity"
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dtype: str = "float32"
    compute_dtype: Optional[str] = None   # bf16 compute path (see multilayer)
    remat: bool = False                   # per-vertex jax.checkpoint
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0

    def topological_order(self) -> List[str]:
        """Kahn topological sort over vertex names (the reference precomputes
        `topologicalOrder` in ComputationGraphConfiguration)."""
        indeg = {n: 0 for n in self.vertices}
        children: Dict[str, List[str]] = {n: [] for n in self.vertices}
        for name, ins in self.vertex_inputs.items():
            for src in ins:
                if src in self.vertices:
                    indeg[name] += 1
                    children[src].append(name)
                elif src not in self.network_inputs:
                    raise ValueError(f"Vertex '{name}' input '{src}' unknown")
        order = [n for n in self.vertices if indeg[n] == 0]
        i = 0
        while i < len(order):
            for ch in children[order[i]]:
                indeg[ch] -= 1
                if indeg[ch] == 0:
                    order.append(ch)
            i += 1
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving {sorted(cyc)}")
        return order

    def to_json(self) -> str:
        return json.dumps({
            "format": "deeplearning4j_tpu.ComputationGraphConfiguration.v1",
            "network_inputs": self.network_inputs,
            "input_types": {k: v.to_json() for k, v in self.input_types.items()},
            "vertices": {k: v.to_json() for k, v in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "network_outputs": self.network_outputs,
            "seed": self.seed,
            "updater": self.updater.to_json(),
            "weight_init": self.weight_init,
            "activation": self.activation if isinstance(self.activation, str)
                          else getattr(self.activation, "__name__", "identity"),
            "l1": self.l1, "l2": self.l2, "weight_decay": self.weight_decay,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "remat": self.remat,
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)

        def load_vertex(vd):
            if vd["@vertex"] == "LayerVertex":
                return LayerVertex(name=vd.get("name"),
                                   layer=Layer.from_json(vd["layer"]))
            return GraphVertex.from_json(vd)

        return ComputationGraphConfiguration(
            network_inputs=d["network_inputs"],
            input_types={k: InputType.from_json(v)
                         for k, v in d["input_types"].items()},
            vertices={k: load_vertex(v) for k, v in d["vertices"].items()},
            vertex_inputs={k: list(v) for k, v in d["vertex_inputs"].items()},
            network_outputs=d["network_outputs"],
            seed=d["seed"], updater=IUpdater.from_json(d["updater"]),
            weight_init=d["weight_init"], activation=d["activation"],
            l1=d["l1"], l2=d["l2"], weight_decay=d.get("weight_decay", 0.0),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            remat=d.get("remat", False),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
        )


class GraphBuilder:
    """Fluent DAG builder (reference
    `NeuralNetConfiguration.Builder.graphBuilder()` -> `GraphBuilder`)."""

    def __init__(self):
        self._inputs: List[str] = []
        self._input_types: Dict[str, InputType] = {}
        self._vertices: Dict[str, GraphVertex] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._outputs: List[str] = []
        self._seed = 0
        self._updater: IUpdater = Sgd(1e-2)
        self._weight_init = "XAVIER"
        self._activation: Any = "identity"
        self._l1 = 0.0
        self._l2 = 0.0
        self._weight_decay = 0.0
        self._dtype = "float32"
        self._compute_dtype = None
        self._remat = False
        self._grad_norm = None
        self._grad_norm_threshold = 1.0

    # global defaults (mirror NeuralNetConfiguration.Builder)
    def seed(self, s): self._seed = int(s); return self
    def updater(self, u): self._updater = u; return self
    def weight_init(self, w): self._weight_init = w; return self
    def activation(self, a): self._activation = a; return self
    def l1(self, v): self._l1 = float(v); return self
    def l2(self, v): self._l2 = float(v); return self
    def weight_decay(self, v): self._weight_decay = float(v); return self
    def dtype(self, dt): self._dtype = dt; return self
    def compute_dtype(self, dt): self._compute_dtype = dt; return self

    def gradient_checkpointing(self, on: bool = True):
        """Rematerialize each vertex in the backward pass (jax.checkpoint);
        HBM for FLOPs on deep graphs."""
        self._remat = bool(on); return self

    def gradient_normalization(self, mode, threshold=1.0):
        self._grad_norm = mode; self._grad_norm_threshold = threshold; return self

    # graph topology
    def add_inputs(self, *names: str):
        self._inputs.extend(names); return self

    def set_input_types(self, *types: InputType):
        if len(types) != len(self._inputs):
            raise ValueError(
                f"set_input_types got {len(types)} types for "
                f"{len(self._inputs)} declared inputs (call add_inputs first)")
        for name, t in zip(self._inputs, types):
            self._input_types[name] = t
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str):
        layer.name = layer.name or name
        return self.add_vertex(name, LayerVertex(layer=layer), *inputs)

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str):
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex name '{name}'")
        vertex.name = name
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str):
        self._outputs = list(names); return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._outputs:
            raise ValueError("set_outputs(...) is required")
        for name in self._inputs:
            if name not in self._input_types:
                raise ValueError(f"Input '{name}' has no InputType "
                                 "(set_input_types required for shape inference)")
        return ComputationGraphConfiguration(
            network_inputs=self._inputs, input_types=dict(self._input_types),
            vertices=self._vertices, vertex_inputs=self._vertex_inputs,
            network_outputs=self._outputs, seed=self._seed,
            updater=self._updater, weight_init=self._weight_init,
            activation=self._activation, l1=self._l1, l2=self._l2,
            weight_decay=self._weight_decay, dtype=self._dtype,
            compute_dtype=self._compute_dtype,
            remat=self._remat,
            gradient_normalization=self._grad_norm,
            gradient_normalization_threshold=self._grad_norm_threshold)


# ---------------------------------------------------------------------------
# Network
# ---------------------------------------------------------------------------

class ComputationGraph:
    """DAG network (reference `ComputationGraph`).  API parity:
    `init`, `fit(MultiDataSet | (features, labels))`, `output(*features)`,
    `score`, `evaluate`, `gradient_for`, `save`/`load`."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params_: Optional[Params] = None
        self.state_: Optional[Params] = None
        self.opt_state_: Optional[PyTree] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._rng = jax.random.PRNGKey(conf.seed)
        self._topo = conf.topological_order()
        self._train_step = None
        self._scan_step = None
        self._grad_step = None    # hierarchical-sharing split: grad half
        self._apply_step = None   # hierarchical-sharing split: apply half
        self._grad_sharing = None  # parallel.hierarchical.HierarchicalAllReduce
        self._output_fn = None
        self._step_transform = None   # ZeRO-1 weight update (parallel/zero)
        self._vertex_types: Dict[str, InputType] = {}
        self._device_norm: Dict[str, Any] = {}  # input name -> DeviceNormalizer
        self._instr: Optional[TrainingInstruments] = None
        self._exec_cache_override = None  # compile.PersistentExecutableCache
        self._schedule = None             # compile.Schedule (autotuner)

    def _instruments(self) -> TrainingInstruments:
        """Lazy telemetry handles shared via the monitor registry."""
        if self._instr is None:
            self._instr = TrainingInstruments(type(self).__name__)
        return self._instr

    def _layer_of(self, name: str) -> Optional[Layer]:
        v = self.conf.vertices[name]
        return v.layer if isinstance(v, LayerVertex) else None

    # ---- init ----
    def init(self) -> "ComputationGraph":
        dtype = jnp.dtype(self.conf.dtype)
        types: Dict[str, InputType] = dict(self.conf.input_types)
        params: Params = {}
        state: Params = {}
        key = jax.random.PRNGKey(self.conf.seed)
        for name in self._topo:
            vertex = self.conf.vertices[name]
            layer = self._layer_of(name)
            if layer is not None:
                if layer.weight_init is None:
                    layer.weight_init = self.conf.weight_init
                if layer.activation is None and not hasattr(layer, "loss"):
                    layer.activation = self.conf.activation
            in_types = [types[s] for s in self.conf.vertex_inputs[name]]
            key, sub = jax.random.split(key)
            p, s, out_t = vertex.initialize(sub, in_types, dtype)
            params[name] = p
            state[name] = s
            types[name] = out_t
        self._vertex_types = types
        self.params_ = params
        self.state_ = state
        self.opt_state_ = self._init_opt_state(params)
        return self

    def _updater_for(self, name: str) -> IUpdater:
        layer = self._layer_of(name)
        if layer is not None and layer.updater is not None:
            return layer.updater
        return self.conf.updater

    def _init_opt_state(self, params: Params) -> PyTree:
        return {name: self._updater_for(name).init_state(params[name])
                for name in self._topo}

    # ---- forward ----
    def _forward(self, params: Params, state: Params, inputs: Dict[str, Any],
                 *, train: bool, rng: Optional[jax.Array],
                 want_head_inputs: bool = False):
        """Run the DAG; returns activations for every vertex (plus, when
        `want_head_inputs`, the raw input of each loss head for
        `compute_loss` — heads still produce their normal activation so
        downstream consumers see real outputs; XLA dead-code-eliminates an
        unused head forward)."""
        cd = self.conf.compute_dtype
        if cd is not None:
            dt = jnp.dtype(cd)
            cast = (lambda a: a.astype(dt)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a)
            params = jax.tree_util.tree_map(cast, params)
            inputs = {k: cast(jnp.asarray(v)) for k, v in inputs.items()}
        acts: Dict[str, jnp.ndarray] = dict(inputs)
        head_inputs: Dict[str, jnp.ndarray] = {}
        new_state = dict(state)
        for i, name in enumerate(self._topo):
            vertex = self.conf.vertices[name]
            layer = self._layer_of(name)
            vrng = None
            if rng is not None and layer is not None and layer.STOCHASTIC:
                vrng = jax.random.fold_in(rng, i)
            xs = [acts[s] for s in self.conf.vertex_inputs[name]]
            if (want_head_inputs and name in self.conf.network_outputs
                    and layer is not None and hasattr(layer, "compute_loss")):
                head_inputs[name] = xs[0]
            if self.conf.remat and train:
                # train only (see MultiLayerNetwork._forward)
                def _apply(p_, s_, xs_, r_, _v=vertex, _train=train):
                    return _v.apply(p_, s_, xs_, train=_train, rng=r_)
                acts[name], new_state[name] = jax.checkpoint(_apply)(
                    params[name], state[name], xs, vrng)
            else:
                acts[name], new_state[name] = vertex.apply(
                    params[name], state[name], xs, train=train, rng=vrng)
        if want_head_inputs:
            return acts, new_state, head_inputs
        return acts, new_state

    def _loss(self, params: Params, state: Params, inputs: Dict[str, Any],
              labels: List[Any], rng, labels_masks: Optional[List[Any]] = None,
              train: bool = True) -> Tuple[jnp.ndarray, Params]:
        """Summed loss over all output heads + regularization (reference
        `ComputationGraph.computeGradientAndScore` sums output-layer scores)."""
        acts, new_state, head_inputs = self._forward(
            params, state, inputs, train=train, rng=rng, want_head_inputs=True)
        loss = 0.0
        for j, name in enumerate(self.conf.network_outputs):
            layer = self._layer_of(name)
            if layer is None or not hasattr(layer, "compute_loss"):
                raise ValueError(f"Output vertex '{name}' is not a loss head")
            lrng = None if rng is None else jax.random.fold_in(rng, 10_000 + j)
            lmask = labels_masks[j] if labels_masks else None
            loss = loss + layer.compute_loss(
                params[name], state[name], head_inputs[name], labels[j],
                train=train, rng=lrng, mask=lmask)
        return loss + self._reg_penalty(params), new_state

    def _reg_penalty(self, params: Params):
        penalty = 0.0
        for name in self._topo:
            layer = self._layer_of(name)
            if layer is None:
                continue
            l1 = layer.l1 if layer.l1 is not None else self.conf.l1
            l2 = layer.l2 if layer.l2 is not None else self.conf.l2
            if l1 == 0.0 and l2 == 0.0:
                continue
            rmask = layer.regularizable_mask(params[name])
            for w in _masked_leaves(params[name], rmask):
                if l1:
                    penalty = penalty + l1 * jnp.sum(jnp.abs(w))
                if l2:
                    penalty = penalty + 0.5 * l2 * jnp.sum(w * w)
        return penalty

    # ---- compiled step ----
    def _build_step_body(self):
        conf = self.conf
        zt = self._step_transform   # ZeRO-1 sharded weight update, or None

        def step(params, state, opt_state, inputs, labels, lmasks, rng,
                 iteration, epoch):
            # split inside the compiled step (see MultiLayerNetwork._fit_batch:
            # device-resident rng/iteration carries, no per-step H2D)
            inputs = self._apply_device_norm(inputs)
            rng, srng = jax.random.split(rng)
            master = params
            if zt is not None:
                # all-gather sharded master params once per step; the DAG
                # forward/backward run on the gathered (or TP) layout
                params = zt.gather_all(params)

            def loss_fn(p):
                return self._loss(p, state, inputs, labels, srng, lmasks)

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)

            new_params, new_opt = {}, {}
            for name in self._topo:
                layer = self._layer_of(name)
                if not params[name]:
                    new_params[name], new_opt[name] = master[name], opt_state[name]
                    continue
                if layer is not None and layer.frozen:
                    new_params[name], new_opt[name] = master[name], opt_state[name]
                    continue
                g = grads[name]
                gn = (layer.gradient_normalization if layer is not None and
                      layer.gradient_normalization is not None
                      else conf.gradient_normalization)
                if gn:
                    thr = (layer.gradient_normalization_threshold
                           if layer is not None and
                           layer.gradient_normalization is not None
                           else conf.gradient_normalization_threshold)
                    g = apply_gradient_normalization(g, gn, thr)
                if zt is None:
                    p_upd = params[name]
                else:
                    # reduce-scatter grads; updater touches only this
                    # device's shard of params/moments
                    g = zt.scatter(name, g)
                    p_upd = zt.update_view(name, master[name])
                upd_cfg = self._updater_for(name)
                upd, new_o = upd_cfg.apply(
                    opt_state[name], g, iteration, epoch, params=p_upd)
                wd = (layer.weight_decay if layer is not None and
                      layer.weight_decay is not None else conf.weight_decay)
                if wd and layer is not None:
                    lr = upd_cfg.lr_at(iteration, epoch)
                    upd = _add_scaled_where(
                        upd, p_upd,
                        layer.regularizable_mask(p_upd), lr * wd)
                new_p = jax.tree_util.tree_map(
                    lambda p_, u_: p_ - u_, p_upd, upd)
                if zt is not None:
                    new_p = zt.restore(name, new_p)
                    new_o = zt.constrain_opt(name, new_o)
                new_params[name], new_opt[name] = new_p, new_o
            return new_params, new_state, new_opt, loss, rng, iteration + 1

        return step

    def _exec_cache(self):
        """The persistent executable cache in play: the per-model override
        (`set_executable_cache`), else the process default — None keeps
        the plain jax.jit path."""
        if self._exec_cache_override is not None:
            return self._exec_cache_override
        from deeplearning4j_tpu.compile import default_cache
        return default_cache()

    def set_executable_cache(self, cache) -> "ComputationGraph":
        """Route this graph's train-step compilation through a
        `compile.PersistentExecutableCache` (or a directory path); None
        reverts to the process default.  Triggers a step rebuild."""
        if isinstance(cache, str):
            from deeplearning4j_tpu.compile import PersistentExecutableCache
            cache = PersistentExecutableCache(cache)
        self._exec_cache_override = cache
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        return self

    def apply_schedule(self, schedule) -> "ComputationGraph":
        """Install an autotuned `compile.Schedule` (iterator `fit()`
        defaults `fused_steps` from it; step builders honor
        `schedule.donation`).  Triggers a step rebuild."""
        self._schedule = schedule
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        return self

    def _donate_argnums(self) -> tuple:
        if self._schedule is not None and not self._schedule.donation:
            return ()
        return (0, 1, 2)

    def _aot_key_parts(self) -> dict:
        from deeplearning4j_tpu.compile import (model_fingerprint,
                                                transform_fingerprint)
        return {"kind": "cg_train_step",
                "model": model_fingerprint(self),
                "transform": transform_fingerprint(self._step_transform)}

    def _get_train_step(self):
        if self._train_step is None:
            from deeplearning4j_tpu.compile import step_function
            self._train_step = step_function(
                self._build_step_body(),
                donate_argnums=self._donate_argnums(),
                key_base=self._aot_key_parts,
                cache=self._exec_cache(),
                dynamic_argnums=(3, 4, 5))
        return self._train_step

    # ---- hierarchical gradient sharing (parallel.hierarchical) ----
    def set_gradient_sharing(self, sharing) -> "ComputationGraph":
        """Enable/disable hierarchical compressed cross-host gradient
        sharing (see MultiLayerNetwork.set_gradient_sharing — identical
        semantics over the DAG step)."""
        from deeplearning4j_tpu.parallel.hierarchical import (
            HierarchicalAllReduce, HierarchicalGradientSharing)
        if sharing is None:
            if self._grad_sharing is not None:
                self._grad_sharing.close()
            self._grad_sharing = None
        elif isinstance(sharing, HierarchicalGradientSharing):
            self._grad_sharing = HierarchicalAllReduce(sharing)
        elif isinstance(sharing, HierarchicalAllReduce):
            self._grad_sharing = sharing
        else:
            raise TypeError(
                "set_gradient_sharing expects HierarchicalGradientSharing, "
                f"HierarchicalAllReduce or None, got {type(sharing).__name__}")
        self._grad_step = None
        self._apply_step = None
        return self

    @property
    def gradient_sharing(self):
        """The installed `HierarchicalAllReduce`, or None."""
        return self._grad_sharing

    def _build_grad_body(self):
        """Grad half of the split step (params NOT donated — the apply
        half consumes them next)."""
        zt = self._step_transform

        def grad_step(params, state, inputs, labels, lmasks, rng):
            inputs = self._apply_device_norm(inputs)
            rng, srng = jax.random.split(rng)
            fwd_params = params if zt is None else zt.gather_all(params)

            def loss_fn(p):
                return self._loss(p, state, inputs, labels, srng, lmasks)

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(fwd_params)
            if zt is not None:
                # ship the reduce-scattered (padded) shard, not the
                # gathered tree; empty param subtrees scatter to empty
                grads = {name: zt.scatter(name, grads[name])
                         for name in self._topo}
            return grads, new_state, loss, rng

        return grad_step

    def _build_apply_body(self):
        """Apply half: updater loop on the DCN-combined gradient
        (normalization runs here, on the combined gradient)."""
        conf = self.conf
        zt = self._step_transform

        def apply_step(params, opt_state, grads, iteration, epoch):
            new_params, new_opt = {}, {}
            for name in self._topo:
                layer = self._layer_of(name)
                if not params[name]:
                    new_params[name] = params[name]
                    new_opt[name] = opt_state[name]
                    continue
                if layer is not None and layer.frozen:
                    new_params[name] = params[name]
                    new_opt[name] = opt_state[name]
                    continue
                g = grads[name]
                if zt is not None:
                    g = zt.constrain_update(name, g)
                gn = (layer.gradient_normalization if layer is not None and
                      layer.gradient_normalization is not None
                      else conf.gradient_normalization)
                if gn:
                    thr = (layer.gradient_normalization_threshold
                           if layer is not None and
                           layer.gradient_normalization is not None
                           else conf.gradient_normalization_threshold)
                    g = apply_gradient_normalization(g, gn, thr)
                p_upd = (params[name] if zt is None
                         else zt.update_view(name, params[name]))
                upd_cfg = self._updater_for(name)
                upd, new_o = upd_cfg.apply(
                    opt_state[name], g, iteration, epoch, params=p_upd)
                wd = (layer.weight_decay if layer is not None and
                      layer.weight_decay is not None else conf.weight_decay)
                if wd and layer is not None:
                    lr = upd_cfg.lr_at(iteration, epoch)
                    upd = _add_scaled_where(
                        upd, p_upd,
                        layer.regularizable_mask(p_upd), lr * wd)
                new_p = jax.tree_util.tree_map(
                    lambda p_, u_: p_ - u_, p_upd, upd)
                if zt is not None:
                    new_p = zt.restore(name, new_p)
                    new_o = zt.constrain_opt(name, new_o)
                new_params[name], new_opt[name] = new_p, new_o
            return new_params, new_opt, iteration + 1

        return apply_step

    def _get_grad_step(self):
        if self._grad_step is None:
            from deeplearning4j_tpu.compile import step_function
            self._grad_step = step_function(
                self._build_grad_body(),
                donate_argnums=(1,),
                key_base=lambda: dict(
                    self._aot_key_parts(), kind="cg_grad_step"),
                cache=self._exec_cache(),
                dynamic_argnums=(2, 3, 4))
        return self._grad_step

    def _get_apply_step(self):
        if self._apply_step is None:
            from deeplearning4j_tpu.compile import step_function
            self._apply_step = step_function(
                self._build_apply_body(),
                donate_argnums=(0, 1),
                key_base=lambda: dict(
                    self._aot_key_parts(), kind="cg_apply_step"),
                cache=self._exec_cache(),
                dynamic_argnums=())
        return self._apply_step

    def _fit_batch_shared(self, inputs, labels, lmasks=None):
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        t0 = time.perf_counter()
        gstep = self._get_grad_step()
        grads, self.state_, loss, self._rng = gstep(
            self.params_, self.state_, inputs, labels, lmasks, self._rng)
        combined = self._grad_sharing.exchange(grads)
        astep = self._get_apply_step()
        it_dev, ep_dev = device_counters(self)
        self.params_, self.opt_state_, new_it = astep(
            self.params_, self.opt_state_, combined, it_dev, ep_dev)
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0)
        ins.check_compile(gstep, self)
        ins.check_compile(astep, self)
        self._score = loss
        self._last_batch_size = int(next(iter(inputs.values())).shape[0])
        advance(self, new_it)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)

    def _get_scan_step(self):
        if self._scan_step is None:
            from deeplearning4j_tpu.utils.scan_fit import make_scan_step
            body = self._build_step_body()

            def tick(carry, epoch, batch):
                p, s, o, r, it = carry
                ins, ys, lm = batch
                p, s, o, loss, r, it = body(p, s, o, ins, ys, lm,
                                            r, it, epoch)
                return (p, s, o, r, it), loss

            self._scan_step = make_scan_step(
                tick,
                key_base=lambda: dict(self._aot_key_parts(),
                                      kind="cg_scan_step"),
                cache=self._exec_cache(),
                donate=(self._schedule is None or self._schedule.donation))
        return self._scan_step

    def fit_steps(self, features, labels, labels_masks=None):
        """Run k training steps in one device dispatch; every array in
        `features`/`labels`/`labels_masks` carries a leading `[k, batch]`
        steps axis.  Same math as k sequential `fit` calls (scan carries
        params/updater/rng/iteration); listeners fire once per block."""
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        inputs = self._as_input_dict(features)
        labels = self._as_list(labels)
        if labels_masks is not None and not isinstance(labels_masks,
                                                       (list, tuple)):
            labels_masks = [labels_masks]
        lmasks = (None if labels_masks is None
                  else [jnp.asarray(m) for m in labels_masks])
        from deeplearning4j_tpu.utils.scan_fit import check_steps_axes
        k = check_steps_axes(
            [(f"input '{n}'", a) for n, a in inputs.items()]
            + [(f"label {i}", l) for i, l in enumerate(labels)]
            + [(f"labels_mask {i}", m) for i, m in enumerate(lmasks or [])])
        if self._grad_sharing is not None:
            # host exchange can't run mid-scan: per-step two-phase loop
            # (same math; see MultiLayerNetwork.fit_steps)
            losses = []
            for i in range(int(k)):
                self._fit_batch_shared(
                    {n: a[i] for n, a in inputs.items()},
                    [l[i] for l in labels],
                    None if lmasks is None else [m[i] for m in lmasks])
                losses.append(self._score)
            return jnp.stack(losses)
        step = self._get_scan_step()
        it_dev, ep_dev = device_counters(self)
        t0 = time.perf_counter()
        ((self.params_, self.state_, self.opt_state_, self._rng, new_it),
         losses, last_loss) = step((self.params_, self.state_,
                                    self.opt_state_, self._rng, it_dev),
                                   ep_dev, (inputs, labels, lmasks))
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0, steps=int(k))
        ins.check_compile(step, self)
        self._score = last_loss
        self._last_batch_size = int(next(iter(inputs.values())).shape[1])
        advance(self, new_it, steps=int(k))
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)
        return losses

    # ---- public API ----
    def _as_input_dict(self, features) -> Dict[str, jnp.ndarray]:
        if isinstance(features, dict):
            return {k: jnp.asarray(v) for k, v in features.items()}
        if not isinstance(features, (list, tuple)):
            features = [features]
        return {n: jnp.asarray(f)
                for n, f in zip(self.conf.network_inputs, features)}

    @staticmethod
    def _as_list(labels) -> List[jnp.ndarray]:
        if labels is None:
            return None
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        return [jnp.asarray(l) for l in labels]

    def fit(self, data, labels=None, *, epochs: int = 1,
            fused_steps: Optional[int] = None):
        """fit(features, labels) for one batch (single- or multi-output), or
        fit(MultiDataSetIterator | DataSetIterator, epochs=N).

        `fused_steps=k` fuses blocks of k consecutive same-shape batches
        into one compiled dispatch (`fit_steps`); tails and shape changes
        fall back to per-step dispatch (identical math either way).  Unset,
        it defaults to the installed schedule's (`apply_schedule`), else 1."""
        if labels is not None:
            if fused_steps not in (None, 1):
                raise ValueError(
                    "fused_steps applies to the iterator form only; for a "
                    "pre-stacked [k, batch, ...] block call fit_steps")
            self._fit_batch(self._as_input_dict(data), self._as_list(labels))
            return self
        if fused_steps is None:
            fused_steps = (self._schedule.fused_steps
                           if self._schedule is not None else 1)
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            with span("fit_epoch", model=type(self).__name__):
                if fused_steps > 1:
                    self._fit_epoch_fused(data, fused_steps)
                else:
                    for ds in data:
                        self._fit_dataset(ds)
            self.epoch += 1
            self._instruments().record_epoch()
            for lst in self.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self)
        return self

    def _fit_dataset(self, ds):
        lmasks = getattr(ds, "labels_mask", None)
        if lmasks is not None and not isinstance(lmasks, (list, tuple)):
            lmasks = [lmasks]
        self._fit_batch(self._as_input_dict(ds.features),
                        self._as_list(ds.labels),
                        None if lmasks is None else
                        [jnp.asarray(m) for m in lmasks])

    def _fit_epoch_fused(self, iterator, k: int):
        # blocks stack ON DEVICE (jnp.stack over staged per-batch arrays):
        # no per-block host np.stack copy, and prefetched batches fuse
        # without touching the host again (data.pipeline).
        from deeplearning4j_tpu.data.pipeline import _stack_staged
        from deeplearning4j_tpu.utils.scan_fit import blocks_of
        for block in blocks_of(iterator, k):
            if len(block) == 1:
                self._fit_dataset(block[0])
                continue
            feats = [self._as_input_dict(ds.features) for ds in block]
            labs = [self._as_list(ds.labels) for ds in block]
            lms = []
            for ds in block:
                lm = getattr(ds, "labels_mask", None)
                if lm is not None and not isinstance(lm, (list, tuple)):
                    lm = [lm]
                lms.append(lm)
            if any(m is None for m in lms) and not all(m is None for m in lms):
                for ds in block:            # mixed-mask block: not fusable
                    self._fit_dataset(ds)
                continue
            stacked_feats = {n: _stack_staged([f[n] for f in feats])
                             for n in feats[0]}
            stacked_labs = [_stack_staged([l[i] for l in labs])
                            for i in range(len(labs[0]))]
            stacked_lms = (None if lms[0] is None else
                           [_stack_staged([m[i] for m in lms])
                            for i in range(len(lms[0]))])
            self.fit_steps(stacked_feats, stacked_labs, stacked_lms)

    def _fit_batch(self, inputs: Dict[str, jnp.ndarray],
                   labels: List[jnp.ndarray], lmasks=None):
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        if self._grad_sharing is not None:
            return self._fit_batch_shared(inputs, labels, lmasks)
        step = self._get_train_step()
        it_dev, ep_dev = device_counters(self)
        t0 = time.perf_counter()
        (self.params_, self.state_, self.opt_state_, loss, self._rng,
         new_it) = step(
            self.params_, self.state_, self.opt_state_, inputs, labels,
            lmasks, self._rng, it_dev, ep_dev)
        ins = self._instruments()
        ins.record_dispatch(time.perf_counter() - t0)
        ins.check_compile(step, self)
        self._score = loss
        self._last_batch_size = int(next(iter(inputs.values())).shape[0])
        advance(self, new_it)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch)

    def score(self) -> float:
        """Blocking read of the most recent minibatch loss; steady-state
        loops should prefer `score_array()` (no host sync)."""
        s = getattr(self, "_score", None)
        return float(s) if s is not None else float("nan")

    def score_array(self):
        """Most recent minibatch loss as a (possibly in-flight) device
        array, or None before the first step.  Never forces a host sync."""
        return getattr(self, "_score", None)

    def _apply_device_norm(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        if not self._device_norm:
            return inputs
        return {n: (self._device_norm[n].apply_features(a)
                    if n in self._device_norm else a)
                for n, a in inputs.items()}

    def set_normalizer(self, normalizers) -> "ComputationGraph":
        """Fold fitted normalizers into the compiled step/output as an
        on-device prologue.  `normalizers` is `{input_name: normalizer}`
        (a bare normalizer is applied to every network input), or None to
        clear.  Labels pass through untouched (the MultiNormalizer
        features-only contract)."""
        from deeplearning4j_tpu.data.pipeline import DeviceNormalizer
        if normalizers is None:
            self._device_norm = {}
        else:
            if not isinstance(normalizers, dict):
                normalizers = {n: normalizers
                               for n in self.conf.network_inputs}
            unknown = set(normalizers) - set(self.conf.network_inputs)
            if unknown:
                raise ValueError(f"unknown network inputs: {sorted(unknown)}")
            self._device_norm = {n: DeviceNormalizer.from_host(nz)
                                 for n, nz in normalizers.items()}
        self._train_step = None
        self._scan_step = None
        self._grad_step = None
        self._apply_step = None
        self._output_fn = None
        return self

    def score_for(self, features, labels) -> float:
        loss, _ = self._loss(self.params_, self.state_,
                             self._apply_device_norm(
                                 self._as_input_dict(features)),
                             self._as_list(labels), None, train=False)
        return float(loss)

    def output(self, *features, train: bool = False) -> List[jnp.ndarray]:
        """Inference outputs in `network_outputs` order (reference
        `output(INDArray...)`), jitted."""
        if len(features) == 1 and isinstance(features[0], (list, tuple, dict)):
            features = features[0]
        else:
            features = list(features)
        inputs = self._as_input_dict(features)
        if self._output_fn is None:
            def fwd(p, s, ins, train):
                # train=True runs stochastic layers deterministically off
                # (no rng at inference — matches reference output(train) which
                # only toggles BN/eval-mode semantics, not dropout sampling)
                ins = self._apply_device_norm(ins)
                acts, _ = self._forward(p, s, ins, train=train, rng=None)
                return [acts[n] for n in self.conf.network_outputs]
            self._output_fn = jax.jit(fwd, static_argnums=(3,))
        return self._output_fn(self.params_, self.state_, inputs, train)

    def feed_forward(self, *features, train: bool = False) -> Dict[str, jnp.ndarray]:
        """All vertex activations by name (reference `feedForward()`)."""
        if len(features) == 1 and isinstance(features[0], (list, tuple, dict)):
            features = features[0]
        else:
            features = list(features)
        acts, _ = self._forward(self.params_, self.state_,
                                self._as_input_dict(features),
                                train=train, rng=None)
        return acts

    def evaluate(self, iterator, evaluation=None):
        """Single-output classification eval (the reference likewise rejects
        multi-output graphs in `evaluate()`); for multi-head graphs run
        `output()` and feed an Evaluation per head."""
        if len(self.conf.network_outputs) != 1:
            raise ValueError(
                "evaluate() requires a single-output graph; this one has "
                f"{self.conf.network_outputs} — use output() + Evaluation "
                "per head")
        from deeplearning4j_tpu.train.evaluation import Evaluation
        ev = evaluation or Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features)
            labels = ds.labels if isinstance(ds.labels, (list, tuple)) else [ds.labels]
            ev.eval(np.asarray(labels[0]), np.asarray(out[0]))
        return ev

    # ---- params / gradients ----
    def num_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self.params_))

    def params(self) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(self.params_)
        return np.concatenate([np.asarray(l).ravel() for l in leaves]) if leaves \
            else np.zeros((0,), np.float32)

    def set_params(self, flat: np.ndarray):
        leaves, treedef = jax.tree_util.tree_flatten(self.params_)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(flat[off:off + n], l.dtype).reshape(l.shape))
            off += n
        if off != flat.size:
            raise ValueError(f"Param count mismatch: {flat.size} vs {off}")
        self.params_ = jax.tree_util.tree_unflatten(treedef, out)

    def gradient_for(self, features, labels) -> Params:
        """Analytic gradients (GradientCheckUtil hook).  Eval mode, matching
        `score_for` — finite differences of score_for are only comparable to
        gradients taken in the same mode (BN running stats, no dropout)."""
        inputs = self._as_input_dict(features)
        labels = self._as_list(labels)

        def loss_fn(p):
            return self._loss(p, self.state_, inputs, labels, None,
                              train=False)[0]
        return jax.grad(loss_fn)(self.params_)

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    # ---- persistence ----
    def save(self, path: str, save_updater: bool = True):
        from deeplearning4j_tpu.utils.serialization import write_model
        write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "ComputationGraph":
        from deeplearning4j_tpu.utils.serialization import read_model
        return read_model(path, load_updater=load_updater)
