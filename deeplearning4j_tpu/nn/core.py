"""Layer-config NN API core.

TPU-native re-design of the reference's DL4J-nn config layer
(`deeplearning4j-nn/.../nn/conf/**`, `nn/layers/**`): layer *configs* are
lightweight dataclasses; parameters live in a jax pytree keyed by layer name;
forward/backward is one traced function compiled by XLA.  The reference's
hand-managed workspace choreography (WS_LAYER_WORKING_MEM etc.,
`MultiLayerNetwork.java`) is intentionally absent — XLA buffer assignment
owns activation memory when the whole step is jitted.

Data layout is NHWC / HWIO (TPU-native), not the reference's NCHW default;
the Keras/TF importers transpose at the boundary.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.initializers import init_weights

PyTree = Any


# ---------------------------------------------------------------------------
# InputType — mirrors org.deeplearning4j.nn.conf.inputs.InputType
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class InputType:
    """Shape metadata (without batch dim) used for layer shape inference,
    replacing `InputType.feedForward/convolutional/recurrent` and the
    auto-added InputPreProcessors."""

    kind: str           # "feedforward" | "convolutional" | "recurrent"
    shape: Tuple[int, ...]

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("feedforward", (int(size),))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        # NHWC without batch: (H, W, C)
        return InputType("convolutional", (int(height), int(width), int(channels)))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        # (T, F) without batch; T may be None (dynamic padded length)
        return InputType("recurrent", (timesteps if timesteps is None else int(timesteps), int(size)))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        # NDHWC without batch: (D, H, W, C)
        return InputType("convolutional3d",
                         (int(depth), int(height), int(width),
                          int(channels)))

    def flat_size(self) -> int:
        n = 1
        for s in self.shape:
            if s is None:
                raise ValueError("Cannot flatten dynamic dimension")
            n *= s
        return n

    def to_json(self) -> dict:
        return {"kind": self.kind, "shape": list(self.shape)}

    @staticmethod
    def from_json(d: dict) -> "InputType":
        return InputType(d["kind"], tuple(d["shape"]))


# ---------------------------------------------------------------------------
# Layer base
# ---------------------------------------------------------------------------

@dataclasses.dataclass(kw_only=True)
class Layer:
    """Base layer config — the `org.deeplearning4j.nn.conf.layers.Layer`
    equivalent.  Subclasses implement `initialize` (params + output InputType)
    and `apply` (pure forward).

    Per-layer hyperparameters override the global defaults set on
    `NeuralNetConfiguration` (same precedence as the reference's
    `BaseLayer.Builder` overrides).
    """

    name: Optional[str] = None
    activation: Optional[Any] = None          # name or callable
    weight_init: Optional[str] = None         # WeightInit scheme name
    bias_init: float = 0.0
    updater: Optional[Any] = None             # per-layer IUpdater override
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    dropout: Optional[float] = None           # RETAIN probability (reference semantics)
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    frozen: bool = False                      # transfer-learning freeze

    # param keys subject to l1/l2/weight-decay (biases excluded, ref default)
    REGULARIZABLE: Tuple[str, ...] = ("W",)
    # does this layer carry non-trainable state (e.g. BN running stats)?
    HAS_STATE: bool = False
    # does apply() consume an rng in train mode (dropout etc.)?
    STOCHASTIC: bool = False

    def initialize(self, rng: jax.Array, input_type: InputType,
                   dtype=jnp.float32) -> Tuple[PyTree, PyTree, InputType]:
        """Returns (params, state, output_type)."""
        raise NotImplementedError

    def apply(self, params: PyTree, state: PyTree, x: jnp.ndarray, *,
              train: bool = False, rng: Optional[jax.Array] = None,
              mask: Optional[jnp.ndarray] = None
              ) -> Tuple[jnp.ndarray, PyTree]:
        """Returns (output, new_state)."""
        raise NotImplementedError

    def regularizable_mask(self, params: PyTree) -> PyTree:
        """Bool pytree matching `params`: True where l1/l2/weight-decay apply
        (the reference's `getRegularizationByParam` per-param dispatch).
        Wrapper layers override to delegate to their inner layer."""
        return {k: (k in self.REGULARIZABLE) for k in params}

    # ---- config resolution helpers ----
    def act_fn(self, default="identity"):
        return get_activation(self.activation if self.activation is not None else default)

    def winit(self, default="XAVIER") -> str:
        return self.weight_init if self.weight_init is not None else default

    def maybe_input_dropout(self, x, train, rng):
        """Reference semantics: `dropOut` on a layer config drops the layer
        *input* (IDropout applied in `BaseLayer.applyDropOutIfNecessary`)."""
        if not train or self.dropout is None or self.dropout >= 1.0 or rng is None:
            return x
        p = self.dropout  # retain probability
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / p, 0.0)

    # ---- JSON round-trip ----
    def to_json(self) -> dict:
        from deeplearning4j_tpu.train.updaters import IUpdater
        d = {}
        for f in dataclasses.fields(self):
            if f.name in ("REGULARIZABLE", "HAS_STATE", "STOCHASTIC"):
                continue
            v = getattr(self, f.name)
            if isinstance(v, IUpdater):
                v = v.to_json()
            elif isinstance(v, Layer):      # nested layer (Bidirectional etc.)
                v = v.to_json()
            elif callable(v) and not isinstance(v, str):
                v = getattr(v, "__name__", str(v))
            d[f.name] = v
        d["@layer"] = type(self).__name__
        return d

    @staticmethod
    def from_json(d: dict) -> "Layer":
        from deeplearning4j_tpu.nn import LAYER_REGISTRY
        from deeplearning4j_tpu.train.updaters import IUpdater
        d = dict(d)
        cls = LAYER_REGISTRY[d.pop("@layer")]
        if isinstance(d.get("updater"), dict):
            d["updater"] = IUpdater.from_json(d["updater"])
        for k, v in list(d.items()):
            if isinstance(v, dict) and "@layer" in v:
                d[k] = Layer.from_json(v)
        field_names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in field_names})

