"""User-defined layers — the SameDiff escape hatch and CapsNet.

Reference: `deeplearning4j-nn/.../nn/conf/layers/samediff/
{AbstractSameDiffLayer,SameDiffLayer,SameDiffLambdaLayer}.java` (subclass,
declare parameters, define the forward in SameDiff ops) and
`nn/conf/layers/{PrimaryCapsules,CapsuleLayer,CapsuleStrengthLayer}.java`
(Sabour et al. 2017 dynamic routing, which the reference builds ON SameDiff
layers — the canonical use of the escape hatch).

TPU-native inversion: the "define your layer as a graph" contract becomes
"define your layer as a jax-traceable function".  Subclasses write plain
jnp/lax ops; XLA fuses them into the same compiled train step as the
built-in layers.  Custom subclasses JSON-round-trip like any layer once
registered (`deeplearning4j_tpu.nn.register_layer`), matching the
reference's Jackson-by-class-name behavior.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.core import InputType, Layer
from deeplearning4j_tpu.ops.initializers import init_weights

ShapeSpec = Union[Tuple[int, ...], Tuple[Tuple[int, ...], str]]


@dataclasses.dataclass(kw_only=True)
class SameDiffLayer(Layer):
    """Subclass-and-implement custom layer (reference `SameDiffLayer`):

    - `define_parameters(input_type) -> {name: shape | (shape, init)}`
      (the `defineParameters(SDLayerParams)` role; `init` is a WeightInit
      scheme name, default this layer's `weight_init`)
    - `define_layer(params, x, mask=None) -> y` with jnp ops
      (the `defineLayer(sd, input, params, mask)` role)
    - `get_output_type(input_type)` (defaults to same-as-input)
    """

    REGULARIZABLE: Tuple[str, ...] = ("W",)

    def define_parameters(self, input_type: InputType) -> Dict[str, ShapeSpec]:
        raise NotImplementedError

    def define_layer(self, params, x, mask=None):
        raise NotImplementedError

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def initialize(self, rng, input_type, dtype=jnp.float32):
        params = {}
        for i, (name, spec) in enumerate(
                sorted(self.define_parameters(input_type).items())):
            if (isinstance(spec, tuple) and len(spec) == 2
                    and isinstance(spec[1], str)):
                shape, scheme = spec
            else:
                shape, scheme = spec, self.winit("XAVIER")
            if scheme.upper() == "ZERO":
                params[name] = jnp.zeros(tuple(shape), dtype)
            else:
                params[name] = init_weights(jax.random.fold_in(rng, i),
                                            tuple(shape), scheme, dtype)
        return params, {}, self.get_output_type(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_input_dropout(x, train, rng)
        return self.act_fn()(self.define_layer(params, x, mask=mask)), state


@dataclasses.dataclass(kw_only=True)
class LambdaLayer(Layer):
    """Parameter-free function layer (reference `SameDiffLambdaLayer`).
    Quick inline use: `LambdaLayer(fn=lambda x: x * 2)`.  Inline callables
    cannot survive config JSON (same as the reference's anonymous
    subclasses); subclass and register for serializable models."""

    fn: Optional[Callable[[Any], Any]] = None
    REGULARIZABLE: Tuple[str, ...] = ()

    def call(self, x):
        if self.fn is None:
            raise NotImplementedError("pass fn= or subclass and override call")
        return self.fn(x)

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def initialize(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, self.get_output_type(input_type)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.call(x), state

    def to_json(self) -> dict:
        if type(self) is LambdaLayer and self.fn is not None:
            raise ValueError(
                "LambdaLayer with an inline fn cannot be serialized — "
                "subclass LambdaLayer, override call(), and register_layer "
                "it (reference SameDiffLambdaLayer has the same contract)")
        return super().to_json()


# ---------------------------------------------------------------------------
# CapsNet (Sabour et al. 2017; reference PrimaryCapsules / CapsuleLayer /
# CapsuleStrengthLayer configs, built on the SameDiff escape hatch upstream)
# ---------------------------------------------------------------------------

def _squash(s, axis=-1, eps=1e-8):
    """v = (|s|^2 / (1+|s|^2)) * s/|s| — the capsule nonlinearity."""
    sq = jnp.sum(s * s, axis=axis, keepdims=True)
    return (sq / (1.0 + sq)) * s / jnp.sqrt(sq + eps)


@dataclasses.dataclass(kw_only=True)
class PrimaryCapsules(Layer):
    """Conv → capsule reshape → squash (reference `PrimaryCapsules`):
    a conv2d with `capsules * capsule_dim` filters whose output becomes
    [B, N_caps, capsule_dim] capsule vectors."""

    capsules: int = 8
    capsule_dim: int = 8
    kernel_size: int = 9
    stride: int = 2
    REGULARIZABLE: Tuple[str, ...] = ("W",)

    def initialize(self, rng, input_type, dtype=jnp.float32):
        h, w, c = input_type.shape
        k = int(self.kernel_size)
        n_ch = self.capsules * self.capsule_dim
        params = {"W": init_weights(rng, (k, k, c, n_ch),
                                    self.winit("RELU"), dtype),
                  "b": jnp.zeros((n_ch,), dtype)}
        oh = (h - k) // int(self.stride) + 1
        ow = (w - k) // int(self.stride) + 1
        self._n_caps = oh * ow * self.capsules
        return params, {}, InputType.recurrent(self.capsule_dim,
                                               self._n_caps)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from jax import lax
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(int(self.stride),) * 2,
            padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y + params["b"]
        caps = y.reshape(y.shape[0], -1, self.capsule_dim)
        return _squash(caps), state


@dataclasses.dataclass(kw_only=True)
class CapsuleLayer(Layer):
    """Dynamic-routing capsule layer (reference `CapsuleLayer`, which
    builds the routing loop in SameDiff ops and therefore backprops
    through it — matched here): input [B, N_in, D_in] capsules are
    linearly mapped to per-output predictions and combined over
    `routings` agreement iterations, differentiated end-to-end."""

    capsules: int = 10
    capsule_dim: int = 16
    routings: int = 3
    REGULARIZABLE: Tuple[str, ...] = ("W",)

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n_in, d_in = input_type.shape
        params = {"W": init_weights(
            rng, (n_in, d_in, self.capsules * self.capsule_dim),
            self.winit("XAVIER"), dtype)}
        return params, {}, InputType.recurrent(self.capsule_dim,
                                               self.capsules)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        B, N_in, _ = x.shape
        # predictions u_hat[b, i, j, d]: per-input-capsule votes
        u_hat = jnp.einsum("bni,nio->bno", x, params["W"]).reshape(
            B, N_in, self.capsules, self.capsule_dim)
        logits = jnp.zeros((B, N_in, self.capsules), u_hat.dtype)
        v = None
        for r in range(int(self.routings)):
            c = jax.nn.softmax(logits, axis=-1)          # couple over j
            s = jnp.einsum("bnj,bnjd->bjd", c, u_hat)
            v = _squash(s)
            if r + 1 < self.routings:
                # agreement update; fully differentiated (the routing is a
                # fixed-iteration unrolled loop, finite-difference-checked
                # in tests/test_gradientcheck.py)
                logits = logits + jnp.einsum("bnjd,bjd->bnj", u_hat, v)
        return v, state


@dataclasses.dataclass(kw_only=True)
class CapsuleStrengthLayer(Layer):
    """Capsule length head (reference `CapsuleStrengthLayer`):
    [B, N, D] → [B, N] vector norms = class probabilities."""

    REGULARIZABLE: Tuple[str, ...] = ()

    def initialize(self, rng, input_type, dtype=jnp.float32):
        n, _ = input_type.shape
        return {}, {}, InputType.feed_forward(n)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.sqrt(jnp.sum(x * x, axis=-1) + 1e-8), state
