"""Declare-then-compile graph engine (SameDiff equivalent, reference L3)."""
from deeplearning4j_tpu.autodiff.samediff import (  # noqa: F401
    SameDiff, SDVariable, TrainingConfig)
