"""Op table for the SameDiff-equivalent graph engine.

Replaces the reference's ~500 libnd4j declarable ops
(`libnd4j/include/ops/declarable/generic/**` + the codegen'd Java namespaces
`org/nd4j/autodiff/samediff/ops/SD{Math,NN,CNN,RNN,Loss,...}.java`) with
jax/lax lowerings: each entry is a pure function over jnp arrays; XLA fuses
and differentiates them, so there are no hand-written `doDiff` rules.

The registry covers 400+ of the reference's declarable inventory —
elementwise/reduction/linalg/segment/scatter/image/FFT/random/bitwise/
distance/set/updater/morphology/loss families (SURVEY.md §7 'hard parts
(a)' started minimal; rounds widen it) — and is open: `register_op` adds
more.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OP_TABLE: Dict[str, Callable] = {}


def register_op(name: str, fn: Callable = None):
    if name in OP_TABLE:
        raise ValueError(f"op {name!r} already registered — duplicate "
                         "registrations inflate the op-inventory count")
    if fn is None:
        def deco(f):
            OP_TABLE[name] = f
            return f
        return deco
    OP_TABLE[name] = fn
    return fn


def _axis_tuple(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return (int(axis),)


# ---- elementwise arithmetic ----
register_op("add", lambda a, b: a + b)
register_op("sub", lambda a, b: a - b)
register_op("mul", lambda a, b: a * b)
register_op("div", lambda a, b: a / b)
register_op("rsub", lambda a, b: b - a)
register_op("rdiv", lambda a, b: b / a)
register_op("pow", lambda a, b: a ** b)
register_op("neg", lambda a: -a)
register_op("abs", jnp.abs)
register_op("exp", jnp.exp)
register_op("log", jnp.log)
register_op("log1p", jnp.log1p)
register_op("sqrt", jnp.sqrt)
register_op("square", lambda a: a * a)
register_op("reciprocal", lambda a: 1.0 / a)
register_op("sign", jnp.sign)
register_op("floor", jnp.floor)
register_op("ceil", jnp.ceil)
register_op("round", jnp.round)
register_op("clip", lambda a, lo=None, hi=None: jnp.clip(a, lo, hi))
register_op("maximum", jnp.maximum)
register_op("minimum", jnp.minimum)
register_op("less", lambda a, b: a < b)
register_op("less_equal", lambda a, b: a <= b)
register_op("greater", lambda a, b: a > b)
register_op("greater_equal", lambda a, b: a >= b)
register_op("equal", lambda a, b: a == b)
register_op("not_equal", lambda a, b: a != b)
register_op("logical_and", jnp.logical_and)
register_op("logical_or", jnp.logical_or)
register_op("logical_not", jnp.logical_not)

# ---- trig / hyperbolic ----
for n in ["sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
          "tanh", "asinh", "acosh", "atanh"]:
    register_op(n, getattr(jnp, n))

# ---- comparisons / logic ----
register_op("eq", lambda a, b: (a == b))
register_op("neq", lambda a, b: (a != b))
register_op("gt", lambda a, b: (a > b))
register_op("gte", lambda a, b: (a >= b))
register_op("lt", lambda a, b: (a < b))
register_op("lte", lambda a, b: (a <= b))
register_op("where", jnp.where)
register_op("isnan", jnp.isnan)
register_op("isinf", jnp.isinf)

# ---- reductions ----
register_op("sum", lambda a, axis=None, keepdims=False:
            jnp.sum(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("mean", lambda a, axis=None, keepdims=False:
            jnp.mean(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("max", lambda a, axis=None, keepdims=False:
            jnp.max(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("min", lambda a, axis=None, keepdims=False:
            jnp.min(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("prod", lambda a, axis=None, keepdims=False:
            jnp.prod(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("std", lambda a, axis=None, keepdims=False, ddof=0:
            jnp.std(a, axis=_axis_tuple(axis), keepdims=keepdims, ddof=ddof))
register_op("var", lambda a, axis=None, keepdims=False, ddof=0:
            jnp.var(a, axis=_axis_tuple(axis), keepdims=keepdims, ddof=ddof))
register_op("norm2", lambda a, axis=None, keepdims=False:
            jnp.sqrt(jnp.sum(a * a, axis=_axis_tuple(axis), keepdims=keepdims)))
register_op("argmax", lambda a, axis=-1: jnp.argmax(a, axis=axis))
register_op("argmin", lambda a, axis=-1: jnp.argmin(a, axis=axis))
register_op("cumsum", lambda a, axis=0: jnp.cumsum(a, axis=axis))
register_op("logsumexp", lambda a, axis=None, keepdims=False:
            jax.scipy.special.logsumexp(a, axis=_axis_tuple(axis),
                                        keepdims=keepdims))

# ---- linalg / shape ----
register_op("matmul", jnp.matmul)
register_op("mmul", jnp.matmul)
register_op("tensordot", lambda a, b, axes=2: jnp.tensordot(a, b, axes))
register_op("transpose", lambda a, perm=None: jnp.transpose(a, perm))
register_op("reshape", lambda a, shape: jnp.reshape(a, tuple(shape)))
register_op("permute", lambda a, perm: jnp.transpose(a, perm))
register_op("expand_dims", lambda a, axis=0: jnp.expand_dims(a, axis))
register_op("squeeze", lambda a, axis=None: jnp.squeeze(a, axis))
register_op("concat", lambda *xs, axis=0: jnp.concatenate(xs, axis=axis))
register_op("stack", lambda *xs, axis=0: jnp.stack(xs, axis=axis))
register_op("unstack_at", lambda a, index=0, axis=0:
            lax.index_in_dim(a, index, axis, keepdims=False))
register_op("tile", lambda a, reps: jnp.tile(a, tuple(reps)))
register_op("slice", lambda a, begin, size:
            lax.dynamic_slice(a, tuple(begin), tuple(size)))
register_op("strided_slice", lambda a, begin, end, strides=None:
            a[tuple(slice(b, e, s) for b, e, s in
                    zip(begin, end, strides or [1] * len(begin)))])
register_op("gather", lambda a, idx, axis=0:
            jnp.take(a, idx.astype(jnp.int32), axis=axis))
register_op("one_hot", lambda idx, depth, dtype="float32":
            jax.nn.one_hot(idx, depth, dtype=jnp.dtype(dtype)))
register_op("cast", lambda a, dtype: a.astype(jnp.dtype(dtype)))
register_op("shape_of", lambda a: jnp.asarray(a.shape, jnp.int32))
register_op("zeros_like", jnp.zeros_like)
register_op("zeros_rows_like", lambda a, n: jnp.zeros((a.shape[0], int(n)),
                                                      a.dtype))
register_op("ones_like", jnp.ones_like)
register_op("pad", lambda a, paddings, value=0.0:
            jnp.pad(a, tuple(tuple(p) for p in paddings),
                    constant_values=value))
register_op("identity", lambda a: a)

# ---- nn ----
register_op("relu", jax.nn.relu)
register_op("relu6", jax.nn.relu6)
register_op("leaky_relu", lambda a, alpha=0.01: jax.nn.leaky_relu(a, alpha))
register_op("elu", jax.nn.elu)
register_op("selu", jax.nn.selu)
register_op("gelu", jax.nn.gelu)
register_op("sigmoid", jax.nn.sigmoid)
register_op("softplus", jax.nn.softplus)
register_op("softsign", jax.nn.soft_sign)
register_op("swish", jax.nn.swish)
register_op("hard_sigmoid", jax.nn.hard_sigmoid)
register_op("softmax", lambda a, axis=-1: jax.nn.softmax(a, axis=axis))
register_op("log_softmax", lambda a, axis=-1: jax.nn.log_softmax(a, axis=axis))
register_op("erf", jax.scipy.special.erf)


@register_op("linear")
def _linear(x, w, b=None):
    y = x @ w
    return y if b is None else y + b


@register_op("layer_norm")
def _layer_norm(x, gain, bias=None, eps=1e-5, axis=-1):
    if axis in (-1, x.ndim - 1):
        # measured dispatch: Pallas fused kernel on TPU for big tiling
        # shapes, plain jnp otherwise (norm_kernels._LN_MIN_ROWS policy)
        from deeplearning4j_tpu.ops.norm_kernels import fused_layer_norm
        return fused_layer_norm(x, gain, bias, eps)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps) * gain
    return y if bias is None else y + bias


@register_op("batch_norm")
def _batch_norm(x, mean, var, gamma=None, beta=None, eps=1e-5):
    y = (x - mean) / jnp.sqrt(var + eps)
    if gamma is not None:
        y = y * gamma
    if beta is not None:
        y = y + beta
    return y


@register_op("dropout")
def _dropout(x, rng=None, p=0.5):
    """p = RETAIN probability (reference semantics).  Identity when no rng
    is fed (inference)."""
    if rng is None:
        return x
    keep = jax.random.bernoulli(rng, p, x.shape)
    return jnp.where(keep, x / p, 0.0)


@register_op("embedding_lookup")
def _embedding_lookup(table, idx):
    return table[idx.astype(jnp.int32)]


# ---- cnn (NHWC / HWIO) ----
@register_op("conv2d")
def _conv2d(x, w, b=None, stride=(1, 1), padding="SAME", dilation=(1, 1)):
    # adoption hook (default OFF): when the Pallas conv-backward flags
    # are enabled and the config is the 3x3-s1-SAME ResNet-body shape,
    # route through the custom_vjp whose backward uses the wgrad/dgrad
    # kernels (ops/conv_kernels.py; playbook stage 8 measures before any
    # flip of the default)
    from deeplearning4j_tpu.ops.conv_kernels import (conv3x3_eligible,
                                                     conv3x3_same)
    if conv3x3_eligible(x.shape, w.shape, b, stride, padding, dilation):
        return conv3x3_same(x, w)
    y = lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y if b is None else y + b


@register_op("max_pooling2d")
def _max_pool(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
    from deeplearning4j_tpu.ops.pool_kernels import max_pool2d
    return max_pool2d(x, tuple(kernel), tuple(stride), padding)


@register_op("avg_pooling2d")
def _avg_pool(x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
    dims = (1,) + tuple(kernel) + (1,)
    strides = (1,) + tuple(stride) + (1,)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides,
                          padding)
    return s / c


# ---- attention ----
@register_op("dot_product_attention")
def _dpa(q, k, v, mask=None, scaled=True):
    """[B, T, H] single-head (reference `dotProductAttention` declarable op,
    `libnd4j .../generic/nn/dot_product_attention.cpp`)."""
    scores = q @ jnp.swapaxes(k, -1, -2)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    if mask is not None:
        scores = jnp.where(mask[..., None, :] > 0, scores, -1e9)
    return jax.nn.softmax(scores, axis=-1) @ v


# ---- losses (label-first signature, reference SDLoss convention) ----
@register_op("softmax_cross_entropy")
def _sce(labels, logits, axis=-1):
    return jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits, axis=axis),
                             axis=axis))


@register_op("sparse_softmax_cross_entropy")
def _ssce(labels, logits):
    lp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(lp, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    return -jnp.mean(picked)


@register_op("sigmoid_cross_entropy")
def _sigce(labels, logits):
    return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


@register_op("mean_squared_error")
def _mse(labels, preds):
    return jnp.mean((labels - preds) ** 2)


@register_op("absolute_difference")
def _mae(labels, preds):
    return jnp.mean(jnp.abs(labels - preds))


@register_op("l2_loss")
def _l2(a):
    return 0.5 * jnp.sum(a * a)


@register_op("huber_loss")
def _huber(labels, preds, delta=1.0):
    err = jnp.abs(labels - preds)
    quad = jnp.minimum(err, delta)
    return jnp.mean(0.5 * quad * quad + delta * (err - quad))


@register_op("log_loss")
def _log_loss(labels, probs, eps=1e-7):
    p = jnp.clip(probs, eps, 1 - eps)
    return -jnp.mean(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))


@register_op("cosine_distance")
def _cos_dist(labels, preds, axis=-1, eps=1e-8):
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=axis,
                                              keepdims=True), eps)
    pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=axis,
                                             keepdims=True), eps)
    return jnp.mean(1.0 - jnp.sum(ln * pn, axis=axis))


# ---- control-flow support ----
# Multi-output control-flow nodes (cond/while_loop/scan) cache a Python
# tuple; tuple_get projects one element out at trace time (free under XLA).
register_op("tuple_get", lambda t, index: t[index])


# ---------------------------------------------------------------------------
# ONNX-layout ops (NCHW / OIHW — used by modelimport.onnx_import; the
# reference's equivalent lives in samediff-import-onnx's op mappers).
# XLA is layout-agnostic on TPU, so keeping the imported graph in its
# native NCHW avoids transpose chatter at every boundary.
# ---------------------------------------------------------------------------

@register_op("conv2d_nchw")
def _conv2d_nchw(x, w, b=None, stride=(1, 1), pads=(0, 0, 0, 0),
                 dilation=(1, 1), groups=1):
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(stride),
        padding=((pads[0], pads[2]), (pads[1], pads[3])),
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


@register_op("max_pool2d_nchw")
def _max_pool2d_nchw(x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0)):
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        lax.max, (1, 1) + tuple(kernel), (1, 1) + tuple(stride),
        ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))


@register_op("avg_pool2d_nchw")
def _avg_pool2d_nchw(x, kernel=(2, 2), stride=(2, 2), pads=(0, 0, 0, 0),
                     count_include_pad=False):
    dims = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3]))
    s = lax.reduce_window(x, jnp.zeros((), x.dtype), lax.add, dims, strides,
                          padding)
    if count_include_pad or not any(pads):
        return s / (kernel[0] * kernel[1])
    cnt = lax.reduce_window(jnp.ones_like(x), jnp.zeros((), x.dtype),
                            lax.add, dims, strides, padding)
    return s / cnt


register_op("global_avg_pool_nchw",
            lambda x: jnp.mean(x, axis=(2, 3), keepdims=True))


@register_op("reshape_onnx")
def _reshape_onnx(x, shape):
    # ONNX Reshape: 0 = copy the input dim at that position, -1 = infer.
    shp = [x.shape[i] if s == 0 else int(s) for i, s in enumerate(shape)]
    return jnp.reshape(x, shp)


@register_op("flatten2d")
def _flatten2d(x, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1))


@register_op("gemm")
def _gemm(a, b, c=None, alpha=1.0, beta=1.0, trans_a=0, trans_b=0):
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = alpha * (a @ b)
    if c is not None:
        out = out + beta * c
    return out


@register_op("batch_norm_nchw")
def _batch_norm_nchw(x, scale, b, mean, var, eps=1e-5):
    shp = (1, -1) + (1,) * (x.ndim - 2)
    inv = scale.reshape(shp) * lax.rsqrt(var.reshape(shp) + eps)
    return (x - mean.reshape(shp)) * inv + b.reshape(shp)


@register_op("deconv2d_nchw")
def _deconv2d_nchw(x, w, b=None, stride=(1, 1), pads=(0, 0, 0, 0),
                   dilation=(1, 1), output_padding=(0, 0), groups=1):
    """ONNX ConvTranspose: x [B,Ci,H,W], w [Ci, Co/groups, kh, kw]
    (IOHW — torch's conv_transpose2d layout), gradient-form semantics.
    ONNX pads (t, l, b, r) REMOVE border rows from the full gradient-form
    output; lax.conv_transpose pads the lhs-dilated input, so the mapping
    is (k-1)*dilation - pad per edge, plus output_padding on the
    trailing edges.  Kernel spatially flipped for lax (see deconv2d)."""
    if groups != 1:
        raise NotImplementedError(
            "deconv2d_nchw: grouped ConvTranspose is not supported — "
            "export with group=1")
    kh, kw = w.shape[2], w.shape[3]
    eh = (kh - 1) * dilation[0]
    ew = (kw - 1) * dilation[1]
    pad = ((eh - pads[0], eh - pads[2] + output_padding[0]),
           (ew - pads[1], ew - pads[3] + output_padding[1]))
    y = lax.conv_transpose(
        x, jnp.flip(w, (2, 3)), strides=tuple(stride), padding=pad,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NCHW", "IOHW", "NCHW"))
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


@register_op("split_axis")
def _split_axis(x, sizes, axis=0):
    points = []
    acc = 0
    for s in sizes[:-1]:
        acc += int(s)
        points.append(acc)
    return tuple(jnp.split(x, points, axis=axis))


@register_op("slice_onnx")
def _slice_onnx(x, starts, ends, axes=None, steps=None):
    axes = list(range(len(starts))) if axes is None else [
        int(a) % x.ndim for a in axes]
    steps = [1] * len(starts) if steps is None else [int(s) for s in steps]
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        st, en = int(st), int(en)
        dim = x.shape[ax]
        # ONNX clamps INT64_MIN/MAX sentinels to the dim bounds
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        if en < 0:
            en = max(en + dim, -1 if sp < 0 else 0)
        else:
            en = min(en, dim)
        idx[ax] = slice(st, en if en != -1 else None, sp)
    return x[tuple(idx)]


# ---- TF-import support ops (modelimport.tf_import; BERT-class graphs) ----

register_op("swap_last2", lambda a: jnp.swapaxes(a, -1, -2))
register_op("split_equal", lambda a, num, axis=0:
            tuple(jnp.split(a, num, axis=axis)))


@register_op("tf_strided_slice")
def _tf_strided_slice_op(x, begin, end, strides, begin_mask=0, end_mask=0,
                         ellipsis_mask=0, new_axis_mask=0,
                         shrink_axis_mask=0):
    """TF StridedSlice semantics (masks are bitfields over spec positions)."""
    idx = []
    for i in range(len(begin)):
        if (ellipsis_mask >> i) & 1:
            idx.append(Ellipsis)
        elif (new_axis_mask >> i) & 1:
            idx.append(None)
        elif (shrink_axis_mask >> i) & 1:
            idx.append(int(begin[i]))
        else:
            b = None if (begin_mask >> i) & 1 else int(begin[i])
            e = None if (end_mask >> i) & 1 else int(end[i])
            idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


register_op("floor_div", jnp.floor_divide)   # int-preserving (TF FloorDiv)


# ---------------------------------------------------------------------------
# Extended declarable-op coverage (reference: the wider
# `libnd4j/include/ops/declarable/generic/**` inventory beyond the baseline
# configs — shape/order ops, segment reductions, scatter, linalg, image).
# ---------------------------------------------------------------------------

register_op("expm1", jnp.expm1)
register_op("rsqrt", lambda a: lax.rsqrt(a))
register_op("cbrt", jnp.cbrt)
register_op("erfc", jax.scipy.special.erfc)
register_op("mod", jnp.mod)
register_op("fmod", jnp.fmod)
register_op("squared_difference", lambda a, b: (a - b) ** 2)
register_op("xlogy", jax.scipy.special.xlogy)
register_op("hypot", jnp.hypot)
register_op("atan2", jnp.arctan2)
register_op("digamma", jax.scipy.special.digamma)
register_op("lgamma", jax.scipy.special.gammaln)
register_op("sinc", jnp.sinc)
register_op("rint", jnp.rint)
register_op("trunc", jnp.trunc)
register_op("relu_derivative", lambda a: (a > 0).astype(a.dtype))
register_op("hard_tanh", lambda a: jnp.clip(a, -1.0, 1.0))
register_op("rational_tanh", lambda a: 1.7159 * jnp.tanh(2.0 * a / 3.0))
register_op("rectified_tanh", lambda a: jnp.maximum(0.0, jnp.tanh(a)))
register_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
register_op("gelu_tanh", lambda a: jax.nn.gelu(a, approximate=True))
register_op("thresholded_relu", lambda a, theta=1.0:
            jnp.where(a > theta, a, 0.0))

# order / search
register_op("sort", lambda a, axis=-1, descending=False:
            -jnp.sort(-a, axis=axis) if descending
            else jnp.sort(a, axis=axis))
register_op("argsort", lambda a, axis=-1: jnp.argsort(a, axis=axis))
register_op("top_k", lambda a, k=1: lax.top_k(a, k))
def _unique(a, size=None):
    if size is None:
        raise ValueError(
            "unique needs a static `size` under jit (pad/truncate "
            "semantics of jnp.unique) — pass size=<max distinct>")
    return jnp.unique(a, size=size)


register_op("unique", _unique)
register_op("searchsorted", lambda sorted_seq, values:
            jnp.searchsorted(sorted_seq, values))
register_op("flip", lambda a, axis=None: jnp.flip(a, axis=axis))
register_op("roll", lambda a, shift, axis=None:
            jnp.roll(a, shift, axis=axis))
register_op("diag", jnp.diag)
register_op("diag_part", jnp.diagonal)
register_op("trace", jnp.trace)
register_op("tri", lambda n, m=None, k=0: jnp.tri(n, m, k))
register_op("tril", lambda a, k=0: jnp.tril(a, k))
register_op("triu", lambda a, k=0: jnp.triu(a, k))
register_op("eye", lambda n, m=None, dtype="float32":
            jnp.eye(n, m, dtype=jnp.dtype(dtype)))
register_op("reverse_sequence", lambda a, lengths, seq_axis=1,
            batch_axis=0: _reverse_sequence(a, lengths, seq_axis,
                                            batch_axis))


def _reverse_sequence(a, lengths, seq_axis, batch_axis):
    if batch_axis != 0 or seq_axis != 1:
        raise NotImplementedError(
            "reverse_sequence supports batch_axis=0, seq_axis=1 — "
            "transpose first for other layouts")
    idx = jnp.arange(a.shape[seq_axis])
    rev = lengths[:, None] - 1 - idx[None, :]
    take = jnp.where(rev >= 0, rev, idx[None, :])
    return jnp.take_along_axis(
        a, take.reshape(take.shape + (1,) * (a.ndim - 2))
        if a.ndim > 2 else take, axis=seq_axis)


# segment / scatter
register_op("segment_sum", lambda data, ids, num_segments:
            jax.ops.segment_sum(data, ids, num_segments))
register_op("segment_max", lambda data, ids, num_segments:
            jax.ops.segment_max(data, ids, num_segments))
register_op("segment_min", lambda data, ids, num_segments:
            jax.ops.segment_min(data, ids, num_segments))
register_op("segment_mean", lambda data, ids, num_segments:
            jax.ops.segment_sum(data, ids, num_segments)
            / jnp.maximum(jax.ops.segment_sum(
                jnp.ones(data.shape[0], data.dtype), ids, num_segments),
                1.0).reshape((-1,) + (1,) * (data.ndim - 1)))
register_op("scatter_add", lambda a, idx, updates:
            a.at[idx].add(updates))
register_op("scatter_update", lambda a, idx, updates:
            a.at[idx].set(updates))
register_op("scatter_max", lambda a, idx, updates:
            a.at[idx].max(updates))
register_op("scatter_min", lambda a, idx, updates:
            a.at[idx].min(updates))
register_op("gather_nd", lambda a, idx: a[tuple(jnp.moveaxis(idx, -1, 0))])
register_op("take_along_axis", lambda a, idx, axis=-1:
            jnp.take_along_axis(a, idx, axis=axis))

# linalg (reference generic/linalg/**)
register_op("cholesky", jnp.linalg.cholesky)
register_op("solve", jnp.linalg.solve)
register_op("triangular_solve", lambda a, b, lower=True:
            jax.scipy.linalg.solve_triangular(a, b, lower=lower))
register_op("matrix_inverse", jnp.linalg.inv)
register_op("matrix_determinant", jnp.linalg.det)
register_op("log_matrix_determinant", lambda a:
            jnp.linalg.slogdet(a)[1])
register_op("qr", jnp.linalg.qr)
register_op("svd", jnp.linalg.svd)
register_op("eig_sym", jnp.linalg.eigh)
register_op("lstsq", lambda a, b: jnp.linalg.lstsq(a, b)[0])
register_op("matrix_band_part", lambda a, lower, upper:
            _band_part(a, lower, upper))
register_op("outer", jnp.outer)
register_op("kron", jnp.kron)
register_op("cross", jnp.cross)
register_op("dot", jnp.dot)
register_op("vdot", jnp.vdot)


def _band_part(a, lower, upper):
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if lower >= 0:
        keep &= (i - j) <= lower
    if upper >= 0:
        keep &= (j - i) <= upper
    return jnp.where(keep, a, jnp.zeros((), a.dtype))


# normalization / image
register_op("l2_normalize", lambda a, axis=-1, eps=1e-12:
            a / jnp.maximum(jnp.linalg.norm(a, axis=axis, keepdims=True),
                            eps))
register_op("standardize", lambda a, axis=-1, eps=1e-8:
            (a - jnp.mean(a, axis=axis, keepdims=True))
            / (jnp.std(a, axis=axis, keepdims=True) + eps))
register_op("moments", lambda a, axis=None, keepdims=False:
            (jnp.mean(a, axis=_axis_tuple(axis), keepdims=keepdims),
             jnp.var(a, axis=_axis_tuple(axis), keepdims=keepdims)))
register_op("normalize_moments", lambda count, mean_ss, var_ss, shift=0.0:
            (mean_ss / count + shift,
             var_ss / count - (mean_ss / count) ** 2))
register_op("resize_nearest", lambda a, size:
            jax.image.resize(a, (a.shape[0],) + tuple(size)
                             + (a.shape[-1],), "nearest"))
register_op("resize_bilinear", lambda a, size:
            jax.image.resize(a, (a.shape[0],) + tuple(size)
                             + (a.shape[-1],), "bilinear"))
register_op("image_resize", lambda a, size, method="bilinear":
            jax.image.resize(a, (a.shape[0],) + tuple(size)
                             + (a.shape[-1],), method))
register_op("space_to_depth", lambda a, block_size=2:
            _space_to_depth(a, block_size))
register_op("depth_to_space", lambda a, block_size=2:
            _depth_to_space(a, block_size))


def _space_to_depth(x, b):
    B, H, W, C = x.shape
    x = x.reshape(B, H // b, b, W // b, b, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // b, W // b,
                                                 b * b * C)


def _depth_to_space(x, b):
    B, H, W, C = x.shape
    x = x.reshape(B, H, W, b, b, C // (b * b))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H * b, W * b,
                                                 C // (b * b))


# cumulative / windowed
register_op("cumprod", lambda a, axis=0: jnp.cumprod(a, axis=axis))
register_op("cummax", lambda a, axis=0: lax.cummax(a, axis=axis))
register_op("cummin", lambda a, axis=0: lax.cummin(a, axis=axis))
register_op("count_nonzero", lambda a, axis=None:
            jnp.count_nonzero(a, axis=_axis_tuple(axis)))
register_op("bincount", lambda a, length: jnp.bincount(a, length=length))
register_op("histogram_fixed_width", lambda a, lo, hi, nbins=100:
            jnp.histogram(a, bins=nbins, range=(lo, hi))[0])
register_op("clip_by_norm", lambda a, clip_norm, axis=None:
            a * jnp.minimum(1.0, clip_norm / jnp.maximum(
                jnp.linalg.norm(a, axis=axis, keepdims=axis is not None),
                1e-12)))
register_op("meshgrid", lambda *xs, indexing="xy":
            jnp.meshgrid(*xs, indexing=indexing))
register_op("linspace", lambda start, stop, num=50:
            jnp.linspace(start, stop, num))
register_op("arange", lambda start, stop=None, step=1, dtype="float32":
            jnp.arange(start, stop, step, dtype=jnp.dtype(dtype)))
register_op("full", lambda shape, value, dtype="float32":
            jnp.full(tuple(shape), value, jnp.dtype(dtype)))


@register_op("depthwise_conv2d")
def _depthwise_conv2d(x, w, stride=(1, 1), padding="SAME",
                      dilation=(1, 1)):
    """NHWC x, HWIO w with I=1 grouping per input channel (TF
    DepthwiseConv2dNative filter layout [H, W, C, mult] reshaped by the
    importer to [H, W, 1, C*mult])."""
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1])


# ---------------------------------------------------------------------------
# Wider declarable-op inventory, round 2 (reference: `libnd4j/include/ops/
# declarable/generic/{random,bitwise,broadcastable,images,transforms,
# compat,nn}/**` + `headers/*.h`).  Grouped as upstream groups them.
# ---------------------------------------------------------------------------

# ---- random (reference generic/random/**; rng is an explicit jax PRNG key,
# the functional replacement for libnd4j's RandomGenerator state).  A None
# key falls back to a fixed seed — SameDiff feeds the per-iteration key only
# during fit(), so inference-time output() still samples deterministically.
def _key(rng):
    return jax.random.PRNGKey(0) if rng is None else rng


# key-folding helpers for graph engines feeding ONE per-step key to many
# stochastic nodes: each node folds its own static tag so independent
# random sites draw independent streams.
register_op("rng_fold", lambda rng, tag=0: jax.random.fold_in(_key(rng),
                                                              tag))
# None-preserving variant for dropout-style ops where a missing key means
# "inference — identity", which must survive the fold
register_op("rng_fold_opt", lambda rng, tag=0:
            None if rng is None else jax.random.fold_in(rng, tag))


register_op("random_uniform", lambda rng, shape, minval=0.0, maxval=1.0,
            dtype="float32": jax.random.uniform(
                _key(rng), tuple(shape), jnp.dtype(dtype), minval, maxval))
register_op("random_normal", lambda rng, shape, mean=0.0, stddev=1.0,
            dtype="float32": mean + stddev * jax.random.normal(
                _key(rng), tuple(shape), jnp.dtype(dtype)))
register_op("random_bernoulli", lambda rng, shape, p=0.5:
            jax.random.bernoulli(_key(rng), p, tuple(shape)))
register_op("random_exponential", lambda rng, shape, lam=1.0,
            dtype="float32": jax.random.exponential(
                _key(rng), tuple(shape), jnp.dtype(dtype)) / lam)
register_op("random_gamma", lambda rng, shape, alpha=1.0, beta=1.0,
            dtype="float32": jax.random.gamma(
                _key(rng), alpha, tuple(shape), jnp.dtype(dtype)) / beta)
register_op("random_poisson", lambda rng, shape, lam=1.0:
            jax.random.poisson(_key(rng), lam, tuple(shape)))
register_op("random_shuffle", lambda rng, a, axis=0:
            jax.random.permutation(_key(rng), a, axis=axis))
register_op("multinomial", lambda rng, logits, num_samples:
            jnp.swapaxes(jax.random.categorical(
                _key(rng), logits, axis=-1,
                shape=(num_samples,) + logits.shape[:-1]), 0, -1))
register_op("dropout_inverted", lambda x, rng, p=0.5:
            jnp.where(jax.random.bernoulli(rng, 1.0 - p, x.shape),
                      x / (1.0 - p), 0.0))

# ---- bitwise (reference generic/bitwise/**) ----
register_op("bitwise_and", jnp.bitwise_and)
register_op("bitwise_or", jnp.bitwise_or)
register_op("bitwise_xor", jnp.bitwise_xor)
register_op("bitwise_not", jnp.bitwise_not)
register_op("shift_left", jnp.left_shift)
register_op("shift_right", jnp.right_shift)
@register_op("cyclic_shift_left")
def _cyclic_shift_left(a, n):
    """Rotate bits left by a static int `n` (a full-width logical shift is
    undefined in HLO, so n ≡ 0 (mod width) short-circuits)."""
    bits = a.dtype.itemsize * 8
    n = int(n) % bits
    if n == 0:
        return a
    return (a << n) | lax.shift_right_logical(
        a, jnp.asarray(bits - n, a.dtype))
register_op("bits_hamming_distance", lambda a, b: jnp.sum(
    jax.lax.population_count(jnp.bitwise_xor(a, b))))
register_op("toggle_bits", jnp.bitwise_not)

# ---- unsorted segment reductions (reference generic/transforms/
# unsorted_segment_*.cpp) ----
register_op("unsorted_segment_sum", lambda data, ids, num_segments:
            jax.ops.segment_sum(data, ids, num_segments,
                                indices_are_sorted=False))
register_op("unsorted_segment_max", lambda data, ids, num_segments:
            jax.ops.segment_max(data, ids, num_segments,
                                indices_are_sorted=False))
register_op("unsorted_segment_min", lambda data, ids, num_segments:
            jax.ops.segment_min(data, ids, num_segments,
                                indices_are_sorted=False))
register_op("unsorted_segment_prod", lambda data, ids, num_segments:
            jax.ops.segment_prod(data, ids, num_segments,
                                 indices_are_sorted=False))


@register_op("unsorted_segment_mean")
def _unsorted_segment_mean(data, ids, num_segments):
    s = jax.ops.segment_sum(data, ids, num_segments)
    n = jax.ops.segment_sum(jnp.ones(data.shape[0], data.dtype), ids,
                            num_segments)
    return s / jnp.maximum(n, 1.0).reshape((-1,) + (1,) * (data.ndim - 1))


@register_op("unsorted_segment_sqrt_n")
def _unsorted_segment_sqrt_n(data, ids, num_segments):
    s = jax.ops.segment_sum(data, ids, num_segments)
    n = jax.ops.segment_sum(jnp.ones(data.shape[0], data.dtype), ids,
                            num_segments)
    return s / jnp.sqrt(jnp.maximum(n, 1.0)).reshape(
        (-1,) + (1,) * (data.ndim - 1))


# ---- scatter breadth (reference generic/transforms/scatter_*.cpp) ----
register_op("scatter_sub", lambda a, idx, updates: a.at[idx].add(-updates))
register_op("scatter_mul", lambda a, idx, updates:
            a.at[idx].multiply(updates))
register_op("scatter_div", lambda a, idx, updates:
            a.at[idx].divide(updates))
register_op("scatter_nd", lambda idx, updates, shape:
            jnp.zeros(tuple(shape), updates.dtype)
            .at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates))
register_op("scatter_nd_add", lambda a, idx, updates:
            a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates))
register_op("scatter_nd_sub", lambda a, idx, updates:
            a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(-updates))
register_op("scatter_nd_update", lambda a, idx, updates:
            a.at[tuple(jnp.moveaxis(idx, -1, 0))].set(updates))


@register_op("dynamic_stitch")
def _dynamic_stitch(indices, data):
    """TF DynamicStitch: merge `data[i]` rows at positions `indices[i]`
    (lists of equal length).  Output length is max(index)+1 when the
    indices are graph-time constants (the TF norm); under a jit trace the
    data-dependent size is unknowable, so it falls back to the total index
    count (correct whenever indices form a permutation)."""
    try:
        n = max(int(jnp.max(i)) for i in indices) + 1
    except (jax.errors.ConcretizationTypeError,
            jax.errors.TracerIntegerConversionError):
        n = sum(int(i.size) for i in indices)
    first = data[0]
    out = jnp.zeros((n,) + first.shape[1:], first.dtype)
    for idx, d in zip(indices, data):
        out = out.at[idx.reshape(-1)].set(
            d.reshape((-1,) + first.shape[1:]))
    return out


# ---- reduce3 / distance ops (reference `libnd4j/include/loops/reduce3.h`:
# the pairwise-reduction family) ----
register_op("euclidean_distance", lambda a, b, axis=None:
            jnp.sqrt(jnp.sum((a - b) ** 2, axis=_axis_tuple(axis))))
register_op("manhattan_distance", lambda a, b, axis=None:
            jnp.sum(jnp.abs(a - b), axis=_axis_tuple(axis)))
register_op("cosine_similarity", lambda a, b, axis=-1, eps=1e-12:
            jnp.sum(a * b, axis=axis)
            / jnp.maximum(jnp.linalg.norm(a, axis=axis)
                          * jnp.linalg.norm(b, axis=axis), eps))
register_op("jaccard_distance", lambda a, b, axis=None:
            1.0 - jnp.sum(jnp.minimum(a, b), axis=_axis_tuple(axis))
            / jnp.maximum(jnp.sum(jnp.maximum(a, b),
                                  axis=_axis_tuple(axis)), 1e-12))
register_op("hamming_distance", lambda a, b, axis=None:
            jnp.sum((a != b).astype(jnp.float32), axis=_axis_tuple(axis)))

# ---- reduction breadth (reference loops/reduce_*.h + generic/reduce/**) ----
register_op("amax", lambda a, axis=None, keepdims=False:
            jnp.max(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("amin", lambda a, axis=None, keepdims=False:
            jnp.min(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("asum", lambda a, axis=None, keepdims=False:
            jnp.sum(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("amean", lambda a, axis=None, keepdims=False:
            jnp.mean(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("norm1", lambda a, axis=None, keepdims=False:
            jnp.sum(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("norm_max", lambda a, axis=None, keepdims=False:
            jnp.max(jnp.abs(a), axis=_axis_tuple(axis), keepdims=keepdims))
register_op("reduce_any", lambda a, axis=None, keepdims=False:
            jnp.any(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("reduce_all", lambda a, axis=None, keepdims=False:
            jnp.all(a, axis=_axis_tuple(axis), keepdims=keepdims))
register_op("entropy", lambda a, axis=None:
            -jnp.sum(a * jnp.log(jnp.maximum(a, 1e-12)),
                     axis=_axis_tuple(axis)))
register_op("log_entropy", lambda a, axis=None:
            jnp.log(-jnp.sum(a * jnp.log(jnp.maximum(a, 1e-12)),
                             axis=_axis_tuple(axis))))
register_op("shannon_entropy", lambda a, axis=None:
            -jnp.sum(a * jnp.log2(jnp.maximum(a, 1e-12)),
                     axis=_axis_tuple(axis)))
register_op("zero_fraction", lambda a:
            jnp.mean((a == 0).astype(jnp.float32)))
register_op("square_sum", lambda a, axis=None, keepdims=False:
            jnp.sum(a * a, axis=_axis_tuple(axis), keepdims=keepdims))


@register_op("percentile")
def _percentile(a, q, axis=None, interpolation="linear"):
    return jnp.percentile(a, q, axis=_axis_tuple(axis),
                          method=interpolation)


register_op("median", lambda a, axis=None:
            jnp.median(a, axis=_axis_tuple(axis)))


@register_op("nth_element")
def _nth_element(a, n, reverse=False):
    s = jnp.sort(a, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return lax.index_in_dim(s, n, axis=-1, keepdims=False)


# ---- image ops (reference generic/images/**: colorspace conversions,
# crop_and_resize, extract_image_patches, non_max_suppression) ----
@register_op("rgb_to_grs")
def _rgb_to_grs(x):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@register_op("rgb_to_hsv")
def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.max(x, axis=-1)
    mn = jnp.min(x, axis=-1)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
    h = jnp.where(d == 0, 0.0, h) / 6.0
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@register_op("hsv_to_rgb")
def _hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


# Kept as numpy (not jnp): a module-level jnp constant would initialize
# the JAX backend at import time, before callers can select a platform.
_YIQ = np.asarray([[0.299, 0.587, 0.114],
                   [0.5959, -0.2746, -0.3213],
                   [0.2115, -0.5227, 0.3112]], np.float32)
register_op("rgb_to_yiq", lambda x: x @ jnp.asarray(_YIQ.T, x.dtype))
register_op("yiq_to_rgb", lambda x:
            x @ jnp.asarray(np.linalg.inv(_YIQ).T, x.dtype))
_YUV = np.asarray([[0.299, 0.587, 0.114],
                   [-0.14714119, -0.28886916, 0.43601035],
                   [0.61497538, -0.51496512, -0.10001026]], np.float32)
register_op("rgb_to_yuv", lambda x: x @ jnp.asarray(_YUV.T, x.dtype))
register_op("yuv_to_rgb", lambda x:
            x @ jnp.asarray(np.linalg.inv(_YUV).T, x.dtype))


@register_op("adjust_hue")
def _adjust_hue(x, delta):
    hsv = _rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


@register_op("adjust_saturation")
def _adjust_saturation(x, factor):
    hsv = _rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@register_op("adjust_contrast")
def _adjust_contrast(x, factor):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@register_op("crop_and_resize")
def _crop_and_resize(image, boxes, box_indices, crop_size,
                     method="bilinear"):
    """[B,H,W,C] image + normalized [N,4] (y1,x1,y2,x2) boxes (TF/reference
    CropAndResize semantics)."""
    ch, cw = crop_size

    def one(box, bi):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        img = image[bi]
        h, w = image.shape[1], image.shape[2]
        # size-1 crops sample the box CENTER (TF CropAndResize contract),
        # not the top-left corner
        if ch == 1:
            ys = (y1 + y2) / 2 * (h - 1) + jnp.zeros(1)
        else:
            ys = y1 * (h - 1) + jnp.arange(ch) / (ch - 1) \
                * (y2 - y1) * (h - 1)
        if cw == 1:
            xs = (x1 + x2) / 2 * (w - 1) + jnp.zeros(1)
        else:
            xs = x1 * (w - 1) + jnp.arange(cw) / (cw - 1) \
                * (x2 - x1) * (w - 1)
        if method == "nearest":
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, w - 1)
            return img[yi][:, xi]
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        return ((1 - wy) * (1 - wx) * img[y0][:, x0]
                + (1 - wy) * wx * img[y0][:, x1i]
                + wy * (1 - wx) * img[y1i][:, x0]
                + wy * wx * img[y1i][:, x1i])

    return jax.vmap(one)(boxes, box_indices.astype(jnp.int32))


@register_op("extract_image_patches")
def _extract_image_patches(x, ksizes, strides=(1, 1), rates=(1, 1),
                           padding="VALID"):
    """NHWC → [B, OH, OW, kh*kw*C] (TF ExtractImagePatches / the im2col
    declarable op's public face)."""
    kh, kw = ksizes
    c = x.shape[-1]
    ident = jnp.eye(kh * kw * c, dtype=x.dtype).reshape(
        kh, kw, c, kh * kw * c)
    return lax.conv_general_dilated(
        x, ident, window_strides=tuple(strides), padding=padding,
        rhs_dilation=tuple(rates),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@register_op("non_max_suppression")
def _non_max_suppression(boxes, scores, max_output_size,
                         iou_threshold=0.5, score_threshold=-jnp.inf):
    """Greedy NMS over [N,4] (y1,x1,y2,x2) boxes; returns fixed-size index
    array padded with -1 (static shapes for jit)."""
    n = boxes.shape[0]
    y1, x1, y2, x2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j])
        xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j])
        xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area[j] - inter, 1e-12)

    live = scores > score_threshold

    def body(state, _):
        live, sel_scores = state
        best = jnp.argmax(jnp.where(live, sel_scores, -jnp.inf))
        ok = live[best]
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(n))
        live = live & (ious <= iou_threshold)
        live = live.at[best].set(False)
        return (live, sel_scores), jnp.where(ok, best, -1)

    (_, _), picked = lax.scan(body, (live, scores), None,
                              length=max_output_size)
    return picked


# ---- spatial / shape breadth ----
register_op("broadcast_to", lambda a, shape:
            jnp.broadcast_to(a, tuple(shape)))
register_op("repeat", lambda a, repeats, axis=None:
            jnp.repeat(a, repeats, axis=axis))
register_op("mirror_pad", lambda a, paddings, mode="REFLECT":
            jnp.pad(a, tuple(tuple(p) for p in paddings),
                    mode="reflect" if mode.upper() == "REFLECT"
                    else "symmetric"))


@register_op("sequence_mask")
def _sequence_mask(lengths, maxlen, dtype="float32"):
    return (jnp.arange(maxlen)[None, :]
            < lengths.reshape(-1, 1)).astype(jnp.dtype(dtype))


@register_op("space_to_batch")
def _space_to_batch(x, block=2, paddings=((0, 0), (0, 0))):
    B, H, W, C = x.shape
    x = jnp.pad(x, ((0, 0), tuple(paddings[0]), tuple(paddings[1]),
                    (0, 0)))
    H2, W2 = x.shape[1], x.shape[2]
    x = x.reshape(B, H2 // block, block, W2 // block, block, C)
    return x.transpose(2, 4, 0, 1, 3, 5).reshape(
        block * block * B, H2 // block, W2 // block, C)


@register_op("batch_to_space")
def _batch_to_space(x, block=2, crops=((0, 0), (0, 0))):
    NB, H, W, C = x.shape
    B = NB // (block * block)
    x = x.reshape(block, block, B, H, W, C)
    x = x.transpose(2, 3, 0, 4, 1, 5).reshape(B, H * block, W * block, C)
    (ct, cb), (cl, cr) = crops
    return x[:, ct:x.shape[1] - cb or None, cl:x.shape[2] - cr or None]


@register_op("upsampling2d")
def _upsampling2d(x, scale=2):
    return jnp.repeat(jnp.repeat(x, scale, axis=1), scale, axis=2)


@register_op("im2col")
def _im2col(x, kh, kw, sh=1, sw=1, ph=0, pw=0, dh=1, dw=1):
    """NHWC → [B, OH, OW, kh, kw, C] (reference generic/nn/im2col)."""
    pads = "VALID" if (ph, pw) == (0, 0) else [(ph, ph), (pw, pw)]
    patches = _extract_image_patches(
        x, (kh, kw), (sh, sw), (dh, dw),
        pads if isinstance(pads, str) else pads)
    b, oh, ow, _ = patches.shape
    return patches.reshape(b, oh, ow, kh, kw, x.shape[-1])


# ---- nn breadth (conv3d/pool3d/deconv/lrn/prelu/gru) ----
@register_op("conv3d")
def _conv3d(x, w, b=None, stride=(1, 1, 1), padding="SAME",
            dilation=(1, 1, 1)):
    """NDHWC x, DHWIO w (reference generic/nn/convo/conv3d.cpp)."""
    y = lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return y if b is None else y + b


@register_op("deconv2d")
def _deconv2d(x, w, b=None, stride=(2, 2), padding="SAME"):
    """Gradient-form transposed conv (reference deconv2d.cpp; same
    convention as TF/Keras/torch).  lax.conv_transpose slides the kernel
    in CORRELATION orientation over the dilated input — spatially flipped
    relative to the gradient form — so flip here (validated against a
    scatter-accumulate golden in tests/opval_specs_nn.py)."""
    y = lax.conv_transpose(x, jnp.flip(w, (0, 1)), strides=tuple(stride),
                           padding=padding,
                           dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y if b is None else y + b


@register_op("max_pooling3d")
def _max_pool3d(x, kernel=(2, 2, 2), stride=(2, 2, 2), padding="VALID"):
    return lax.reduce_window(x, -jnp.inf, lax.max,
                             (1,) + tuple(kernel) + (1,),
                             (1,) + tuple(stride) + (1,), padding)


@register_op("avg_pooling3d")
def _avg_pool3d(x, kernel=(2, 2, 2), stride=(2, 2, 2), padding="VALID"):
    dims = (1,) + tuple(kernel) + (1,)
    strides = (1,) + tuple(stride) + (1,)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides,
                          padding)
    return s / c


@register_op("lrn")
def _lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    half = n // 2
    sq = x * x
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    win = sum(padded[..., i:i + x.shape[-1]] for i in range(n))
    return x / (k + alpha * win) ** beta


register_op("prelu", lambda x, alpha:
            jnp.where(x >= 0, x, alpha * x))
register_op("log_sigmoid", jax.nn.log_sigmoid)
register_op("hard_swish", jax.nn.hard_swish)
register_op("celu", lambda a, alpha=1.0: jax.nn.celu(a, alpha))
register_op("glu", lambda a, axis=-1: jax.nn.glu(a, axis))


@register_op("gru_cell")
def _gru_cell(x, h, w_ih, w_hh, b_ih=None, b_hh=None):
    """Single GRU step, gate order [reset, update, new] (reference
    generic/nn/recurrent/gruCell.cpp)."""
    gi = x @ w_ih + (0 if b_ih is None else b_ih)
    gh = h @ w_hh + (0 if b_hh is None else b_hh)
    H = h.shape[-1]
    r = jax.nn.sigmoid(gi[..., :H] + gh[..., :H])
    z = jax.nn.sigmoid(gi[..., H:2 * H] + gh[..., H:2 * H])
    n = jnp.tanh(gi[..., 2 * H:] + r * gh[..., 2 * H:])
    return (1 - z) * n + z * h


@register_op("lstm_cell")
def _lstm_cell(x, h, c, w_ih, w_hh, b=None):
    """Single LSTM step, IFCO gate order (reference lstmCell; the layer-level
    scan lives in `nn/recurrent.py`)."""
    g = x @ w_ih + h @ w_hh + (0 if b is None else b)
    H = h.shape[-1]
    i = jax.nn.sigmoid(g[..., :H])
    f = jax.nn.sigmoid(g[..., H:2 * H])
    cc = jnp.tanh(g[..., 2 * H:3 * H])
    o = jax.nn.sigmoid(g[..., 3 * H:])
    c_new = f * c + i * cc
    return o * jnp.tanh(c_new), c_new


# ---- special functions (reference generic/parity_ops + transforms) ----
register_op("betainc", jax.scipy.special.betainc)
register_op("polygamma", lambda n, x: jax.scipy.special.polygamma(n, x))
register_op("zeta", lambda x, q: jax.scipy.special.zeta(x, q))
register_op("igamma", jax.scipy.special.gammainc)
register_op("igammac", jax.scipy.special.gammaincc)


# ---- matrix breadth ----
@register_op("matrix_diag")
def _matrix_diag(d):
    return d[..., :, None] * jnp.eye(d.shape[-1], dtype=d.dtype)


register_op("matrix_diag_part", lambda a:
            jnp.diagonal(a, axis1=-2, axis2=-1))


@register_op("matrix_set_diag")
def _matrix_set_diag(a, d):
    m, n = a.shape[-2], a.shape[-1]
    k = min(m, n)
    dk = d[..., :k]
    if n > k:
        dk = jnp.concatenate(
            [dk, jnp.zeros(dk.shape[:-1] + (n - k,), dk.dtype)], axis=-1)
    # at (i, j) with i == j the broadcast row picks dk[..., j] == d[..., i]
    return jnp.where(jnp.eye(m, n, dtype=bool), dk[..., None, :], a)


register_op("lu", jax.scipy.linalg.lu)
register_op("pinv", jnp.linalg.pinv)
register_op("expm", jax.scipy.linalg.expm)
def _einsum(*args, equation=None):
    """Equation as first positional (numpy style) OR as the `equation`
    kwarg (graph engines can't pass strings positionally — sd.op turns
    positional non-variables into constants)."""
    if equation is None:
        equation, args = args[0], args[1:]
    return jnp.einsum(equation, *args)


register_op("einsum", _einsum)
register_op("norm_fro", lambda a: jnp.linalg.norm(a))


# ---- compare / classification helpers (reference compat/** + parity) ----
@register_op("is_max")
def _is_max(a, axis=-1):
    # exactly ONE element marked per slice (reference IsMax contract);
    # argmax breaks value ties toward the lower index
    idx = jnp.argmax(a, axis=axis)
    n = a.shape[axis]
    onehot = jax.nn.one_hot(idx, n, dtype=a.dtype)
    return jnp.moveaxis(onehot, -1, axis)


@register_op("in_top_k")
def _in_top_k(predictions, targets, k=1):
    target_logits = jnp.take_along_axis(
        predictions, targets[:, None].astype(jnp.int32), axis=-1)
    return jnp.sum((predictions > target_logits).astype(jnp.int32),
                   axis=-1) < k


@register_op("confusion_matrix")
def _confusion_matrix(labels, predictions, num_classes, weights=None):
    idx = labels.astype(jnp.int32) * num_classes \
        + predictions.astype(jnp.int32)
    w = jnp.ones_like(idx, jnp.float32) if weights is None else weights
    flat = jnp.zeros(num_classes * num_classes, w.dtype).at[idx].add(w)
    return flat.reshape(num_classes, num_classes)


register_op("assign", lambda a, b: jnp.broadcast_to(b, a.shape))
register_op("compare_and_set", lambda a, compare, set_val, eps=1e-7:
            jnp.where(jnp.abs(a - compare) < eps, set_val, a))
register_op("clip_by_value", lambda a, lo, hi: jnp.clip(a, lo, hi))
register_op("clip_by_global_norm", lambda norm_cap, *xs: tuple(
    x * jnp.minimum(1.0, norm_cap / jnp.maximum(
        jnp.sqrt(sum(jnp.sum(y * y) for y in xs)), 1e-12)) for x in xs))


# ---- loss breadth (reference SDLoss / generic/loss/**) ----
@register_op("hinge_loss")
def _hinge_loss(labels, logits):
    """labels in {0,1} (reference hingeLoss converts to ±1)."""
    signed = 2.0 * labels - 1.0
    return jnp.mean(jnp.maximum(0.0, 1.0 - signed * logits))


@register_op("weighted_cross_entropy_with_logits")
def _weighted_xent(labels, logits, pos_weight):
    log_w = 1.0 + (pos_weight - 1.0) * labels
    return jnp.mean((1 - labels) * logits + log_w * (
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
        + jnp.maximum(-logits, 0.0)))


@register_op("poisson_loss")
def _poisson_loss(labels, preds, log_input=False, eps=1e-8):
    if log_input:
        return jnp.mean(jnp.exp(preds) - labels * preds)
    return jnp.mean(preds - labels * jnp.log(preds + eps))


@register_op("kl_divergence")
def _kl_divergence(labels, preds, eps=1e-12):
    p = jnp.clip(labels, eps, 1.0)
    q = jnp.clip(preds, eps, 1.0)
    return jnp.sum(p * (jnp.log(p) - jnp.log(q)), axis=-1).mean()


# ---- signal / FFT (reference generic/fft/** + helpers) ----
register_op("fft", lambda a, axis=-1: jnp.fft.fft(a, axis=axis))
register_op("ifft", lambda a, axis=-1: jnp.fft.ifft(a, axis=axis))
register_op("rfft", lambda a, axis=-1: jnp.fft.rfft(a, axis=axis))
register_op("irfft", lambda a, n=None, axis=-1:
            jnp.fft.irfft(a, n=n, axis=axis))
register_op("fft2", lambda a: jnp.fft.fft2(a))
register_op("ifft2", lambda a: jnp.fft.ifft2(a))


# ---- image transforms (reference generic/images/** continued) ----
register_op("image_flip_left_right", lambda a: jnp.flip(a, axis=-2))
register_op("image_flip_up_down", lambda a: jnp.flip(a, axis=-3))
register_op("image_rot90", lambda a, k=1:
            jnp.rot90(a, k, axes=(-3, -2)))


@register_op("per_image_standardization")
def _per_image_standardization(x):
    axes = tuple(range(1, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    n = 1
    for d in x.shape[1:]:
        n *= d
    std = jnp.maximum(jnp.std(x, axis=axes, keepdims=True),
                      1.0 / jnp.sqrt(float(n)))
    return (x - mean) / std


@register_op("image_central_crop")
def _image_central_crop(x, fraction):
    """TF central_crop semantics: the OFFSET is floored first and the
    remainder pixel is kept (h=5, fraction=0.5 → offset 1, size 3)."""
    h, w = x.shape[-3], x.shape[-2]
    t = int((h - h * fraction) / 2)
    l = int((w - w * fraction) / 2)
    return x[..., t:h - t, l:w - l, :]


@register_op("random_crop")
def _random_crop(rng, x, size):
    """Crop `size` (per-axis) at a random offset (reference
    generic/random/random_crop.cpp)."""
    k = _key(rng)
    starts = []
    for i, (d, s) in enumerate(zip(x.shape, size)):
        sub = jax.random.fold_in(k, i)
        starts.append(jax.random.randint(sub, (), 0, d - s + 1))
    return lax.dynamic_slice(x, starts, tuple(size))


# ---- bit manipulation (reference transforms/bitcast + compat) ----
register_op("bitcast", lambda a, dtype:
            lax.bitcast_convert_type(a, jnp.dtype(dtype)))
register_op("population_count", lambda a: lax.population_count(a))


# ---- set / search ops (static-size contracts under jit, like `unique`) ----
@register_op("unique_with_counts")
def _unique_with_counts(a, size=None):
    if size is None:
        raise ValueError("unique_with_counts needs static `size` under jit")
    vals, counts = jnp.unique(a, size=size, return_counts=True)
    return vals, counts


@register_op("setdiff1d")
def _setdiff1d(a, b, size=None):
    """Elements of `a` not in `b` (TF ListDiff), padded to `size` with the
    first kept element (size should be the true difference count)."""
    if size is None:
        raise ValueError("setdiff1d needs static `size` under jit")
    keep = ~jnp.isin(a, b)
    first_kept = jnp.argmax(keep)        # index of the first True
    idx = jnp.nonzero(keep, size=size, fill_value=first_kept)[0]
    return a[idx]


@register_op("nonzero")
def _nonzero(a, size=None):
    if size is None:
        raise ValueError("nonzero needs static `size` under jit")
    return jnp.stack(jnp.nonzero(a, size=size), axis=-1)


register_op("isin", lambda a, b: jnp.isin(a, b))
register_op("equals_with_eps", lambda a, b, eps=1e-5:
            jnp.all(jnp.abs(a - b) <= eps))
register_op("isclose", lambda a, b, rtol=1e-5, atol=1e-8:
            jnp.isclose(a, b, rtol, atol))
register_op("is_finite", jnp.isfinite)
register_op("is_finite_all", lambda a: jnp.all(jnp.isfinite(a)))


# ---- scatter/segment completions ----
register_op("scatter_nd_min", lambda a, idx, updates:
            a.at[tuple(jnp.moveaxis(idx, -1, 0))].min(updates))
register_op("scatter_nd_max", lambda a, idx, updates:
            a.at[tuple(jnp.moveaxis(idx, -1, 0))].max(updates))
register_op("segment_prod", lambda data, ids, num_segments:
            jax.ops.segment_prod(data, ids, num_segments))


# ---- shape / layout completions ----
register_op("unstack", lambda a, axis=0: tuple(
    jnp.squeeze(s, axis=axis)
    for s in jnp.split(a, a.shape[axis], axis=axis)))
register_op("size_of", lambda a: jnp.asarray(a.size, jnp.int32))
register_op("rank_of", lambda a: jnp.asarray(a.ndim, jnp.int32))
register_op("eye_like", lambda a: jnp.eye(a.shape[-2], a.shape[-1],
                                          dtype=a.dtype))
register_op("fill_like", lambda a, value: jnp.full_like(a, value))
register_op("swap_axes", lambda a, axis1, axis2:
            jnp.swapaxes(a, axis1, axis2))
register_op("moveaxis", lambda a, source, destination:
            jnp.moveaxis(a, source, destination))
register_op("atleast_2d", jnp.atleast_2d)
register_op("ravel", jnp.ravel)


@register_op("pad_mode")
def _pad_mode(a, paddings, mode="constant", value=0.0):
    """Generalized pad (constant/reflect/symmetric/edge — the reference's
    pad op mode attr)."""
    pads = tuple(tuple(p) for p in paddings)
    if mode == "constant":
        return jnp.pad(a, pads, constant_values=value)
    return jnp.pad(a, pads, mode=mode)


@register_op("cumsum_ext")
def _cumsum_ext(a, axis=0, exclusive=False, reverse=False):
    """TF-style cumsum with exclusive/reverse attrs (the reference cumsum
    declarable op's full contract)."""
    if reverse:
        a = jnp.flip(a, axis=axis)
    out = jnp.cumsum(a, axis=axis)
    if exclusive:
        out = out - a
    if reverse:
        out = jnp.flip(out, axis=axis)
    return out


# ---- updater ops (reference generic/updaters/{sgd,rmsProp,adam,adaGrad,
# adaMax,adaDelta,nadam,amsGrad,nesterovs}Updater.cpp — the functional
# faces of the optimizer family; stateful use lives in train/updaters.py)
register_op("sgd_updater", lambda g, lr=0.01: g * lr)


@register_op("nesterovs_updater")
def _nesterovs_updater(g, v, lr=0.1, momentum=0.9):
    """Returns (update-to-subtract, new velocity) — same contract as
    train/updaters.Nesterovs."""
    v_new = momentum * v - lr * g
    return momentum * v - (1 + momentum) * v_new, v_new


@register_op("adam_updater")
def _adam_updater(g, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    t1 = t + 1
    mhat = m_new / (1 - beta1 ** t1)
    vhat = v_new / (1 - beta2 ** t1)
    return lr * mhat / (jnp.sqrt(vhat) + eps), m_new, v_new


@register_op("rms_prop_updater")
def _rms_prop_updater(g, s, lr=1e-3, decay=0.95, eps=1e-8):
    s_new = decay * s + (1 - decay) * g * g
    return lr * g / jnp.sqrt(s_new + eps), s_new


@register_op("ada_grad_updater")
def _ada_grad_updater(g, h, lr=1e-2, eps=1e-6):
    h_new = h + g * g
    return lr * g / (jnp.sqrt(h_new) + eps), h_new


@register_op("ada_delta_updater")
def _ada_delta_updater(g, msg, msdx, rho=0.95, eps=1e-6):
    msg_new = rho * msg + (1 - rho) * g * g
    dx = jnp.sqrt(msdx + eps) / jnp.sqrt(msg_new + eps) * g
    return dx, msg_new, rho * msdx + (1 - rho) * dx * dx


@register_op("ada_max_updater")
def _ada_max_updater(g, m, u, t, lr=2e-3, beta1=0.9, beta2=0.999,
                     eps=1e-8):
    m_new = beta1 * m + (1 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    return (lr / (1 - beta1 ** (t + 1))) * m_new / (u_new + eps), \
        m_new, u_new


@register_op("nadam_updater")
def _nadam_updater(g, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    t1 = t + 1
    mhat = m_new / (1 - beta1 ** t1)
    vhat = v_new / (1 - beta2 ** t1)
    return lr * (beta1 * mhat + (1 - beta1) * g / (1 - beta1 ** t1)) \
        / (jnp.sqrt(vhat) + eps), m_new, v_new


@register_op("ams_grad_updater")
def _ams_grad_updater(g, m, v, vhat, t, lr=1e-3, beta1=0.9, beta2=0.999,
                      eps=1e-8):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    vhat_new = jnp.maximum(vhat, v_new)
    return lr * m_new / (jnp.sqrt(vhat_new) + eps), m_new, v_new, vhat_new


# ---- rnn: whole-sequence GRU (reference generic/nn/recurrent/gru.cpp) ----
@register_op("gru_layer")
def _gru_layer(x, h0, w_ih, w_hh, b_ih=None, b_hh=None):
    """[B, T, F] → [B, T, H] via lax.scan of gru_cell."""
    cell = OP_TABLE["gru_cell"]

    def step(h, xt):
        h_new = cell(xt, h, w_ih, w_hh, b_ih, b_hh)
        return h_new, h_new

    _, ys = lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1)


# ---- morphology / pooling extras ----
@register_op("dilation2d")
def _dilation2d(x, filt, stride=(1, 1), padding="SAME"):
    """Grayscale morphological dilation (TF Dilation2D / reference
    generic/nn/dilation2d.cpp): max over window of (x + filter).  SAME
    borders pad with dtype-min (the morphological identity), matching TF —
    zero-padding would corrupt borders of negative feature maps."""
    kh, kw, c = filt.shape
    if padding == "SAME":
        H, W = x.shape[1], x.shape[2]
        sh, sw = stride
        oh, ow = -(-H // sh), -(-W // sw)
        ph = max((oh - 1) * sh + kh - H, 0)
        pw = max((ow - 1) * sw + kw - W, 0)
        neg = (jnp.finfo(x.dtype).min
               if jnp.issubdtype(x.dtype, jnp.floating)
               else jnp.iinfo(x.dtype).min)
        x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                        (pw // 2, pw - pw // 2), (0, 0)),
                    constant_values=neg)
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(stride), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    B, OH, OW, _ = patches.shape
    # patches feature axis is ordered [c, kh, kw]
    p = patches.reshape(B, OH, OW, c, kh * kw)
    f = filt.transpose(2, 0, 1).reshape(c, kh * kw)
    return jnp.max(p + f[None, None, None], axis=-1)


@register_op("max_pool_with_argmax")
def _max_pool_with_argmax(x, kernel=(2, 2), stride=(2, 2),
                          padding="VALID"):
    """Returns (pooled, argmax indices) with the TF MaxPoolWithArgmax
    contract (include_batch_in_index=False): index = (h*W + w)*C + c."""
    B, H, W, C = x.shape
    hw = jnp.arange(H * W).reshape(1, H, W, 1)
    ch = jnp.arange(C).reshape(1, 1, 1, C)
    flat_idx = jnp.broadcast_to(hw * C + ch, x.shape).astype(jnp.int32)
    kh, kw = kernel

    if jnp.issubdtype(x.dtype, jnp.integer):
        lowest = jnp.iinfo(x.dtype).min
    else:
        lowest = -jnp.inf
    dims = (1, kh, kw, 1)
    strides = (1,) + tuple(stride) + (1,)

    # values via a plain max reduce_window — differentiable (the variadic
    # value+index reduce below has no JVP, so it runs under stop_gradient
    # purely to produce the argmax)
    vals = lax.reduce_window(x, jnp.asarray(lowest, x.dtype), lax.max,
                             dims, strides, padding)

    def both(xv, iv):
        # index sentinel = int max so value ties resolve to the real
        # (smaller) index, matching TF's lowest-index contract
        init = (jnp.asarray(lowest, xv.dtype),
                jnp.asarray(jnp.iinfo(iv.dtype).max, iv.dtype))

        def reducer(a, b):
            av, ai = a
            bv, bi = b
            take_b = (bv > av) | ((bv == av) & (bi < ai))
            return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

        return lax.reduce_window(
            (xv, iv), init, reducer, dims, strides, padding)

    _, idxs = both(lax.stop_gradient(x), flat_idx)
    return vals, idxs


@register_op("col2im")
def _col2im(cols, h, w, kh, kw, sh=1, sw=1):
    """Inverse of im2col (VALID padding): scatter-add patches back to
    [B, H, W, C] (reference generic/nn/col2im.cpp)."""
    B, OH, OW, _, _, C = cols.shape
    out = jnp.zeros((B, h, w, C), cols.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, i:i + OH * sh:sh, j:j + OW * sw:sw, :].add(
                cols[:, :, :, i, j, :])
    return out


# ---- merge ops (reference generic/broadcastable/merge_{max,add,avg}) ----
register_op("mergemax", lambda *xs: functools.reduce(jnp.maximum, xs))
register_op("mergeadd", lambda *xs: sum(xs))
register_op("mergeavg", lambda *xs: sum(xs) / len(xs))


# ---- misc completions ----
register_op("bias_add", lambda x, b: x + b)
register_op("assign_add", lambda a, b: a + b)
register_op("assign_sub", lambda a, b: a - b)
register_op("histogram", lambda a, bins: jnp.histogram(a, bins=bins)[0])
register_op("norm_p", lambda a, p=2, axis=None, keepdims=False:
            jnp.sum(jnp.abs(a) ** p, axis=_axis_tuple(axis),
                    keepdims=keepdims) ** (1.0 / p))
# TF/libnd4j clip_by_average_norm: the divisor is norm2 / numel
register_op("clip_by_avg_norm", lambda a, clip_norm:
            a * jnp.minimum(1.0, clip_norm /
                            jnp.maximum(jnp.sqrt(jnp.sum(a * a)) / a.size,
                                        1e-12)))


@register_op("log_poisson_loss")
def _log_poisson_loss(labels, log_input, compute_full_loss=False):
    loss = jnp.exp(log_input) - labels * log_input
    if compute_full_loss:
        # Stirling approximation for log(y!) as TF does
        ls = labels * jnp.log(jnp.maximum(labels, 1e-8)) - labels \
            + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(labels, 1.0))
        loss = loss + jnp.where(labels > 1.0, ls, 0.0)
    return jnp.mean(loss)


# ---- rnn: SRU (reference generic/nn/recurrent/sru.cpp) ----
@register_op("sru_cell")
def _sru_cell(x, c, w, b):
    """Simple Recurrent Unit step (Lei et al.; reference sru.cpp): w packs
    [W, Wf, Wr] as [F, 3H]; b packs [bf, br] as [2H].  The highway skip
    uses the RAW input, so F must equal H (the reference asserts
    inSize == nUnits for the same reason)."""
    H = c.shape[-1]
    if x.shape[-1] != H:
        raise ValueError(
            f"sru requires input size == hidden size (got {x.shape[-1]} "
            f"vs {H}) — the highway term is the raw input")
    z = x @ w
    xt, f_in, r_in = z[..., :H], z[..., H:2 * H], z[..., 2 * H:]
    f = jax.nn.sigmoid(f_in + b[:H])
    r = jax.nn.sigmoid(r_in + b[H:])
    c_new = f * c + (1 - f) * xt
    h = r * jnp.tanh(c_new) + (1 - r) * x
    return h, c_new


@register_op("sru_layer")
def _sru_layer(x, c0, w, b):
    """[B, T, F] → [B, T, H] SRU via lax.scan."""
    def step(c, xt):
        h, c_new = _sru_cell(xt, c, w, b)
        return c_new, h

    _, ys = lax.scan(step, c0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1)


# ---- resize variants / nd space-batch ----
register_op("resize_bicubic", lambda a, size:
            jax.image.resize(a, (a.shape[0],) + tuple(size)
                             + (a.shape[-1],), "cubic"))
register_op("resize_lanczos", lambda a, size:
            jax.image.resize(a, (a.shape[0],) + tuple(size)
                             + (a.shape[-1],), "lanczos3"))


# ---- solves ----
register_op("cholesky_solve", lambda chol, b:
            jax.scipy.linalg.cho_solve((chol, True), b))
register_op("lu_solve", lambda a, b:
            jax.scipy.linalg.lu_solve(jax.scipy.linalg.lu_factor(a), b))


# ---- losses / decode ----
@register_op("mean_pairwise_squared_error")
def _mean_pairwise_squared_error(labels, preds):
    """TF mean_pairwise_squared_error (reference SDLoss
    meanPairwiseSquaredError): for each sample, mean over pairs (i, j) of
    ((d_i - d_j)^2) where d = preds - labels."""
    d = (preds - labels).reshape(labels.shape[0], -1)
    n = d.shape[-1]
    if n <= 1:
        return jnp.asarray(0.0, d.dtype)
    sum_d = jnp.sum(d, axis=-1)
    sum_d2 = jnp.sum(d * d, axis=-1)
    # TF per-sample formula: 2*sum(d^2)/(n-1) - 2*sum(d)^2/(n*(n-1)).
    # Batch reduction is a plain mean (TF's SUM_BY_NONZERO_WEIGHTS
    # denominator here is a historical quirk, not replicated).
    per = (2.0 * sum_d2 / (n - 1)
           - 2.0 * sum_d * sum_d / (n * (n - 1)))
    return jnp.mean(per)


@register_op("ctc_greedy_decode")
def _ctc_greedy_decode(log_probs, input_lengths, blank=0):
    """Greedy (best-path) CTC decoding: argmax per frame, collapse
    repeats, drop blanks; returns ids padded with -1 (static shapes)."""
    B, T, C = log_probs.shape
    best = jnp.argmax(log_probs, axis=-1)                 # [B, T]
    t_idx = jnp.arange(T)
    live = t_idx[None, :] < input_lengths[:, None]
    prev = jnp.concatenate(
        [jnp.full((B, 1), -1, best.dtype), best[:, :-1]], axis=1)
    keep = live & (best != blank) & (best != prev)
    # stable left-compaction: kept symbols scatter to their cumulative
    # slot, dropped ones target an out-of-bounds index (mode="drop")
    pos = jnp.cumsum(keep, axis=1) - 1

    def row(k, p, b):
        idx = jnp.where(k, p, T)
        return jnp.full((T,), -1, best.dtype).at[idx].set(b, mode="drop")

    return jax.vmap(row)(keep, pos, best)


# ---- dropout variants / sparse ----
@register_op("alpha_dropout")
def _alpha_dropout(x, rng, p=0.05):
    """SELU-compatible alpha dropout (reference alphaDropOut): keeps the
    self-normalizing property; p = DROP probability."""
    if rng is None:
        return x
    alpha_p = -1.7580993408473766
    keep = jax.random.bernoulli(rng, 1.0 - p, x.shape)
    # Klambauer et al. affine correction: restores zero mean/unit variance
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * p * alpha_p
    return a * jnp.where(keep, x, alpha_p) + b


@register_op("sparse_to_dense")
def _sparse_to_dense(indices, shape, values, default_value=0.0):
    """TF SparseToDense: indices are [N, ndims], or a plain [N] vector of
    positions when the output is 1-D."""
    out = jnp.full(tuple(shape), default_value,
                   values.dtype if hasattr(values, "dtype")
                   else jnp.float32)
    if indices.ndim == 1:
        return out.at[indices].set(values)
    return out.at[tuple(jnp.moveaxis(indices, -1, 0))].set(values)


@register_op("fused_batch_norm")
def _fused_batch_norm(x, scale, offset, eps=1e-3):
    """TF FusedBatchNorm training contract: normalize with the biased batch
    variance, but return the Bessel-corrected variance as batch_var (TF
    feeds it into running-variance updates); NHWC."""
    axes = tuple(range(x.ndim - 1))
    n = 1
    for ax in axes:
        n *= x.shape[ax]
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    y = (x - mean) / jnp.sqrt(var + eps) * scale + offset
    return y, mean, var * (n / max(n - 1, 1))


# ---- linalg completions ----
register_op("slogdet", lambda a: jnp.linalg.slogdet(a))
register_op("matrix_rank", lambda a: jnp.linalg.matrix_rank(a))
register_op("batched_matmul", lambda a, b: jnp.matmul(a, b))
register_op("truncate_div", lambda a, b:
            jnp.trunc(a / b).astype(jnp.promote_types(a.dtype, b.dtype)))
register_op("remainder", jnp.remainder)


@register_op("ctc_loss")
def _ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0):
    """CTC negative log-likelihood via the standard log-space alpha
    recursion (reference generic/loss/ctcLoss.cpp).  `log_probs` is
    [B, T, C] log-softmaxed; `labels` [B, S] int; returns [B] losses."""
    B, T, C = log_probs.shape
    S = labels.shape[1]
    if S == 0:
        # empty targets: the only valid path emits blank everywhere
        t_idx = jnp.arange(T)
        live = t_idx[None, :] < input_lengths[:, None]
        return -jnp.sum(jnp.where(live, log_probs[:, :, blank], 0.0),
                        axis=1)
    L = 2 * S + 1
    NEG = jnp.asarray(-1e30, log_probs.dtype)
    lab = labels.astype(jnp.int32)
    ext = jnp.full((B, L), blank, jnp.int32).at[:, 1::2].set(lab)
    # skip-transition allowed where ext[s] != blank and ext[s] != ext[s-2]
    can_skip = jnp.concatenate(
        [jnp.zeros((B, 2), bool),
         (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

    def emit(t):
        return jnp.take_along_axis(log_probs[:, t], ext, axis=-1)

    alpha0 = jnp.full((B, L), NEG)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(S > 0, emit(0)[:, 1], NEG))

    def lse(*xs):
        m = xs[0]
        for x in xs[1:]:
            m = jnp.maximum(m, x)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        s = sum(jnp.exp(x - m_safe) for x in xs)
        return jnp.where(jnp.isfinite(m), m_safe + jnp.log(s), NEG)

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]],
                                axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]],
                                axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        new = lse(alpha, prev1, prev2) + emit(t)
        # freeze past each sequence's input length so the final read at
        # t = input_length - 1 is preserved
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    last = 2 * label_lengths.astype(jnp.int32)
    final = lse(
        jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0],
        jnp.where(label_lengths > 0,
                  jnp.take_along_axis(
                      alpha, jnp.maximum(last - 1, 0)[:, None],
                      axis=1)[:, 0], NEG))
    return -final


# ---- gradient compression (reference libnd4j .../compression/
# threshold_encoding.cpp — the encode_threshold/decode_threshold declarable
# ops behind SharedTrainingMaster's compressed-DP path).  In-graph jnp
# forms with STATIC capacity (jit-compatible); the host-side C++ codec
# (native_ops.ThresholdCodec) adds residual carry-over for the transport
# path and is bit-compatible on the wire format: int32 sign-in-index
# codes ±(idx+1), 0 = padding. ----

@register_op("encode_threshold")
def _encode_threshold(grad, threshold=1e-3, max_elements=None):
    """Flattened sparse threshold encoding: the first `max_elements`
    entries (in index order) with |g| >= threshold become ±(idx+1)."""
    v = grad.reshape(-1)
    n = v.shape[0]
    if max_elements is None:
        max_elements = n
    keep = jnp.abs(v) >= threshold
    # stable order-preserving compaction: non-kept slots sort to the end
    order_key = jnp.where(keep, jnp.arange(n), n)
    first = jnp.sort(order_key)[:max_elements]
    valid = first < n
    idx = jnp.where(valid, first, 0)
    code = jnp.sign(v[idx]).astype(jnp.int32) * (idx.astype(jnp.int32) + 1)
    return jnp.where(valid, code, 0)


@register_op("decode_threshold")
def _decode_threshold(encoded, size, threshold=1e-3):
    """Inverse: scatter-add ±threshold at |code|-1; 0 codes are padding."""
    e = encoded.astype(jnp.int32)
    idx = jnp.clip(jnp.abs(e) - 1, 0, size - 1)
    val = jnp.sign(e).astype(jnp.float32) * threshold
    return jnp.zeros((size,), jnp.float32).at[idx].add(val)


# ---- round-3 declarable-op tail (reference libnd4j
# include/ops/declarable/generic/** families not yet covered: parity/
# transforms/nn/compat/image/quantization exotica) ----

register_op("stop_gradient", lax.stop_gradient)
register_op("invert_permutation", lambda p: jnp.argsort(p))
register_op("divide_no_nan", lambda a, b:
            jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)))
register_op("lbeta", lambda x:
            jnp.sum(jax.scipy.special.gammaln(x), axis=-1)
            - jax.scipy.special.gammaln(jnp.sum(x, axis=-1)))
register_op("bucketize", lambda x, boundaries:
            jnp.searchsorted(jnp.asarray(boundaries), x, side="right")
            .astype(jnp.int32))
register_op("truncated_normal", lambda rng, shape, mean=0.0, stddev=1.0,
            dtype="float32": mean + stddev * jax.random.truncated_normal(
                _key(rng), -2.0, 2.0, tuple(shape), jnp.dtype(dtype)))
register_op("random_randint", lambda rng, shape, minval, maxval:
            jax.random.randint(_key(rng), tuple(shape), minval, maxval))
@register_op("cyclic_shift_right")
def _cyclic_shift_right(x, n):
    # rotate on the UNSIGNED view: arithmetic right-shift on signed
    # dtypes sign-extends and corrupts the rotation; n is taken mod the
    # bit width so n=0 never emits an undefined full-width shift
    bits = x.dtype.itemsize * 8
    n = n % bits
    u = x.view(jnp.dtype(f"uint{bits}")) if jnp.issubdtype(
        x.dtype, jnp.signedinteger) else x
    r = jnp.bitwise_or(jnp.right_shift(u, n),
                       jnp.left_shift(u, (bits - n) % bits))
    return r.view(x.dtype) if r.dtype != x.dtype else r
register_op("xw_plus_b", lambda x, w, b: x @ w + b)
register_op("relu_layer", lambda x, w, b: jax.nn.relu(x @ w + b))
register_op("reverse", lambda x, axes:
            jnp.flip(x, axis=tuple(axes) if isinstance(axes, (list, tuple))
                     else int(axes)))
register_op("mergemaxindex", lambda *xs:
            jnp.argmax(jnp.stack(xs), axis=0).astype(jnp.int32))


@register_op("dynamic_partition")
def _dynamic_partition(data, partitions, num_partitions):
    """TF DynamicPartition (ragged outputs — host-side op, not jittable;
    the reference's op is likewise host-orchestrated)."""
    import numpy as onp
    data = onp.asarray(data)
    partitions = onp.asarray(partitions)
    return tuple(jnp.asarray(data[partitions == i])
                 for i in range(num_partitions))


@register_op("sufficient_statistics")
def _sufficient_statistics(x, axes, shift=None):
    """TF nn.sufficient_statistics: (count, mean_ss, var_ss, shift)."""
    axes = _axis_tuple(axes)
    count = 1
    for a in axes:
        count *= x.shape[a]
    xs = x if shift is None else x - shift
    m_ss = jnp.sum(xs, axis=axes)
    v_ss = jnp.sum(xs * xs, axis=axes)
    return jnp.asarray(count, x.dtype), m_ss, v_ss, shift


@register_op("compare_and_bitpack")
def _compare_and_bitpack(x, threshold):
    """TF CompareAndBitpack: pack groups of 8 (x > threshold) bits into
    uint8, MSB first."""
    bits = (x > threshold).astype(jnp.uint8)
    b8 = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(b8 * weights, axis=-1).astype(jnp.uint8)


@register_op("fake_quant_with_min_max_args")
def _fake_quant_args(x, min=-6.0, max=6.0, num_bits=8, narrow_range=False):
    """Quantize-dequantize through an affine int grid (reference
    fake_quant_with_min_max_vars.cpp; TF nudged-range semantics)."""
    qmin = 1.0 if narrow_range else 0.0
    qmax = float(2 ** num_bits - 1)
    scale = (max - min) / (qmax - qmin)
    zero = qmin - min / scale
    nudged_zero = jnp.clip(jnp.round(zero), qmin, qmax)
    nudged_min = (qmin - nudged_zero) * scale
    nudged_max = (qmax - nudged_zero) * scale
    clamped = jnp.clip(x, nudged_min, nudged_max)
    return (jnp.round((clamped - nudged_min) / scale) * scale
            + nudged_min).astype(x.dtype)


register_op("fake_quant_with_min_max_vars", lambda x, min, max, num_bits=8,
            narrow_range=False: _fake_quant_args(
                x, jnp.asarray(min), jnp.asarray(max), num_bits,
                narrow_range))


@register_op("pnorm_pool2d")
def _pnorm_pool2d(x, kernel=(2, 2), stride=(2, 2), p=2, padding="VALID"):
    """P-norm pooling (reference pnormpool2d / SubsamplingLayer PNORM)."""
    kh, kw = kernel
    s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add,
                          (1, kh, kw, 1), (1,) + tuple(stride) + (1,),
                          padding)
    return s ** (1.0 / p)


@register_op("upsampling3d")
def _upsampling3d(x, size=2):
    sd_, sh, sw = (size, size, size) if isinstance(size, int) else size
    x = jnp.repeat(x, sd_, axis=1)
    x = jnp.repeat(x, sh, axis=2)
    return jnp.repeat(x, sw, axis=3)


@register_op("resize_area")
def _resize_area(a, size):
    """TF area resize: exact box-average for integer downscale (the common
    case); bilinear fallback otherwise."""
    H, W = a.shape[-3], a.shape[-2]
    h2, w2 = size
    if H % h2 == 0 and W % w2 == 0:
        fh, fw = H // h2, W // w2
        s = a.shape
        r = a.reshape(s[:-3] + (h2, fh, w2, fw, s[-1]))
        return r.mean(axis=(-4, -2)).astype(a.dtype)
    return jax.image.resize(a, a.shape[:-3] + (h2, w2, a.shape[-1]),
                            "linear").astype(a.dtype)


@register_op("non_max_suppression_overlaps")
def _nms_overlaps(overlaps, scores, max_output_size,
                  overlap_threshold=0.5, score_threshold=-jnp.inf):
    """Greedy NMS on a precomputed [N,N] overlap matrix (reference
    non_max_suppression_overlaps.cpp); fixed-size -1-padded output."""
    n = overlaps.shape[0]
    live = scores > score_threshold

    def body(state, _):
        live_, sc = state
        best = jnp.argmax(jnp.where(live_, sc, -jnp.inf))
        ok = live_[best]
        live_ = live_ & (overlaps[best] <= overlap_threshold)
        live_ = live_.at[best].set(False)
        return (live_, sc), jnp.where(ok, best, -1)

    (_, _), picked = lax.scan(body, (live, scores), None,
                              length=max_output_size)
    return picked


@register_op("draw_bounding_boxes")
def _draw_bounding_boxes(images, boxes, colors=None):
    """[B,H,W,C] images + [B,N,4] normalized (y1,x1,y2,x2) boxes -> 1px
    box outlines (reference generic/images/draw_bounding_boxes.cpp)."""
    B, H, W, C = images.shape
    N = boxes.shape[1]
    if colors is None:
        colors = jnp.ones((1, C), images.dtype)
    colors = jnp.asarray(colors, images.dtype)
    rows = jnp.arange(H)[:, None]
    cols = jnp.arange(W)[None, :]

    def one_image(img, bxs):
        def one_box(img, i):
            y1, x1, y2, x2 = (bxs[i, 0] * (H - 1), bxs[i, 1] * (W - 1),
                              bxs[i, 2] * (H - 1), bxs[i, 3] * (W - 1))
            inside = ((rows >= jnp.floor(y1)) & (rows <= jnp.ceil(y2))
                      & (cols >= jnp.floor(x1)) & (cols <= jnp.ceil(x2)))
            edge_r = ((jnp.abs(rows - jnp.round(y1)) < 1)
                      | (jnp.abs(rows - jnp.round(y2)) < 1))
            edge_c = ((jnp.abs(cols - jnp.round(x1)) < 1)
                      | (jnp.abs(cols - jnp.round(x2)) < 1))
            mask = inside & (edge_r | edge_c)
            col = colors[i % colors.shape[0]]
            return jnp.where(mask[..., None], col, img), None

        img, _ = lax.scan(one_box, img, jnp.arange(N))
        return img

    return jax.vmap(one_image)(images, boxes)


@register_op("conv1d")
def _conv1d(x, w, stride=1, padding="SAME", dilation=1):
    """[B,T,Ci] x [K,Ci,Co] temporal conv via conv_general_dilated."""
    return lax.conv_general_dilated(
        x, w, (stride,), padding, rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"))


@register_op("max_pooling1d")
def _max_pooling1d(x, kernel=2, stride=2, padding="VALID"):
    if jnp.issubdtype(x.dtype, jnp.integer):
        lowest = jnp.iinfo(x.dtype).min
    else:
        lowest = -jnp.inf
    return lax.reduce_window(x, lowest, lax.max, (1, kernel, 1),
                             (1, stride, 1), padding)


@register_op("avg_pooling1d")
def _avg_pooling1d(x, kernel=2, stride=2, padding="VALID"):
    s = lax.reduce_window(x, 0.0, lax.add, (1, kernel, 1), (1, stride, 1),
                          padding)
    n = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, (1, kernel, 1),
                          (1, stride, 1), padding)
    return s / n


register_op("pointwise_conv2d", lambda x, w:
            jnp.einsum("bhwi,io->bhwo", x, w.reshape(w.shape[-2:])))


@register_op("separable_conv2d")
def _separable_conv2d(x, w_depth, w_point, stride=(1, 1), padding="SAME"):
    """Depthwise [Kh,Kw,Ci,M] then pointwise [1,1,Ci*M,Co] (reference
    sconv2d.cpp)."""
    ci = x.shape[-1]
    d = lax.conv_general_dilated(
        x, w_depth.reshape(w_depth.shape[0], w_depth.shape[1], 1, -1),
        tuple(stride), padding, feature_group_count=ci,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return jnp.einsum("bhwi,io->bhwo", d,
                      w_point.reshape(-1, w_point.shape[-1]))


@register_op("deconv3d")
def _deconv3d(x, w, stride=(1, 1, 1), padding="SAME"):
    """[B,D,H,W,Ci] x [Kd,Kh,Kw,Ci,Co] transpose conv (reference
    deconv3d.cpp) — gradient form, so the kernel is flipped before
    lax.conv_transpose (see deconv2d)."""
    return lax.conv_transpose(x, jnp.flip(w, (0, 1, 2)), tuple(stride),
                              padding,
                              dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))


@register_op("lstm_layer")
def _lstm_layer_ifog(x, w, rw, b):
    """Whole-sequence LSTM, IFOG gate order, single [B,T,H] output — the
    samediff `sd.rnn.lstm_layer` contract (SURVEY §7 hard part (d):
    cuDNN-LSTM → lax.scan).  Registered here (not via samediff's
    setdefault) so the duplicate guard protects the name.  The reference
    lstmLayer's full-output mode is `lstm_layer_full` below."""
    H = rw.shape[0]

    def cell(carry, xt):
        h, c = carry
        z = xt @ w + h @ rw + b
        i, f, o, g = (jax.nn.sigmoid(z[:, :H]),
                      jax.nn.sigmoid(z[:, H:2 * H]),
                      jax.nn.sigmoid(z[:, 2 * H:3 * H]),
                      jnp.tanh(z[:, 3 * H:]))
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    Bsz = x.shape[0]
    h0 = jnp.zeros((Bsz, H), x.dtype)
    (_, _), hs = jax.lax.scan(cell, (h0, h0), jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(hs, 0, 1)


@register_op("lstm_layer_full")
def _lstm_layer_full(x, w_ih, w_hh, b=None, h0=None, c0=None):
    """Reference lstmLayer's full-output mode: (h sequence, last h, last
    c), IFCO gate order via lstm_cell.  The single-output IFOG form lives
    under `lstm_layer` (samediff namespace contract).  x: [B,T,F]."""
    Bsz, T, _ = x.shape
    H = w_hh.shape[0]
    h = jnp.zeros((Bsz, H), x.dtype) if h0 is None else h0
    c = jnp.zeros((Bsz, H), x.dtype) if c0 is None else c0
    cell = OP_TABLE["lstm_cell"]

    def step(carry, xt):
        h_, c_ = carry
        h_new, c_new = cell(xt, h_, c_, w_ih, w_hh, b)
        return (h_new, c_new), h_new

    (h, c), ys = lax.scan(step, (h, c), jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1), h, c


@register_op("space_to_batch_nd")
def _space_to_batch_nd(x, block_shape, paddings):
    """General ND form (reference space_to_batch_nd.cpp)."""
    nb = len(block_shape)
    pads = [(0, 0)] + [tuple(p) for p in paddings] \
        + [(0, 0)] * (x.ndim - 1 - nb)
    x = jnp.pad(x, pads)
    B = x.shape[0]
    spatial = x.shape[1:1 + nb]
    rest = x.shape[1 + nb:]
    shape = [B]
    for s, b in zip(spatial, block_shape):
        shape += [s // b, b]
    x = x.reshape(shape + list(rest))
    block_axes = [2 + 2 * i for i in range(nb)]
    grid_axes = [1 + 2 * i for i in range(nb)]
    rest_axes = list(range(1 + 2 * nb, x.ndim))
    x = x.transpose(block_axes + [0] + grid_axes + rest_axes)
    prod_b = 1
    for b in block_shape:
        prod_b *= b
    return x.reshape([prod_b * B] + [s // b for s, b in
                                     zip(spatial, block_shape)]
                     + list(rest))


@register_op("batch_to_space_nd")
def _batch_to_space_nd(x, block_shape, crops):
    nb = len(block_shape)
    prod_b = 1
    for b in block_shape:
        prod_b *= b
    B = x.shape[0] // prod_b
    spatial = x.shape[1:1 + nb]
    rest = x.shape[1 + nb:]
    x = x.reshape(list(block_shape) + [B] + list(spatial) + list(rest))
    perm = [nb]
    for i in range(nb):
        perm += [nb + 1 + i, i]
    perm += list(range(1 + 2 * nb, x.ndim))
    x = x.transpose(perm)
    x = x.reshape([B] + [s * b for s, b in zip(spatial, block_shape)]
                  + list(rest))
    slices = [slice(None)]
    for (c0, c1), s, b in zip([tuple(c) for c in crops], spatial,
                              block_shape):
        slices.append(slice(c0, s * b - c1))
    return x[tuple(slices)]


@register_op("ctc_beam_decode")
def _ctc_beam_decode(log_probs, input_lengths, beam_width=8, blank=0):
    """CTC prefix beam search (reference ctc_beam.cpp) — host-side numpy
    decode (ragged, data-dependent; not a jit op, same as the reference's
    CPU-only helper).  log_probs: [B,T,C]; returns list of label lists."""
    import numpy as onp
    lp = onp.asarray(log_probs)
    lens = onp.asarray(input_lengths).astype(onp.int64)
    results = []
    NEG = -1e30

    def lse(a, b):
        m = max(a, b)
        if m <= NEG:
            return NEG
        return m + onp.log(onp.exp(a - m) + onp.exp(b - m))

    for b in range(lp.shape[0]):
        # beams: prefix tuple -> (p_blank, p_nonblank)
        beams = {(): (0.0, NEG)}
        for t in range(int(lens[b])):
            new = {}

            def add(prefix, pb, pnb):
                opb, opnb = new.get(prefix, (NEG, NEG))
                new[prefix] = (lse(opb, pb), lse(opnb, pnb))

            for prefix, (pb, pnb) in beams.items():
                for c in range(lp.shape[2]):
                    p = float(lp[b, t, c])
                    if c == blank:
                        add(prefix, lse(pb, pnb) + p, NEG)
                    elif prefix and prefix[-1] == c:
                        add(prefix, NEG, pnb + p)          # repeat merges
                        add(prefix + (c,), NEG, pb + p)    # after blank
                    else:
                        add(prefix + (c,), NEG, lse(pb, pnb) + p)
            beams = dict(sorted(new.items(),
                                key=lambda kv: -lse(*kv[1]))[:beam_width])
        best = max(beams.items(), key=lambda kv: lse(*kv[1]))[0]
        results.append(list(best))
    return results


# ---- round-3 tail, part 2: parity/compat/tsne exotica (reference
# generic/parity_ops/**, generic/compat/**, helpers/cpu/BarnesHutTsne) ----

register_op("erfinv", lambda x: lax.erf_inv(x))
register_op("polyval", lambda coeffs, x: jnp.polyval(jnp.asarray(coeffs), x))
register_op("is_non_decreasing", lambda x:
            jnp.all(jnp.diff(x.reshape(-1)) >= 0))
register_op("is_strictly_increasing", lambda x:
            jnp.all(jnp.diff(x.reshape(-1)) > 0))
register_op("is_numeric_tensor", lambda x:
            jnp.issubdtype(x.dtype, jnp.number))
register_op("unravel_index", lambda indices, shape:
            jnp.stack(jnp.unravel_index(indices, tuple(shape)), axis=0))


@register_op("eig")
def _eig(a):
    """General (non-symmetric) eigendecomposition — CPU-only in XLA, the
    same host-bound role the reference's lapack path has."""
    import numpy as onp
    w, v = onp.linalg.eig(onp.asarray(a))
    return jnp.asarray(w), jnp.asarray(v)


@register_op("hashcode")
def _hashcode(x):
    """Deterministic int64 tensor hash (reference parity op `hashcode` —
    value-dependent checksum; exact constant differs, contract is
    determinism over content)."""
    b = jnp.asarray(x).reshape(-1)
    if jnp.issubdtype(b.dtype, jnp.floating):
        b = b.astype(jnp.float32).view(jnp.int32)
    b = b.astype(jnp.int64)
    n = b.shape[0]
    mult = jnp.asarray(31, jnp.int64) ** (jnp.arange(n, dtype=jnp.int64)
                                          % 16)
    return jnp.sum(b * mult)


@register_op("choose")
def _choose(x, comparable, mode=0):
    """Filter elements by scalar comparison (reference compat `choose`:
    mode 0 '<', 1 '<=', 2 '>', 3 '>=', 4 '=='); ragged result — host-side
    numpy op.  Returns (filtered values, count)."""
    import numpy as onp
    xv = onp.asarray(x).reshape(-1)
    c = float(comparable)
    sel = {0: xv < c, 1: xv <= c, 2: xv > c, 3: xv >= c,
           4: xv == c}[int(mode)]
    kept = xv[sel]
    return jnp.asarray(kept), jnp.asarray(kept.size, jnp.int32)


@register_op("broadcast_dynamic_shape")
def _broadcast_dynamic_shape(s1, s2):
    import numpy as onp
    return jnp.asarray(
        onp.broadcast_shapes(tuple(onp.asarray(s1).astype(int)),
                             tuple(onp.asarray(s2).astype(int))),
        jnp.int32)


@register_op("broadcast_gradient_args")
def _broadcast_gradient_args(s1, s2):
    """Reduction axes each operand's gradient needs after broadcasting
    (TF BroadcastGradientArgs / reference compat op) — host-side."""
    import numpy as onp
    a = list(onp.asarray(s1).astype(int))
    b = list(onp.asarray(s2).astype(int))
    n = max(len(a), len(b))
    a = [1] * (n - len(a)) + a
    b = [1] * (n - len(b)) + b
    ra = [i for i in range(n) if a[i] == 1 and b[i] != 1]
    rb = [i for i in range(n) if b[i] == 1 and a[i] != 1]
    return (jnp.asarray(ra, jnp.int32), jnp.asarray(rb, jnp.int32))


register_op("knn_mindistance", lambda lowest, highest, point:
            jnp.sqrt(jnp.sum(jnp.maximum(
                jnp.maximum(lowest - point, 0.0),
                jnp.maximum(point - highest, 0.0)) ** 2, axis=-1)))
register_op("cell_contains", lambda corner, width, point:
            jnp.all((point >= corner - width / 2)
                    & (point <= corner + width / 2), axis=-1))


@register_op("barnes_gains")
def _barnes_gains(gains, grad, step):
    """t-SNE gain update (reference BarnesHutTsne helpers): gain + 0.2
    where grad and step disagree in sign, gain * 0.8 where they agree,
    floored at 0.01."""
    agree = jnp.sign(grad) == jnp.sign(step)
    return jnp.maximum(jnp.where(agree, gains * 0.8, gains + 0.2), 0.01)


@register_op("barnes_symmetrize")
def _barnes_symmetrize(row_p, col_p, val_p, n):
    """Symmetrize a CSR sparse affinity matrix: (P + P^T) / 2 (reference
    barnes_symmetrized op) — host-side, returns CSR triple."""
    import numpy as onp
    from scipy.sparse import csr_matrix
    rp = onp.asarray(row_p).astype(onp.int64)
    cp = onp.asarray(col_p).astype(onp.int64)
    vp = onp.asarray(val_p).astype(onp.float64)
    m = csr_matrix((vp, cp, rp), shape=(int(n), int(n)))
    s = ((m + m.T) * 0.5).tocsr()
    return (jnp.asarray(s.indptr.astype(onp.int32)),
            jnp.asarray(s.indices.astype(onp.int32)),
            jnp.asarray(s.data.astype(onp.float32)))


@register_op("barnes_edge_forces")
def _barnes_edge_forces(row_p, col_p, val_p, y):
    """t-SNE attractive edge forces: F_i = sum_j P_ij (1+||yi-yj||^2)^-1
    (yi-yj) over the sparse neighbor lists (reference barnes_edge_forces)
    — host-side numpy."""
    import numpy as onp
    rp = onp.asarray(row_p).astype(onp.int64)
    cp = onp.asarray(col_p).astype(onp.int64)
    vp = onp.asarray(val_p).astype(onp.float64)
    yv = onp.asarray(y).astype(onp.float64)
    out = onp.zeros_like(yv)
    for i in range(yv.shape[0]):
        js = cp[rp[i]:rp[i + 1]]
        ws = vp[rp[i]:rp[i + 1]]
        if js.size == 0:
            continue
        d = yv[i] - yv[js]
        q = 1.0 / (1.0 + onp.sum(d * d, axis=1))
        out[i] = onp.sum((ws * q)[:, None] * d, axis=0)
    return jnp.asarray(out.astype(onp.float32))


@register_op("multi_head_dot_product_attention")
def _mhdpa(q, k, v, wq, wk, wv, wo, mask=None, scaled=True):
    """Reference `multi_head_dot_product_attention` declarable op
    (generic/nn/multi_head_dot_product_attention.cpp): project [B,T,F]
    inputs per head, run fused attention, re-project.  Head count comes
    from wq's leading dim: wq [H, dk, F]."""
    from deeplearning4j_tpu.ops.attention_kernels import fused_attention
    H = wq.shape[0]
    def proj(x, w):                          # [B,T,F] x [H,dh,F]
        return jnp.einsum("btf,hdf->bhtd", x, w)
    qh, kh, vh = proj(q, wq), proj(k, wk), proj(v, wv)
    scale = None if scaled else 1.0
    ctx = fused_attention(qh, kh, vh, mask=mask, scale=scale)  # [B,H,T,dv]
    return jnp.einsum("bhtd,ohd->bto", ctx, wo)


# ---- round-3 tail, part 3: bitmap compression + small parity ops ----

register_op("cube", lambda x: x * x * x)
register_op("count_zero", lambda x, axis=None:
            jnp.sum((x == 0).astype(jnp.int32), axis=_axis_tuple(axis)))
register_op("to_degrees", jnp.degrees)
register_op("to_radians", jnp.radians)
register_op("size_at", lambda x, dim: x.shape[int(dim)])


@register_op("cosine_distance_loss")
def _cosine_distance_loss(predictions, labels, axis=-1):
    """Reference loss-family name for the same mean(1 - cos_sim) math as
    the reduce3 `cosine_distance` op — delegates to it."""
    return _cos_dist(labels, predictions, axis=axis)


@register_op("encode_bitmap")
def _encode_bitmap(grad, threshold=1e-3):
    """Bitmap gradient compression (reference legacy ops encode_bitmap):
    2-bit flag per value — 0 none, 1 +threshold, 2 -threshold — packed 16
    flags per int32, plus the flagged count.  Fixed-size output:
    jit-compatible."""
    v = grad.reshape(-1)
    n = v.shape[0]
    flags = jnp.where(v >= threshold, 1,
                      jnp.where(v <= -threshold, 2, 0)).astype(jnp.int32)
    pad = (-n) % 16
    fp = jnp.concatenate([flags, jnp.zeros((pad,), jnp.int32)])
    f16 = fp.reshape(-1, 16)
    shifts = jnp.arange(16, dtype=jnp.int32) * 2
    packed = jnp.sum(f16 << shifts[None, :], axis=1).astype(jnp.int32)
    return packed, jnp.sum((flags != 0).astype(jnp.int32))


@register_op("decode_bitmap")
def _decode_bitmap(packed, size, threshold=1e-3):
    codes = (packed[:, None] >> (jnp.arange(16, dtype=jnp.int32) * 2)) & 3
    codes = codes.reshape(-1)[:size]
    return jnp.where(codes == 1, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))


# ---- round-3 tail, part 4: random family completion, dynamic RNNs,
# legacy pairwise leftovers (reference generic/random/**, generic/recurrent/
# dynamic_rnn.cpp, legacy pairwise ops) ----

register_op("random_binomial", lambda rng, shape, n, p=0.5:
            jax.random.binomial(_key(rng), n, p, shape=tuple(shape)))
register_op("random_lognormal", lambda rng, shape, mean=0.0, stddev=1.0:
            jnp.exp(mean + stddev * jax.random.normal(_key(rng),
                                                      tuple(shape))))
register_op("random_choice", lambda rng, source, probabilities, n:
            source[jax.random.choice(
                _key(rng), source.shape[0], (n,),
                p=probabilities / jnp.sum(probabilities))])
register_op("reverse_mod", lambda a, b: b % a)
register_op("axpy", lambda alpha, x, y: alpha * x + y)
register_op("adjust_contrast_v2", lambda x, factor:
            OP_TABLE["adjust_contrast"](x, factor))


@register_op("logdet")
def _logdet(a):
    """log|det| for symmetric positive-definite input via Cholesky
    (reference parity op logdet)."""
    c = jnp.linalg.cholesky(a)
    return 2.0 * jnp.sum(jnp.log(jnp.diagonal(c, axis1=-2, axis2=-1)),
                         axis=-1)


@register_op("assert_equal")
def _assert_equal(a, b, eps=0.0):
    """Equality assertion (reference Assert/assert ops): raises on
    mismatch, passes `a` through.  Eager inputs check synchronously;
    under jit (the SameDiff execution path) the check runs as a host
    debug callback so graphs containing it still compile."""
    import numpy as onp

    def host_check(av, bv):
        av, bv = onp.asarray(av), onp.asarray(bv)
        if not onp.allclose(av, bv, atol=eps, rtol=0.0):
            raise ValueError(
                f"assert_equal failed: max |a-b| = "
                f"{onp.max(onp.abs(av - bv)):.3g} > {eps}")

    if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
        jax.debug.callback(host_check, a, b)
        return a
    host_check(a, b)
    return a


@register_op("dynamic_rnn")
def _dynamic_rnn(x, w, rw, b=None, h0=None, seq_lengths=None):
    """Plain-RNN whole sequence (reference dynamic_rnn.cpp):
    h_t = tanh(x_t W + h_{t-1} R + b), zeroing steps past seq_lengths.
    x: [B,T,F] -> (outputs [B,T,H], final h [B,H])."""
    B, T, _ = x.shape
    H = rw.shape[0]
    h = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    bias = 0 if b is None else b
    steps = jnp.arange(T)

    def cell(h, inp):
        xt, t = inp
        h_new = jnp.tanh(xt @ w + h @ rw + bias)
        if seq_lengths is not None:
            live = (t < seq_lengths)[:, None]
            h_new = jnp.where(live, h_new, h)
        return h_new, h_new

    h_final, ys = lax.scan(cell, h, (jnp.swapaxes(x, 0, 1), steps))
    out = jnp.swapaxes(ys, 0, 1)
    if seq_lengths is not None:
        out = out * (steps[None, :] < seq_lengths[:, None])[..., None]
    return out, h_final


@register_op("dynamic_bidirectional_rnn")
def _dynamic_bidirectional_rnn(x, w_f, rw_f, b_f, w_b, rw_b, b_b,
                               seq_lengths=None):
    """Two dynamic_rnns over opposite time directions (reference
    dynamic_bidirectional_rnn.cpp); returns (fwd_out, bwd_out,
    fwd_final, bwd_final) with the bwd sequence re-flipped to input
    order."""
    fwd, hf = _dynamic_rnn(x, w_f, rw_f, b_f, seq_lengths=seq_lengths)
    if seq_lengths is None:
        xr = jnp.flip(x, axis=1)
        bwd, hb = _dynamic_rnn(xr, w_b, rw_b, b_b)
        return fwd, jnp.flip(bwd, axis=1), hf, hb
    # per-example reversal up to each sequence's length
    T = x.shape[1]
    idx = jnp.arange(T)[None, :]
    rev = jnp.clip(seq_lengths[:, None] - 1 - idx, 0, T - 1)
    take = jnp.where(idx < seq_lengths[:, None], rev, idx)
    xr = jnp.take_along_axis(x, take[..., None], axis=1)
    bwd, hb = _dynamic_rnn(xr, w_b, rw_b, b_b, seq_lengths=seq_lengths)
    bwd = jnp.take_along_axis(bwd, take[..., None], axis=1)
    return fwd, bwd, hf, hb


# ---- round-3 tail, part 5: TensorList family (reference
# generic/list/*.cpp — the graph-interpreter's TensorArray; host-side
# Python list, same as the reference's non-compiled list store), LSTM
# block ops, static RNN forms ----

class TensorList:
    """Host-side list-of-arrays handle (reference NDArrayList)."""

    def __init__(self, arrays=None):
        self.arrays = list(arrays) if arrays is not None else []

    def __len__(self):
        return len(self.arrays)


register_op("create_list", lambda *, size=0: TensorList(
    [None] * int(size) if size else []))
register_op("size_list", lambda lst: jnp.asarray(len(lst.arrays),
                                                 jnp.int32))
@register_op("read_list")
def _read_list(lst, idx):
    v = lst.arrays[int(idx)]
    if v is None:
        raise ValueError(f"read_list: slot {int(idx)} was never written")
    return v


@register_op("write_list")
def _write_list(lst, idx, value):
    i = int(idx)
    if i >= len(lst.arrays):
        lst.arrays.extend([None] * (i + 1 - len(lst.arrays)))
    lst.arrays[i] = value
    return lst


@register_op("stack_list")
def _stack_list(lst):
    for i, a in enumerate(lst.arrays):
        if a is None:
            raise ValueError(f"stack_list: slot {i} was never written")
    return jnp.stack([jnp.asarray(a) for a in lst.arrays])


@register_op("unstack_list")
def _unstack_list(lst, x):
    lst.arrays = [x[i] for i in range(x.shape[0])]
    return lst


@register_op("gather_list")
def _gather_list(lst, indices):
    return jnp.stack([jnp.asarray(_read_list(lst, int(i)))
                      for i in np.asarray(indices)])


@register_op("scatter_list")
def _scatter_list(lst, indices, x):
    for j, i in enumerate(np.asarray(indices)):
        _write_list(lst, int(i), x[j])
    return lst


@register_op("split_list")
def _split_list(lst, x, sizes):
    sizes = [int(s) for s in np.asarray(sizes)]
    if sum(sizes) != x.shape[0]:
        raise ValueError(
            f"split_list: sizes {sizes} sum to {sum(sizes)} but the "
            f"input has {x.shape[0]} rows (TensorArraySplit contract)")
    out, off = [], 0
    for sz in sizes:
        out.append(x[off:off + sz])
        off += sz
    lst.arrays = out
    return lst


@register_op("pick_list")
def _pick_list(lst, indices):
    return jnp.concatenate([jnp.asarray(_read_list(lst, int(i)))
                            for i in np.asarray(indices)], axis=0)


@register_op("tear")
def _tear(x, axis=0):
    """Split into a TensorList along `axis` (reference parity op tear)."""
    moved = jnp.moveaxis(x, axis, 0)
    return TensorList([moved[i] for i in range(moved.shape[0])])


register_op("real_div", lambda a, b: a / b)    # TF RealDiv declarable


@register_op("print_variable")
def _print_variable(x, message=""):
    """Reference parity op print_variable: prints (host callback under
    jit) and passes through."""
    if isinstance(x, jax.core.Tracer):
        safe = message.replace("{", "{{").replace("}", "}}")
        jax.debug.print(safe + "{x}", x=x)
        return x
    print(f"{message}{np.asarray(x)}")
    return x


@register_op("lstm_block_cell")
def _lstm_block_cell(x, h, c, w_ih, w_hh, b=None):
    """Reference lstmBlockCell: one step returning the full gate trace
    (i, c_new, f, o, z, h_new, y=h_new), IFCO gate order."""
    g = x @ w_ih + h @ w_hh + (0 if b is None else b)
    H = h.shape[-1]
    i = jax.nn.sigmoid(g[..., :H])
    f = jax.nn.sigmoid(g[..., H:2 * H])
    z = jnp.tanh(g[..., 2 * H:3 * H])
    o = jax.nn.sigmoid(g[..., 3 * H:])
    c_new = f * c + i * z
    h_new = o * jnp.tanh(c_new)
    return i, c_new, f, o, z, h_new, h_new


@register_op("lstm_block")
def _lstm_block(x, w_ih, w_hh, b=None):
    """Reference lstmBlock: whole-sequence lstmBlockCell scan; returns
    the stacked (i, c, f, o, z, h, y) sequences, time axis 1."""
    Bsz, T, _ = x.shape
    H = w_hh.shape[0]
    h0 = jnp.zeros((Bsz, H), x.dtype)

    def step(carry, xt):
        h, c = carry
        i, c_new, f, o, z, h_new, y = _lstm_block_cell(xt, h, c, w_ih,
                                                       w_hh, b)
        return (h_new, c_new), (i, c_new, f, o, z, h_new, y)

    (_, _), seqs = lax.scan(step, (h0, h0), jnp.swapaxes(x, 0, 1))
    return tuple(jnp.swapaxes(s, 0, 1) for s in seqs)


register_op("static_rnn", lambda x, w, rw, b=None, h0=None:
            _dynamic_rnn(x, w, rw, b, h0))
register_op("static_bidirectional_rnn",
            lambda x, w_f, rw_f, b_f, w_b, rw_b, b_b:
            _dynamic_bidirectional_rnn(x, w_f, rw_f, b_f, w_b, rw_b, b_b))


# ---- round-3 tail, part 6: select + the word2vec training ops ----

register_op("select", lambda cond, a, b: jnp.where(cond, a, b))


@register_op("skipgram")
def _skipgram(syn0, syn1, centers, contexts, negatives, lr=0.025):
    """Reference skipgram declarable op (generic/nn/skipgram.cpp,
    negative-sampling form): one batched SGD update of the embedding
    matrices, functional (params in -> updated params out, loss).  The
    per-PAIR lr semantics (sum over batch, not mean) match
    nlp.Word2Vec."""
    def loss_fn(params):
        s0, s1 = params
        v = s0[centers]
        pos = jnp.sum(v * s1[contexts], -1)
        negs = jnp.einsum("bd,bnd->bn", v, s1[negatives])
        return -(jnp.sum(jax.nn.log_sigmoid(pos))
                 + jnp.sum(jax.nn.log_sigmoid(-negs)))

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1))
    return syn0 - lr * g0, syn1 - lr * g1, loss


@register_op("cbow")
def _cbow(syn0, syn1, ctx, ctx_mask, centers, negatives, lr=0.025):
    """Reference cbow declarable op: window-mean input embedding predicts
    the center word; one batched functional SGD update."""
    def loss_fn(params):
        s0, s1 = params
        e = s0[ctx] * ctx_mask[..., None]
        v = jnp.sum(e, 1) / jnp.maximum(
            jnp.sum(ctx_mask, 1, keepdims=True), 1.0)
        pos = jnp.sum(v * s1[centers], -1)
        negs = jnp.einsum("bd,bnd->bn", v, s1[negatives])
        return -(jnp.sum(jax.nn.log_sigmoid(pos))
                 + jnp.sum(jax.nn.log_sigmoid(-negs)))

    loss, (g0, g1) = jax.value_and_grad(loss_fn)((syn0, syn1))
    return syn0 - lr * g0, syn1 - lr * g1, loss
