"""Per-op validation harness.

Reference: `nd4j-api/src/main/java/org/nd4j/autodiff/validation/
OpValidation.java` + `TestCase.java` — the framework that checks, for every
registered op: forward value against a golden, the op's shape function
against the executed output, and the analytic gradient against a central
finite difference, while tracking coverage of the whole registry so
never-tested ops fail the build.

TPU-native mapping of those semantics:

- *forward value*: run the `OP_TABLE` entry eagerly on numpy inputs and
  compare against an independent golden (numpy/scipy/torch closed form) or
  a property validator.
- *shape function*: in jax the "shape function" is abstract evaluation —
  `jax.eval_shape` traces the op without running it.  The harness checks
  that the abstract output (shape AND dtype) of every traced op matches
  the concrete result, and that the op compiles and agrees under
  `jax.jit` (a stronger contract than the reference's: declarable ops
  here must be trace-compatible to be usable in SameDiff graphs at all).
- *gradient*: analytic `jax.grad` of a fixed random scalar projection of
  the outputs vs a float64 central finite difference, per differentiable
  tensor argument.
- *coverage*: `coverage_report` diffs the case list against the live
  registry; the test suite fails on any op with neither a case nor an
  allowlist entry (and on stale allowlist entries), exactly like the
  reference's `OpValidation.logCoverageInformation` gate.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["OpTestCase", "validate_case", "coverage_report"]


@dataclasses.dataclass
class OpTestCase:
    """One validation case for a registry op (reference `TestCase`)."""

    op: str
    args: Tuple[Any, ...] = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: numpy value(s) or callable(*np_args, **kwargs) -> value(s)
    golden: Any = None
    #: alternative validator: callable(out_leaves: list[np.ndarray]) that
    #: raises on failure — for ops whose value is checked by property
    #: (random sampling moments, round-trips, decode-of-encode, ...)
    check: Optional[Callable] = None
    #: tensor-arg indices to finite-difference gradient-check
    grad: Tuple[int, ...] = ()
    #: if > 0, FD-check only this many seeded random coordinates per arg
    #: (the reference's `TestCase.gradCheckMaxPerParam` — keeps big-input
    #: ops affordable).  `OPVAL_FULL=1` in the env removes the cap.
    grad_sample: int = 0
    tol: float = 1e-5
    gtol: float = 5e-3
    #: also compile under jit + check eval_shape agreement (off for
    #: host-side/ragged ops, which the reference likewise executes eagerly)
    jit: bool = True
    #: fully custom validation — callable(fn) run instead of the pipeline
    #: (TensorList stateful ops, tuple-input ops)
    custom: Optional[Callable] = None
    #: distinguishes multiple cases for one op in test ids
    tag: str = ""

    @property
    def id(self) -> str:
        return f"{self.op}{'-' + self.tag if self.tag else ''}"


def _leaves(out):
    """Flatten an op result (array / tuple / nested) to array leaves."""
    if isinstance(out, (tuple, list)):
        acc = []
        for o in out:
            acc.extend(_leaves(o))
        return acc
    return [out]


def _to_np(leaf):
    return np.asarray(leaf)


def _is_tensor_arg(a) -> bool:
    return isinstance(a, np.ndarray)


def _compare(got, want, tol, what):
    got_l = [_to_np(g) for g in _leaves(got)]
    want_l = [_to_np(w) for w in _leaves(want)]
    assert len(got_l) == len(want_l), (
        f"{what}: output arity {len(got_l)} != golden arity {len(want_l)}")
    for i, (g, w) in enumerate(zip(got_l, want_l)):
        assert tuple(g.shape) == tuple(w.shape), (
            f"{what} leaf {i}: shape {g.shape} != golden {w.shape}")
        if g.dtype == bool or np.issubdtype(g.dtype, np.integer):
            np.testing.assert_array_equal(
                g, w.astype(g.dtype), err_msg=f"{what} leaf {i}")
        elif np.issubdtype(g.dtype, np.complexfloating):
            # compare as complex — a float64 cast would silently drop
            # the imaginary half of every FFT-family check
            np.testing.assert_allclose(
                g.astype(np.complex128), w.astype(np.complex128),
                rtol=tol, atol=tol, err_msg=f"{what} leaf {i}")
        else:
            np.testing.assert_allclose(
                g.astype(np.float64), w.astype(np.float64),
                rtol=tol, atol=tol, err_msg=f"{what} leaf {i}")


def validate_case(case: OpTestCase) -> None:
    """Run the full forward/shape/jit/grad pipeline for one case."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.autodiff.ops import OP_TABLE

    fn = OP_TABLE[case.op]
    if case.custom is not None:
        case.custom(fn)
        return

    tensor_idx = [i for i, a in enumerate(case.args) if _is_tensor_arg(a)]
    jargs = [jnp.asarray(a) if _is_tensor_arg(a) else a for a in case.args]

    # 1. forward (eager)
    out = fn(*jargs, **case.kwargs)

    # 2. value vs golden / property check
    if case.golden is not None:
        want = (case.golden(*[np.asarray(a) if _is_tensor_arg(a) else a
                              for a in case.args], **case.kwargs)
                if callable(case.golden) else case.golden)
        _compare(out, want, case.tol, f"{case.id} forward")
    if case.check is not None:
        case.check([_to_np(o) for o in _leaves(out)])

    # 3. shape function (eval_shape) + jit agreement
    if case.jit and tensor_idx:
        def closure(*tensors):
            full = list(jargs)
            for i, t in zip(tensor_idx, tensors):
                full[i] = t
            return fn(*full, **case.kwargs)

        tensors = [jargs[i] for i in tensor_idx]
        abstract = jax.eval_shape(closure, *tensors)
        a_l = _leaves(abstract)
        o_l = _leaves(out)
        assert len(a_l) == len(o_l), (
            f"{case.id}: eval_shape arity {len(a_l)} != executed "
            f"{len(o_l)}")
        for i, (a, o) in enumerate(zip(a_l, o_l)):
            o = jnp.asarray(o)
            assert tuple(a.shape) == tuple(o.shape), (
                f"{case.id} leaf {i}: abstract shape {a.shape} != "
                f"executed {o.shape}")
            assert a.dtype == o.dtype, (
                f"{case.id} leaf {i}: abstract dtype {a.dtype} != "
                f"executed {o.dtype}")
        out_j = jax.jit(closure)(*tensors)
        _compare(out_j, [_to_np(o) for o in _leaves(out)],
                 max(case.tol, 1e-6), f"{case.id} jit-vs-eager")

    # 4. gradient: analytic vs central finite difference (float64)
    if case.grad:
        _check_grad(fn, case, tensor_idx)


def _check_grad(fn, case: OpTestCase, tensor_idx) -> None:
    import jax

    # The central difference with eps=1e-5 is below float32 noise:
    # without x64 enabled jnp.asarray silently downcasts the f64 inputs
    # and the check produces spurious results.  Enable x64 locally so
    # validate_case is correct even outside the test suite's conftest.
    # `jax.enable_x64` (the context manager re-exported at top level) was
    # removed from recent jax; its home is jax.experimental, with a plain
    # config flip as the last-resort fallback.
    try:
        from jax.experimental import enable_x64
    except ImportError:
        enable_x64 = None
    if enable_x64 is not None:
        with enable_x64():
            _check_grad_x64(fn, case, tensor_idx)
        return
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        _check_grad_x64(fn, case, tensor_idx)
    finally:
        jax.config.update("jax_enable_x64", prev)


def _check_grad_x64(fn, case: OpTestCase, tensor_idx) -> None:
    import jax
    import jax.numpy as jnp

    f64_args = [
        a.astype(np.float64) if (_is_tensor_arg(a)
                                 and np.issubdtype(a.dtype, np.floating))
        else a for a in case.args]
    rs = np.random.RandomState(7)

    # fixed random projection -> scalar loss over all float output leaves.
    # Only the output SHAPES/dtypes are needed to draw the weights, so
    # trace with eval_shape instead of paying a full eager x64 execution;
    # ops that resist abstract evaluation fall back to running eagerly.
    try:
        probe = jax.eval_shape(
            lambda: fn(*[jnp.asarray(a) if _is_tensor_arg(a) else a
                         for a in f64_args], **case.kwargs))
    except Exception:
        probe = fn(*[jnp.asarray(a) if _is_tensor_arg(a) else a
                     for a in f64_args], **case.kwargs)

    def _pdtype(p):
        d = getattr(p, "dtype", None)
        return d if d is not None else np.asarray(p).dtype

    weights = [rs.uniform(0.5, 1.5, np.shape(p)).astype(np.float64)
               if np.issubdtype(_pdtype(p), np.floating) else None
               for p in _leaves(probe)]

    def loss_at(vals):
        full = list(vals)
        out = fn(*[jnp.asarray(a) if _is_tensor_arg(a) else a
                   for a in full], **case.kwargs)
        total = 0.0
        for p, w in zip(_leaves(out), weights):
            if w is not None:
                total = total + jnp.sum(jnp.asarray(p) * w)
        return total

    import os

    for gi in case.grad:
        assert gi in tensor_idx, (
            f"{case.id}: grad index {gi} is not a tensor arg")
        assert np.issubdtype(f64_args[gi].dtype, np.floating), (
            f"{case.id}: grad arg {gi} is not float")

    # one trace for all checked args (argnums), then per-arg FD
    def loss_args(*xs):
        vals = list(f64_args)
        for i, x in zip(case.grad, xs):
            vals[i] = x
        return loss_at(vals)

    analytic_all = jax.grad(loss_args, argnums=tuple(range(len(case.grad))))(
        *[jnp.asarray(f64_args[i]) for i in case.grad])

    sample = 0 if os.environ.get("OPVAL_FULL") else case.grad_sample
    eps = 1e-5
    for pos, gi in enumerate(case.grad):
        x0 = f64_args[gi]
        analytic = np.asarray(analytic_all[pos])
        flat = x0.reshape(-1)
        if sample and flat.size > sample:
            coords = np.random.RandomState(0xC0FFEE + gi).choice(
                flat.size, sample, replace=False)
        else:
            coords = np.arange(flat.size)

        def loss_wrt(x):
            vals = list(f64_args)
            vals[gi] = x
            return loss_at(vals)

        # Batched central difference: evaluate every +eps/-eps perturbation
        # in ONE vmapped call instead of 2*len(coords) eager dispatches —
        # same coordinates, same eps, same tolerance, ~n× less per-op
        # dispatch overhead.  Ops without batching rules (or whose python
        # shape logic rejects the traced call) fall back to the scalar
        # loop below, so vectorization never changes which cases pass.
        try:
            n = len(coords)
            xs = np.tile(flat, (2 * n, 1))
            xs[np.arange(n), coords] += eps
            xs[np.arange(n, 2 * n), coords] -= eps
            vals = np.asarray(jax.vmap(loss_wrt)(
                jnp.asarray(xs.reshape((2 * n,) + x0.shape))))
        except Exception:
            vals = None                 # not vmappable -> scalar fallback
        if vals is not None:
            fd = (vals[:n] - vals[n:]) / (2 * eps)
            np.testing.assert_allclose(
                analytic.reshape(-1)[coords], fd, rtol=case.gtol,
                atol=case.gtol,
                err_msg=f"{case.id} grad wrt arg {gi} (batched FD)")
            continue

        for k in coords:
            xp = flat.copy()
            xm = flat.copy()
            xp[k] += eps
            xm[k] -= eps
            lp = float(loss_wrt(jnp.asarray(xp.reshape(x0.shape))))
            lm = float(loss_wrt(jnp.asarray(xm.reshape(x0.shape))))
            fd = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(
                analytic.reshape(-1)[k], fd, rtol=case.gtol,
                atol=case.gtol,
                err_msg=f"{case.id} grad wrt arg {gi} coord {k}")


def coverage_report(cases: Sequence[OpTestCase],
                    allowlist: Dict[str, str]):
    """Diff the case list against the live registry.

    Returns (missing, stale_allowlist, unknown_ops, value_checked_pct):
    - missing: registered ops with neither a case nor an allowlist entry
    - stale: allowlist entries that DO have a case (keep the list honest)
    - unknown: cases/allowlist naming ops not in the registry
    - value_checked_pct: fraction of registered ops with at least one
      case carrying a golden or a property check
    """
    from deeplearning4j_tpu.autodiff.ops import OP_TABLE

    registered = set(OP_TABLE)
    tested = {c.op for c in cases}
    value_checked = {c.op for c in cases
                     if c.golden is not None or c.check is not None
                     or c.custom is not None}
    missing = sorted(registered - tested - set(allowlist))
    stale = sorted(set(allowlist) & tested)
    unknown = sorted((tested | set(allowlist)) - registered)
    pct = len(value_checked & registered) / max(len(registered), 1)
    return missing, stale, unknown, pct
