"""SameDiff-equivalent: declare a graph, compile the whole step.

Reference: `org/nd4j/autodiff/samediff/SameDiff.java` (~7k LoC) + sessions
(`internal/{AbstractSession,InferenceSession,TrainingSession}.java`) +
codegen'd op namespaces (`samediff/ops/SD{Math,NN,CNN,RNN,Loss}.java`).

Architectural inversion (SURVEY.md §3.2): the reference interprets the graph
op-by-op in Java with a JNI crossing per op and hand-built `doDiff` gradient
graphs; here the declared graph is *traced into one jax function*, `jax.jit`
compiles the entire training step to a single XLA executable, and autodiff is
`jax.grad` — no per-op gradient rules, no interpreter.  Control-flow ops
(Enter/Exit/Switch/Merge/NextIteration frames) are replaced by structured
`lax.cond`/`lax.while_loop`/`lax.scan` via `SameDiff.cond`/
`SameDiff.while_loop`/`SameDiff.scan`: each body is traced into a
serializable child graph (`_SubGraph`), so control flow survives save/load
and differentiates through `jax.grad` (cond and scan; while is fwd-only,
as lax defines).

Serialization replaces FlatBuffers with a zip of graph-JSON + raw tensors
(same zip discipline as utils.serialization).
"""
from __future__ import annotations

import dataclasses
import json
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.ops import OP_TABLE
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.train.updaters import Adam, IUpdater


# ---------------------------------------------------------------------------
# Graph nodes
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Node:
    name: str
    kind: str                   # placeholder | variable | constant | op
    op: Optional[str] = None
    inputs: Tuple[str, ...] = ()
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    shape: Optional[Tuple[int, ...]] = None
    dtype: str = "float32"


class SDVariable:
    """Symbolic handle into a SameDiff graph (reference `SDVariable`)."""

    def __init__(self, sd: "SameDiff", name: str):
        self.sd = sd
        self.name = name

    # -- operator sugar (reference SDVariable.add/mul/mmul/...) --
    def _coerce(self, other) -> "SDVariable":
        return other if isinstance(other, SDVariable) \
            else self.sd.constant(None, other)

    def __add__(self, o): return self.sd.op("add", self, self._coerce(o))
    def __radd__(self, o): return self.sd.op("add", self._coerce(o), self)
    def __sub__(self, o): return self.sd.op("sub", self, self._coerce(o))
    def __rsub__(self, o): return self.sd.op("sub", self._coerce(o), self)
    def __mul__(self, o): return self.sd.op("mul", self, self._coerce(o))
    def __rmul__(self, o): return self.sd.op("mul", self._coerce(o), self)
    def __truediv__(self, o): return self.sd.op("div", self, self._coerce(o))
    def __rtruediv__(self, o): return self.sd.op("div", self._coerce(o), self)
    def __pow__(self, o): return self.sd.op("pow", self, self._coerce(o))
    def __neg__(self): return self.sd.op("neg", self)
    def __matmul__(self, o): return self.sd.op("matmul", self, self._coerce(o))

    def mmul(self, o): return self.sd.op("matmul", self, self._coerce(o))
    def add(self, o): return self.__add__(o)
    def sub(self, o): return self.__sub__(o)
    def mul(self, o): return self.__mul__(o)
    def reshape(self, *shape): return self.sd.op("reshape", self, shape=list(shape))
    def transpose(self, *perm):
        return self.sd.op("transpose", self, perm=list(perm) or None)
    def sum(self, axis=None, keepdims=False):
        return self.sd.op("sum", self, axis=axis, keepdims=keepdims)
    def mean(self, axis=None, keepdims=False):
        return self.sd.op("mean", self, axis=axis, keepdims=keepdims)
    def max(self, axis=None, keepdims=False):
        return self.sd.op("max", self, axis=axis, keepdims=keepdims)
    def min(self, axis=None, keepdims=False):
        return self.sd.op("min", self, axis=axis, keepdims=keepdims)
    def std(self, axis=None, keepdims=False):
        return self.sd.op("std", self, axis=axis, keepdims=keepdims)
    def argmax(self, axis=-1): return self.sd.op("argmax", self, axis=axis)
    def rename(self, name: str) -> "SDVariable":
        return self.sd.rename(self.name, name)

    def eval(self, feeds: Optional[Dict[str, Any]] = None):
        return self.sd.output(feeds or {}, self.name)[self.name]

    def get_arr(self):
        """Current value for variables/constants (reference
        `SDVariable.getArr`)."""
        node = self.sd._nodes[self.name]
        if node.kind == "variable":
            return self.sd.variables_[self.name]
        if node.kind == "constant":
            return self.sd._constants[self.name]
        raise ValueError(f"{self.name} has no stored array (kind={node.kind})")

    def __repr__(self):
        return f"SDVariable({self.name!r})"


# ---------------------------------------------------------------------------
# Op namespaces (reference codegen'd SDMath / SDNN / SDCNN / SDRNN / SDLoss)
# ---------------------------------------------------------------------------

class _Namespace:
    def __init__(self, sd: "SameDiff"):
        self._sd = sd


class SDMath(_Namespace):
    def __getattr__(self, op):
        if op.startswith("_"):
            raise AttributeError(op)
        if op not in OP_TABLE:
            raise AttributeError(
                f"No op '{op}' registered (reference: unmapped op error in "
                "ImportGraph — add via autodiff.ops.register_op)")

        def call(*args, name=None, **attrs):
            return self._sd.op(op, *args, name=name, **attrs)
        return call


class SDNN(_Namespace):
    def relu(self, x, name=None): return self._sd.op("relu", x, name=name)
    def sigmoid(self, x, name=None): return self._sd.op("sigmoid", x, name=name)
    def tanh(self, x, name=None): return self._sd.op("tanh", x, name=name)
    def gelu(self, x, name=None): return self._sd.op("gelu", x, name=name)
    def elu(self, x, name=None): return self._sd.op("elu", x, name=name)
    def softmax(self, x, axis=-1, name=None):
        return self._sd.op("softmax", x, axis=axis, name=name)
    def log_softmax(self, x, axis=-1, name=None):
        return self._sd.op("log_softmax", x, axis=axis, name=name)
    def linear(self, x, w, b=None, name=None):
        args = (x, w) if b is None else (x, w, b)
        return self._sd.op("linear", *args, name=name)
    def layer_norm(self, x, gain, bias=None, eps=1e-5, name=None):
        args = (x, gain) if bias is None else (x, gain, bias)
        return self._sd.op("layer_norm", *args, eps=eps, name=name)
    def dropout(self, x, p=0.5, name=None):
        """Active only during fit() (rng is fed by the train step); each
        dropout site folds its own tag so masks are independent."""
        site = self._sd.op("rng_fold_opt", self._sd._rng_var(),
                           tag=self._sd._next_rng_tag())
        return self._sd.op("dropout", x, site, p=p, name=name)
    def batch_norm(self, x, mean, var, gamma=None, beta=None, eps=1e-5,
                   name=None):
        args = [x, mean, var] + ([gamma] if gamma is not None else []) \
            + ([beta] if beta is not None else [])
        return self._sd.op("batch_norm", *args, eps=eps, name=name)
    def multi_head_dot_product_attention(self, q, k, v, mask=None, name=None):
        args = (q, k, v) if mask is None else (q, k, v, mask)
        return self._sd.op("dot_product_attention", *args, name=name)


class SDCNN(_Namespace):
    def conv2d(self, x, w, b=None, stride=(1, 1), padding="SAME",
               dilation=(1, 1), name=None):
        args = (x, w) if b is None else (x, w, b)
        return self._sd.op("conv2d", *args, stride=tuple(stride),
                           padding=padding, dilation=tuple(dilation),
                           name=name)
    def max_pooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                      name=None):
        return self._sd.op("max_pooling2d", x, kernel=tuple(kernel),
                           stride=tuple(stride), padding=padding, name=name)
    def avg_pooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID",
                      name=None):
        return self._sd.op("avg_pooling2d", x, kernel=tuple(kernel),
                           stride=tuple(stride), padding=padding, name=name)


class SDRNN(_Namespace):
    def lstm_layer(self, x, w, rw, b, name=None):
        """Whole-sequence LSTM via lax.scan (the cuDNN-LSTM → scan item,
        SURVEY.md §7 hard part (d)); IFOG gate order, [B,T,F] in,
        [B,T,H] out."""
        return self._sd.op("lstm_layer", x, w, rw, b, name=name)


class _TableNamespace(_Namespace):
    """Generic OP_TABLE delegation scoped by a name list (the codegen'd
    namespace classes collapse to a whitelist over the registry)."""

    OPS: tuple = ()

    def __getattr__(self, op):
        if op.startswith("_") or (self.OPS and op not in self.OPS):
            raise AttributeError(
                f"{type(self).__name__} has no op '{op}'")
        if op not in OP_TABLE:
            raise AttributeError(
                f"No op '{op}' registered (reference: unmapped op error in "
                "ImportGraph — add via autodiff.ops.register_op)")

        def call(*args, name=None, **attrs):
            return self._sd.op(op, *args, name=name, **attrs)
        return call


class SDBitwise(_TableNamespace):
    """Reference `SDBitwise` namespace."""
    OPS = ("bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
           "shift_left", "shift_right", "cyclic_shift_left",
           "bits_hamming_distance", "toggle_bits")


class SDImage(_TableNamespace):
    """Reference `SDImage` namespace."""
    OPS = ("rgb_to_hsv", "hsv_to_rgb", "rgb_to_yiq", "yiq_to_rgb",
           "rgb_to_yuv", "yuv_to_rgb", "rgb_to_grs", "adjust_hue",
           "adjust_saturation", "adjust_contrast", "crop_and_resize",
           "extract_image_patches", "non_max_suppression",
           "resize_bilinear", "resize_nearest", "image_resize")


class SDLinalg(_TableNamespace):
    """Reference `SDLinalg` namespace."""
    OPS = ("cholesky", "solve", "triangular_solve", "matrix_inverse",
           "matrix_determinant", "log_matrix_determinant", "qr", "svd",
           "eig_sym", "lstsq", "lu", "pinv", "expm", "matrix_band_part",
           "matrix_diag", "matrix_diag_part", "matrix_set_diag", "mmul",
           "matmul", "tri", "tril", "triu", "cross", "diag", "diag_part",
           "trace", "einsum")


class SDRandom(_Namespace):
    """Reference `SDRandom` namespace; the PRNG key is the train step's
    per-iteration rng feed (same mechanism as dropout), so samples change
    every fit() step and are deterministic per (seed, iteration).  Each
    random node folds a unique tag into the shared per-step key so
    independent sample sites draw independent streams."""

    _OPS = ("random_uniform", "random_normal", "random_bernoulli",
            "random_exponential", "random_gamma", "random_poisson",
            "random_shuffle", "multinomial")

    def _site_key(self):
        return self._sd.op("rng_fold", self._sd._rng_var(),
                           tag=self._sd._next_rng_tag())

    def __getattr__(self, op):
        if op.startswith("_") or op not in self._OPS:
            raise AttributeError(f"SDRandom has no op '{op}'")

        def call(*args, name=None, **attrs):
            return self._sd.op(op, self._site_key(), *args, name=name,
                               **attrs)
        return call

    # reference-style aliases (shape/params ride as attrs: the executor
    # calls OP_TABLE[op](*inputs, **attrs))
    def uniform(self, low, high, shape, name=None):
        return self._sd.op("random_uniform", self._site_key(),
                           shape=tuple(shape), minval=low, maxval=high,
                           name=name)

    def normal(self, mean, stddev, shape, name=None):
        return self._sd.op("random_normal", self._site_key(),
                           shape=tuple(shape), mean=mean, stddev=stddev,
                           name=name)

    def bernoulli(self, p, shape, name=None):
        return self._sd.op("random_bernoulli", self._site_key(),
                           shape=tuple(shape), p=p, name=name)


class SDLoss(_Namespace):
    def softmax_cross_entropy(self, labels, logits, name=None):
        return self._sd.op("softmax_cross_entropy", labels, logits, name=name)
    def sparse_softmax_cross_entropy(self, labels, logits, name=None):
        return self._sd.op("sparse_softmax_cross_entropy", labels, logits,
                           name=name)
    def sigmoid_cross_entropy(self, labels, logits, name=None):
        return self._sd.op("sigmoid_cross_entropy", labels, logits, name=name)
    def mean_squared_error(self, labels, preds, name=None):
        return self._sd.op("mean_squared_error", labels, preds, name=name)
    def absolute_difference(self, labels, preds, name=None):
        return self._sd.op("absolute_difference", labels, preds, name=name)
    def l2_loss(self, x, name=None):
        return self._sd.op("l2_loss", x, name=name)
    def huber_loss(self, labels, preds, delta=1.0, name=None):
        return self._sd.op("huber_loss", labels, preds, delta=delta, name=name)
    def log_loss(self, labels, probs, name=None):
        return self._sd.op("log_loss", labels, probs, name=name)
    def cosine_distance(self, labels, preds, axis=-1, name=None):
        return self._sd.op("cosine_distance", labels, preds, axis=axis,
                           name=name)


# lstm_layer is registered in autodiff.ops (IFOG single-output form —
# the sd.rnn.lstm_layer contract); lstm_layer_full carries the reference
# lstmLayer's (ys, h, c) output mode.


# ---------------------------------------------------------------------------
# TrainingConfig
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TrainingConfig:
    """Reference `TrainingConfig`: updater + which placeholders receive
    features/labels + l1/l2."""

    updater: IUpdater = dataclasses.field(default_factory=lambda: Adam(1e-3))
    data_set_feature_mapping: Sequence[str] = ()
    data_set_label_mapping: Sequence[str] = ()
    l1: float = 0.0
    l2: float = 0.0

    def to_json(self) -> dict:
        return {"updater": self.updater.to_json(),
                "features": list(self.data_set_feature_mapping),
                "labels": list(self.data_set_label_mapping),
                "l1": self.l1, "l2": self.l2}

    @staticmethod
    def from_json(d: dict) -> "TrainingConfig":
        return TrainingConfig(updater=IUpdater.from_json(d["updater"]),
                              data_set_feature_mapping=d["features"],
                              data_set_label_mapping=d["labels"],
                              l1=d.get("l1", 0.0), l2=d.get("l2", 0.0))


# ---------------------------------------------------------------------------
# Control flow (reference: Switch/Merge/Enter/Exit/NextIteration frames in
# `org/nd4j/autodiff/samediff/internal/AbstractSession.java`; here each body
# is traced into a child graph and lowered to lax.cond/while_loop/scan —
# SURVEY.md §3.2's "frames → structured lax control flow" inversion)
# ---------------------------------------------------------------------------

_CONTROL_FLOW_OPS = ("cond", "while_loop", "scan")


class _SubGraph:
    """A traced sub-function: its own node set + constants, positional
    placeholder args, named outputs.  Serializes to plain JSON so
    control-flow nodes survive SameDiff.save/load."""

    def __init__(self, sd: "SameDiff", arg_names: List[str],
                 out_names: List[str]):
        self.sd = sd
        self.arg_names = arg_names
        self.out_names = out_names

    @staticmethod
    def trace(fn: Callable, n_args: int) -> "_SubGraph":
        child = SameDiff()
        phs = [child.placeholder(f"__arg{i}__") for i in range(n_args)]
        outs = fn(child, *phs)
        if isinstance(outs, SDVariable):
            outs = (outs,)
        out_names = []
        for o in outs:
            if not isinstance(o, SDVariable) or o.sd is not child:
                raise ValueError(
                    "control-flow body must return SDVariable(s) built in "
                    "the scope it was handed (fn(scope, *args) -> vars)")
            out_names.append(o.name)
        if child.variables_:
            raise ValueError(
                "control-flow bodies cannot declare trainable variables — "
                "declare them in the outer graph and pass as operands")
        return _SubGraph(child, [p.name for p in phs], out_names)

    def call(self, args: Sequence[Any]) -> Tuple[Any, ...]:
        feeds = dict(zip(self.arg_names, args))
        outs = self.sd._eval_graph(feeds, {}, self.out_names)
        return tuple(outs[n] for n in self.out_names)

    def to_json(self) -> dict:
        consts = {}
        for k, v in self.sd._constants.items():
            a = np.asarray(v)
            consts[k] = {"data": a.tolist(), "dtype": str(a.dtype),
                         "shape": list(a.shape)}
        return {"nodes": [dataclasses.asdict(n)
                          for n in self.sd._nodes.values()],
                "constants": consts,
                "args": self.arg_names, "outputs": self.out_names}

    @staticmethod
    def from_json(d: dict) -> "_SubGraph":
        child = SameDiff()
        for nd in d["nodes"]:
            node = Node(name=nd["name"], kind=nd["kind"], op=nd.get("op"),
                        inputs=tuple(nd["inputs"]),
                        attrs=_detuple_attrs(nd.get("attrs", {})),
                        shape=None if nd.get("shape") is None
                        else tuple(nd["shape"]),
                        dtype=nd.get("dtype", "float32"))
            child._nodes[node.name] = node
        child._constants = {
            k: jnp.asarray(np.array(v["data"], dtype=v["dtype"])
                           .reshape(v["shape"]))
            for k, v in d["constants"].items()}
        return _SubGraph(child, list(d["args"]), list(d["outputs"]))


def _eval_control_flow(node: "Node", args: List[Any]) -> Any:
    """Lower a control-flow node to the matching lax primitive.  Runs at
    trace time only (inside jit), so re-hydrating subgraphs from their JSON
    attrs costs nothing at execution time."""
    a = node.attrs
    if node.op == "cond":
        tg = _SubGraph.from_json(a["true_graph"])
        fg = _SubGraph.from_json(a["false_graph"])
        pred, operands = args[0], tuple(args[1:])
        pred = jnp.reshape(jnp.asarray(pred), ()).astype(bool)
        # lax.cond requires identical output types; promote pairwise so a
        # weakly-typed constant in one branch doesn't poison the node.
        t_shape = jax.eval_shape(tg.call, operands)
        f_shape = jax.eval_shape(fg.call, operands)
        dts = [jnp.promote_types(t.dtype, f.dtype)
               for t, f in zip(t_shape, f_shape)]
        out = jax.lax.cond(
            pred,
            lambda ops: tuple(o.astype(d)
                              for o, d in zip(tg.call(ops), dts)),
            lambda ops: tuple(o.astype(d)
                              for o, d in zip(fg.call(ops), dts)),
            operands)
        return out[0] if len(out) == 1 else tuple(out)
    if node.op == "while_loop":
        cg = _SubGraph.from_json(a["cond_graph"])
        bg = _SubGraph.from_json(a["body_graph"])
        init = tuple(jnp.asarray(x) for x in args)
        dts = [x.dtype for x in init]     # body must preserve state types
        state = jax.lax.while_loop(
            lambda s: jnp.reshape(cg.call(s)[0], ()).astype(bool),
            lambda s: tuple(o.astype(d) for o, d in zip(bg.call(s), dts)),
            init)
        return state[0] if len(state) == 1 else tuple(state)
    if node.op == "scan":
        bg = _SubGraph.from_json(a["body_graph"])
        n_carry = int(a["n_carry"])
        consts = tuple(args[n_carry + 1:])

        def body(carry, x):
            outs = bg.call(tuple(carry) + (x,) + consts)
            new_carry = tuple(o.astype(c.dtype)
                              for o, c in zip(outs[:n_carry], carry))
            return new_carry, tuple(outs[n_carry:])

        carry, ys = jax.lax.scan(body, tuple(args[:n_carry]), args[n_carry])
        return tuple(carry) + tuple(ys)
    raise KeyError(node.op)


# ---------------------------------------------------------------------------
# SameDiff
# ---------------------------------------------------------------------------

RNG_FEED = "__dropout_rng__"


class SameDiff:
    """The graph container (reference `SameDiff.create()`)."""

    def __init__(self):
        self._nodes: Dict[str, Node] = {}
        self.variables_: Dict[str, jnp.ndarray] = {}   # trainable values
        self._constants: Dict[str, jnp.ndarray] = {}
        self._loss_names: List[str] = []
        self._counter = 0
        self.training_config: Optional[TrainingConfig] = None
        self.opt_state_: Optional[Any] = None
        self.iteration = 0
        self.epoch = 0
        self._train_step = None
        self._scan_step = None
        self._step_transform = None   # ZeRO-1 weight update (parallel/zero)
        self._exec_cache_override = None  # compile.PersistentExecutableCache
        self._schedule = None             # compile.Schedule (autotuner)
        self._output_fns: Dict[Tuple[str, ...], Callable] = {}
        self._key = jax.random.PRNGKey(0)
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.bitwise = SDBitwise(self)
        self.image = SDImage(self)
        self.linalg = SDLinalg(self)
        self.random = SDRandom(self)

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ---- naming ----
    def _fresh(self, base: str) -> str:
        # ':' is illegal in TF/ONNX node names, so auto-generated names can
        # never collide with names arriving later from a model import
        self._counter += 1
        name = f"{base}:{self._counter}"
        while name in self._nodes:
            self._counter += 1
            name = f"{base}:{self._counter}"
        return name

    def _add(self, node: Node) -> SDVariable:
        if node.name in self._nodes:
            raise ValueError(f"Duplicate variable name '{node.name}'")
        self._nodes[node.name] = node
        self._invalidate()
        return SDVariable(self, node.name)

    def _invalidate(self):
        self._train_step = None
        self._scan_step = None
        self._output_fns = {}

    # ---- declaration API ----
    def placeholder(self, name: str, shape: Optional[Sequence[int]] = None,
                    dtype: str = "float32") -> SDVariable:
        """reference `sd.placeHolder` (-1 = batch dim, kept as None)."""
        shp = None if shape is None else tuple(
            None if s in (-1, None) else int(s) for s in shape)
        return self._add(Node(name, "placeholder", shape=shp, dtype=dtype))

    place_holder = placeholder

    def var(self, name: str, init: Union[np.ndarray, str],
            *shape: int, dtype: str = "float32") -> SDVariable:
        """Trainable variable: `sd.var("w", array)` or
        `sd.var("w", "XAVIER", 784, 10)` (reference weight-init schemes)."""
        if isinstance(init, str):
            self._key, sub = jax.random.split(self._key)
            arr = init_weights(sub, tuple(shape), init, jnp.dtype(dtype))
        else:
            arr = jnp.asarray(init)
        v = self._add(Node(name, "variable", shape=tuple(arr.shape),
                           dtype=str(arr.dtype)))
        self.variables_[name] = arr
        return v

    def zero(self, name: str, *shape: int, dtype: str = "float32"):
        return self.var(name, np.zeros(shape, dtype))

    def one(self, name: str, *shape: int, dtype: str = "float32"):
        return self.var(name, np.ones(shape, dtype))

    def constant(self, name: Optional[str], value) -> SDVariable:
        arr = jnp.asarray(value)
        name = name or self._fresh("const")
        v = self._add(Node(name, "constant", shape=tuple(arr.shape),
                           dtype=str(arr.dtype)))
        self._constants[name] = arr
        return v

    def op(self, opname: str, *inputs, name: Optional[str] = None,
           **attrs) -> SDVariable:
        if opname not in OP_TABLE and opname not in _CONTROL_FLOW_OPS:
            raise KeyError(
                f"Unmapped op '{opname}' — the reference raises the same "
                "named error from ImportGraph/OpMappingRegistry; register "
                "via autodiff.ops.register_op")
        ins = []
        for x in inputs:
            if isinstance(x, SDVariable):
                if x.sd is not self:
                    raise ValueError(
                        f"'{x.name}' belongs to a different SameDiff scope "
                        "(reference: cross-frame use needs Enter; here, pass "
                        "it as an operand to the control-flow op instead)")
                ins.append(x.name)
            else:
                ins.append(self.constant(None, x).name)
        name = name or self._fresh(opname)
        return self._add(Node(name, "op", op=opname, inputs=tuple(ins),
                              attrs=dict(attrs)))

    def rename(self, old: str, new: str) -> SDVariable:
        if new in self._nodes:
            raise ValueError(f"Cannot rename '{old}' to '{new}': name taken")
        node = self._nodes.pop(old)
        node.name = new
        self._nodes[new] = node
        if old in self.variables_:
            self.variables_[new] = self.variables_.pop(old)
        if old in self._constants:
            self._constants[new] = self._constants.pop(old)
        for n in self._nodes.values():
            n.inputs = tuple(new if i == old else i for i in n.inputs)
        self._loss_names = [new if n == old else n for n in self._loss_names]
        self._invalidate()
        return SDVariable(self, new)

    def get_variable(self, name: str) -> SDVariable:
        return SDVariable(self, name)

    def _rng_var(self) -> SDVariable:
        """Hidden placeholder feeding dropout rng during training."""
        if RNG_FEED not in self._nodes:
            self._add(Node(RNG_FEED, "placeholder", dtype="uint32"))
        return SDVariable(self, RNG_FEED)

    def _next_rng_tag(self) -> int:
        """Unique static tag per stochastic node; folded into the shared
        per-step key so sample sites draw independent streams.  Seeded from
        the tags already present in the graph so nodes added after a
        save()/load() round-trip never reuse an existing tag."""
        tag = getattr(self, "_rng_tag", None)
        if tag is None:
            tag = 1 + max(
                (int(n.attrs.get("tag", -1)) for n in self._nodes.values()
                 if n.kind == "op" and n.op in ("rng_fold", "rng_fold_opt")),
                default=-1)
        self._rng_tag = tag + 1
        return tag

    # ---- control flow (reference Switch/Merge/Enter/Exit → lax) ----
    def _split_outputs(self, v: SDVariable, n_out: int):
        if n_out == 1:
            return v
        return tuple(self.op("tuple_get", v, index=i) for i in range(n_out))

    def cond(self, pred, true_fn: Callable, false_fn: Callable,
             *operands, name: Optional[str] = None):
        """`sd.cond(pred, lambda s, x: ..., lambda s, x: ..., x)` →
        lax.cond.  Each branch fn receives a fresh scope plus one SDVariable
        per operand and returns the same number of outputs as the other
        branch.  Differentiable (reference: Switch/Merge frames in
        AbstractSession.java had no gradient support at all)."""
        n = len(operands)
        tg = _SubGraph.trace(true_fn, n)
        fg = _SubGraph.trace(false_fn, n)
        if len(tg.out_names) != len(fg.out_names):
            raise ValueError(
                f"cond branches disagree on output arity "
                f"({len(tg.out_names)} vs {len(fg.out_names)})")
        v = self.op("cond", pred, *operands, name=name,
                    true_graph=tg.to_json(), false_graph=fg.to_json(),
                    n_out=len(tg.out_names))
        return self._split_outputs(v, len(tg.out_names))

    def while_loop(self, cond_fn: Callable, body_fn: Callable,
                   *init, name: Optional[str] = None):
        """`sd.while_loop(lambda s, i, acc: ..., lambda s, i, acc: (...), i0,
        acc0)` → lax.while_loop.  `cond_fn` returns one scalar-bool output;
        `body_fn` returns one output per loop-state operand.  Forward-only
        (lax.while_loop is not reverse-differentiable; use scan for trainable
        recurrences — same restriction the reference's While frames had in
        practice)."""
        n = len(init)
        cg = _SubGraph.trace(cond_fn, n)
        if len(cg.out_names) != 1:
            raise ValueError("while_loop cond_fn must return exactly one "
                             "(scalar bool) output")
        bg = _SubGraph.trace(body_fn, n)
        if len(bg.out_names) != n:
            raise ValueError(
                f"while_loop body_fn must return {n} outputs (one per loop "
                f"state operand), got {len(bg.out_names)}")
        v = self.op("while_loop", *init, name=name,
                    cond_graph=cg.to_json(), body_graph=bg.to_json())
        return self._split_outputs(v, n)

    def scan(self, body_fn: Callable, init, xs, *, consts=(),
             name: Optional[str] = None):
        """`sd.scan(lambda s, carry..., x, *consts: (new_carry..., y...),
        init, xs, consts=(w, ...))` → lax.scan over the leading axis of
        `xs`.  `consts` are loop-invariant operands (weights etc.) handed to
        every step — the closure-free substitute for the reference frames'
        Enter-as-constant edges.  Returns `(final_carry, ys)` where `ys` are
        the per-step outputs stacked on a new leading axis.  Fully
        differentiable — this is the structured replacement for the
        reference's NextIteration/loop frames."""
        carry = tuple(init) if isinstance(init, (tuple, list)) else (init,)
        n_carry = len(carry)
        consts = tuple(consts)
        bg = _SubGraph.trace(body_fn, n_carry + 1 + len(consts))
        n_ys = len(bg.out_names) - n_carry
        if n_ys < 1:
            raise ValueError(
                f"scan body_fn must return the {n_carry} new carry value(s) "
                "plus at least one per-step output")
        v = self.op("scan", *carry, xs, *consts, name=name,
                    body_graph=bg.to_json(), n_carry=n_carry,
                    n_consts=len(consts))
        parts = self._split_outputs(v, n_carry + n_ys)
        fc = parts[:n_carry]
        ys = parts[n_carry:]
        final_carry = fc if isinstance(init, (tuple, list)) else fc[0]
        return final_carry, (ys[0] if n_ys == 1 else ys)

    def set_loss_variables(self, *names):
        self._loss_names = [n.name if isinstance(n, SDVariable) else n
                            for n in names]
        self._invalidate()

    def set_training_config(self, cfg: TrainingConfig):
        self.training_config = cfg
        self._invalidate()

    # ---- evaluation (the compiled InferenceSession replacement) ----
    def _eval_graph(self, feeds: Dict[str, Any], variables: Dict[str, Any],
                    names: Sequence[str]) -> Dict[str, Any]:
        """Iterative post-order walk (explicit stack, no Python recursion —
        deep chains of ops would blow the recursion limit during tracing)."""
        cache: Dict[str, Any] = {}

        def leaf_value(node: Node):
            n = node.name
            if node.kind == "placeholder":
                if n not in feeds:
                    if n == RNG_FEED:
                        return None
                    raise KeyError(f"Placeholder '{n}' not fed")
                return feeds[n]
            if node.kind == "variable":
                return variables[n]
            return self._constants[n]          # constant

        for target in names:
            stack = [target]
            while stack:
                n = stack[-1]
                if n in cache:
                    stack.pop()
                    continue
                node = self._nodes[n]
                if node.kind != "op":
                    cache[n] = leaf_value(node)
                    stack.pop()
                    continue
                pending = [i for i in node.inputs if i not in cache]
                if pending:
                    stack.extend(pending)
                    continue
                args = [cache[i] for i in node.inputs]
                if node.op in _CONTROL_FLOW_OPS:
                    cache[n] = _eval_control_flow(node, args)
                else:
                    cache[n] = OP_TABLE[node.op](*args, **node.attrs)
                stack.pop()

        return {n: cache[n] for n in names}

    def output(self, feeds: Dict[str, Any], *names) -> Dict[str, Any]:
        """Compiled multi-output inference (reference
        `sd.output(Map, String...)`). One executable per requested-name set."""
        names = tuple(n.name if isinstance(n, SDVariable) else n
                      for n in names)
        feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        if names not in self._output_fns:
            def f(variables, feeds):
                return self._eval_graph(feeds, variables, names)
            self._output_fns[names] = jax.jit(f)
        return self._output_fns[names](self.variables_, feeds)

    def batch_output(self, feeds, *names):
        return self.output(feeds, *names)

    # ---- training (the compiled TrainingSession replacement) ----
    def _total_loss(self, variables, feeds):
        vals = self._eval_graph(feeds, variables, self._loss_names)
        loss = 0.0
        for v in vals.values():
            loss = loss + (v if jnp.ndim(v) == 0 else jnp.sum(v))
        cfg = self.training_config
        if cfg and (cfg.l1 or cfg.l2):
            for arr in variables.values():
                if cfg.l1:
                    loss = loss + cfg.l1 * jnp.sum(jnp.abs(arr))
                if cfg.l2:
                    loss = loss + 0.5 * cfg.l2 * jnp.sum(arr * arr)
        return loss

    def _build_step_body(self):
        cfg = self.training_config
        has_rng = RNG_FEED in self._nodes   # static at trace time; the step
        # cache is invalidated whenever the graph mutates
        zt = self._step_transform   # ZeRO-1 sharded weight update, or None

        def step(variables, opt_state, feeds, rng, iteration, epoch):
            if has_rng:
                rng, sub = jax.random.split(rng)
                feeds = dict(feeds)
                feeds[RNG_FEED] = sub
            master = variables
            if zt is not None:
                variables = zt.gather_all(variables)

            def loss_fn(vs):
                return self._total_loss(vs, feeds)
            loss, grads = jax.value_and_grad(loss_fn)(variables)
            if zt is None:
                upd, new_opt = cfg.updater.apply(opt_state, grads, iteration,
                                                 epoch, params=variables)
                new_vars = jax.tree_util.tree_map(lambda p, u: p - u,
                                                  variables, upd)
            else:
                # reduce-scatter grads over the data axis, run the updater
                # on the local shard, all-gather via restore()
                grads = zt.scatter(None, grads)
                p_upd = zt.update_view(None, master)
                upd, new_opt = cfg.updater.apply(opt_state, grads, iteration,
                                                 epoch, params=p_upd)
                new_vars = jax.tree_util.tree_map(lambda p, u: p - u,
                                                  p_upd, upd)
                new_vars = zt.restore(None, new_vars)
                new_opt = zt.constrain_opt(None, new_opt)
            return new_vars, new_opt, loss, rng, iteration + 1

        return step

    def _exec_cache(self):
        """The persistent executable cache in play: the per-graph override
        (`set_executable_cache`), else the process default — None keeps
        the plain jax.jit path."""
        if self._exec_cache_override is not None:
            return self._exec_cache_override
        from deeplearning4j_tpu.compile import default_cache
        return default_cache()

    def set_executable_cache(self, cache) -> "SameDiff":
        """Route this graph's train-step compilation through a
        `compile.PersistentExecutableCache` (or a directory path); None
        reverts to the process default.  Triggers a step rebuild."""
        if isinstance(cache, str):
            from deeplearning4j_tpu.compile import PersistentExecutableCache
            cache = PersistentExecutableCache(cache)
        self._exec_cache_override = cache
        self._train_step = None
        self._scan_step = None
        return self

    def apply_schedule(self, schedule) -> "SameDiff":
        """Install an autotuned `compile.Schedule` (iterator `fit()`
        defaults `fused_steps` from it; the step builder honors
        `schedule.donation`).  Triggers a step rebuild."""
        self._schedule = schedule
        self._train_step = None
        self._scan_step = None
        return self

    def _donate_argnums(self) -> tuple:
        if self._schedule is not None and not self._schedule.donation:
            return ()
        return (0, 1)

    def _aot_key_parts(self) -> dict:
        from deeplearning4j_tpu.compile import (model_fingerprint,
                                                transform_fingerprint)
        return {"kind": "samediff_train_step",
                "model": model_fingerprint(self),
                "transform": transform_fingerprint(self._step_transform)}

    def _build_train_step(self):
        from deeplearning4j_tpu.compile import step_function
        return step_function(self._build_step_body(),
                             donate_argnums=self._donate_argnums(),
                             key_base=self._aot_key_parts,
                             cache=self._exec_cache(),
                             dynamic_argnums=(2,))

    def _build_scan_step(self):
        """k steps per dispatch (see utils/scan_fit.py); SameDiff's carry
        is (variables, opt_state, rng, iteration), scanning over feeds."""
        from deeplearning4j_tpu.utils.scan_fit import make_scan_step
        body = self._build_step_body()

        def tick(carry, epoch, feed):
            v, o, r, it = carry
            v, o, loss, r, it = body(v, o, feed, r, it, epoch)
            return (v, o, r, it), loss

        return make_scan_step(
            tick,
            key_base=lambda: dict(self._aot_key_parts(),
                                  kind="samediff_scan_step"),
            cache=self._exec_cache(),
            donate=(self._schedule is None or self._schedule.donation))

    def fit(self, data=None, labels=None, *, iterator=None, epochs: int = 1,
            feeds: Optional[Dict[str, Any]] = None,
            fused_steps: Optional[int] = None) -> "SameDiff":
        """fit(features, labels) / fit(feeds={...}) for one batch, or
        fit(iterator=multi_data_set_iterator, epochs=N).  `fused_steps=k`
        fuses blocks of k consecutive same-shape batches from the
        iterator into one `fit_steps` dispatch (tails fall back); unset,
        it defaults to the installed schedule's (`apply_schedule`),
        else 1."""
        if fused_steps is None:
            fused_steps = (self._schedule.fused_steps
                           if self._schedule is not None else 1)
        if self.training_config is None:
            raise ValueError("set_training_config(...) first (reference "
                             "throws the same)")
        if not self._loss_names:
            raise ValueError("set_loss_variables(...) first")
        if self.opt_state_ is None:
            self.opt_state_ = self.training_config.updater.init_state(
                self.variables_)
        if self._train_step is None:
            self._train_step = self._build_train_step()

        if iterator is not None:
            from deeplearning4j_tpu.utils.scan_fit import blocks_of
            for _ in range(epochs):
                if hasattr(iterator, "reset"):
                    iterator.reset()
                if fused_steps > 1:
                    for block in blocks_of(iterator, fused_steps):
                        if len(block) == 1:
                            self._fit_feeds(self._map_dataset(block[0]))
                        else:
                            fl = [self._map_dataset(ds) for ds in block]
                            self.fit_steps(
                                {k: np.stack([np.asarray(f[k]) for f in fl])
                                 for k in fl[0]})
                else:
                    for ds in iterator:
                        self._fit_feeds(self._map_dataset(ds))
                self.epoch += 1
            return self
        if feeds is None:
            cfg = self.training_config
            feeds = {}
            xs = data if isinstance(data, (list, tuple)) else [data]
            ys = labels if isinstance(labels, (list, tuple)) else [labels]
            for n, v in zip(cfg.data_set_feature_mapping, xs):
                feeds[n] = v
            for n, v in zip(cfg.data_set_label_mapping, ys):
                feeds[n] = v
        self._fit_feeds(feeds)
        return self

    def _map_dataset(self, ds):
        cfg = self.training_config
        feeds = {}
        feats = ds.features if isinstance(ds.features, (list, tuple)) \
            else [ds.features]
        labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
            else [ds.labels]
        for n, v in zip(cfg.data_set_feature_mapping, feats):
            feeds[n] = v
        for n, v in zip(cfg.data_set_label_mapping, labs):
            feeds[n] = v
        return feeds

    def _fit_feeds(self, feeds: Dict[str, Any]):
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        it_dev, ep_dev = device_counters(self)
        (self.variables_, self.opt_state_, loss, self._key,
         new_it) = self._train_step(
            self.variables_, self.opt_state_, feeds, self._key,
            it_dev, ep_dev)
        self._score = loss
        advance(self, new_it)

    def fit_steps(self, feeds: Dict[str, Any]):
        """Run k training steps in one device dispatch: every feed array
        carries a leading `[k, batch, ...]` steps axis.  Same math as k
        sequential `fit(feeds=...)` calls (variables/updater-state/rng/
        iteration flow step-to-step as scan carries); returns the
        length-k per-step loss array."""
        from deeplearning4j_tpu.utils.counters import advance, device_counters
        if self.training_config is None:
            raise ValueError("set_training_config(...) first (reference "
                             "throws the same)")
        if not self._loss_names:
            raise ValueError("set_loss_variables(...) first")
        if self.opt_state_ is None:
            self.opt_state_ = self.training_config.updater.init_state(
                self.variables_)
        from deeplearning4j_tpu.utils.scan_fit import check_steps_axes
        feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
        k = check_steps_axes(feeds.items())
        if self._scan_step is None:
            self._scan_step = self._build_scan_step()
        it_dev, ep_dev = device_counters(self)
        ((self.variables_, self.opt_state_, self._key, new_it),
         losses, last_loss) = self._scan_step(
            (self.variables_, self.opt_state_, self._key, it_dev),
            ep_dev, feeds)
        self._score = last_loss
        advance(self, new_it, steps=int(k))
        return losses

    def score(self) -> float:
        s = getattr(self, "_score", None)
        return float(s) if s is not None else float("nan")

    def evaluate(self, iterator, output_name, evaluation=None,
                 label_index: int = 0):
        """Classification eval over a DataSetIterator (reference
        `sd.evaluate(iterator, outputVariable, new Evaluation())`): feeds
        come from the TrainingConfig mappings, predictions from the named
        output."""
        from deeplearning4j_tpu.train.evaluation import Evaluation
        if self.training_config is None:
            raise ValueError("set_training_config(...) first — evaluate "
                             "uses its feature/label mappings")
        output_name = output_name.name if isinstance(output_name,
                                                     SDVariable) \
            else output_name
        ev = evaluation or Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            feeds = self._map_dataset(ds)
            labels = ds.labels[label_index] \
                if isinstance(ds.labels, (list, tuple)) else ds.labels
            # drop label placeholders the forward pass doesn't need
            preds = self.output(
                {k: v for k, v in feeds.items()
                 if k not in self.training_config.data_set_label_mapping},
                output_name)[output_name]
            lmask = getattr(ds, "labels_mask", None)
            if lmask is None:
                lmasks = getattr(ds, "labels_masks", None)
                if lmasks is not None:
                    lmask = lmasks[label_index]
            ev.eval(np.asarray(labels), np.asarray(preds),
                    mask=None if lmask is None else np.asarray(lmask))
        return ev

    def calculate_gradients(self, feeds: Dict[str, Any],
                            *wrt) -> Dict[str, np.ndarray]:
        """Analytic gradients of the summed loss wrt named variables
        (reference `sd.calculateGradients`) — the OpValidation hook."""
        wrt = [w.name if isinstance(w, SDVariable) else w for w in wrt] \
            or list(self.variables_)
        feeds = {k: jnp.asarray(v) for k, v in feeds.items()}

        def loss_fn(vs):
            return self._total_loss(vs, feeds)
        grads = jax.grad(loss_fn)(self.variables_)
        return {w: np.asarray(grads[w]) for w in wrt}

    # ---- serialization (FlatBuffers replacement) ----
    def save(self, path: str, save_updater_state: bool = True):
        graph = {
            "format": "deeplearning4j_tpu.samediff.v1",
            "nodes": [dataclasses.asdict(n) for n in self._nodes.values()],
            "loss_variables": self._loss_names,
            "iteration": self.iteration, "epoch": self.epoch,
            "training_config": (self.training_config.to_json()
                                if self.training_config else None),
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("graph.json", json.dumps(graph, default=_json_default))
            np_vars = {k: np.asarray(v) for k, v in self.variables_.items()}
            z.writestr("variables.npz", _npz_bytes(np_vars))
            np_consts = {k: np.asarray(v) for k, v in self._constants.items()}
            z.writestr("constants.npz", _npz_bytes(np_consts))
            if save_updater_state and self.opt_state_ is not None:
                leaves = jax.tree_util.tree_leaves(self.opt_state_)
                z.writestr("updater.npz", _npz_bytes(
                    {str(i): np.asarray(l) for i, l in enumerate(leaves)}))

    @staticmethod
    def load(path: str, load_updater_state: bool = True) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path, "r") as z:
            graph = json.loads(z.read("graph.json").decode())
            variables = _npz_load(z.read("variables.npz"))
            constants = _npz_load(z.read("constants.npz"))
            for nd in graph["nodes"]:
                node = Node(name=nd["name"], kind=nd["kind"], op=nd["op"],
                            inputs=tuple(nd["inputs"]),
                            attrs=_detuple_attrs(nd["attrs"]),
                            shape=None if nd["shape"] is None
                            else tuple(nd["shape"]),
                            dtype=nd["dtype"])
                sd._nodes[node.name] = node
            sd.variables_ = {k: jnp.asarray(v) for k, v in variables.items()}
            sd._constants = {k: jnp.asarray(v) for k, v in constants.items()}
            sd._loss_names = graph["loss_variables"]
            sd.iteration = graph["iteration"]
            sd.epoch = graph["epoch"]
            if graph["training_config"]:
                sd.training_config = TrainingConfig.from_json(
                    graph["training_config"])
            if load_updater_state and "updater.npz" in z.namelist() \
                    and sd.training_config is not None:
                tmpl = sd.training_config.updater.init_state(sd.variables_)
                leaves, treedef = jax.tree_util.tree_flatten(tmpl)
                saved = _npz_load(z.read("updater.npz"))
                new_leaves = [jnp.asarray(saved[str(i)])
                              for i in range(len(leaves))]
                sd.opt_state_ = jax.tree_util.tree_unflatten(treedef,
                                                             new_leaves)
        return sd

    def summary(self) -> str:
        lines = [f"{'name':30s} {'kind':12s} {'op':24s} inputs"]
        for n in self._nodes.values():
            lines.append(f"{n.name:30s} {n.kind:12s} {n.op or '-':24s} "
                         f"{list(n.inputs)}")
        return "\n".join(lines)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.ndarray, jnp.ndarray)):
        return np.asarray(o).tolist()
    if isinstance(o, tuple):
        return list(o)
    raise TypeError(f"not json-serializable: {type(o)}")


def _detuple_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """JSON turns tuples into lists; ops that need tuples re-tuple them."""
    out = {}
    for k, v in attrs.items():
        out[k] = tuple(v) if isinstance(v, list) and k in (
            "stride", "kernel", "dilation", "perm") else v
    return out


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_load(data: bytes) -> Dict[str, np.ndarray]:
    import io
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}
