"""AOT-cached jitted step functions for the training hot loops.

`jax.jit` keeps its executable cache in-process: a preempted
FaultTolerantTrainer restart, an elastic re-launch, or plain `python
train.py` again re-traces and re-compiles the donated train step from
scratch — routinely the longest stall in a restart.  `step_function()`
wraps a step body so that first-call compilation goes through a
`PersistentExecutableCache`: the lowered program is compiled once per
(model fingerprint, argument signature) *ever* and deserialized on every
later process start.

Dispatch cost: the wrapper keys its in-memory table on the argument
signature.  Hashing the full argument pytree every step would walk
hundreds of parameter leaves, so callers split the signature —
`dynamic_argnums` names the arguments whose shapes/dtypes can change
between calls (the data batch, masks); everything else (params, state,
opt state, rng, counters) is hashed once on first call and assumed
stable, which holds because every step-shape-changing event in this
codebase (set_normalizer, zero1 toggles, graph mutation) rebuilds the
step function anyway.  A signature the table has never seen falls through
to the same lower→compile→persist path, exactly like `jax.jit` retracing.

When no cache is configured the wrapper *is* `jax.jit` (same object,
zero overhead), so the persistent layer stays strictly opt-in.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from deeplearning4j_tpu.compile.fingerprint import (args_signature,
                                                    signature_json)
from deeplearning4j_tpu.compile.persistent import PersistentExecutableCache


class AotStepFunction:
    """Callable wrapping `jax.jit(body, donate_argnums=...)` with a
    persistent executable tier.  Exposes `_cache_size()` (count of actual
    trace+compile events, NOT disk hits) so monitor's compile detection
    keeps reporting real compiles."""

    def __init__(self, body: Callable, *, donate_argnums: Tuple[int, ...],
                 key_base: Callable[[], Dict[str, Any]],
                 cache: PersistentExecutableCache,
                 dynamic_argnums: Sequence[int] = ()):
        import jax
        self._jit = jax.jit(body, donate_argnums=tuple(donate_argnums))
        self._cache = cache
        self._key_base = key_base
        self._dynamic = tuple(dynamic_argnums)
        self._static_sig = None          # signature of the stable args
        self._table: Dict[Any, Any] = {}  # full sig -> executable
        self._n_compiles = 0
        self._donate = tuple(donate_argnums)

    def _split_sig(self, args) -> Tuple[Any, Any]:
        dyn = tuple(args[i] for i in self._dynamic if i < len(args))
        dyn_sig = args_signature(dyn)
        if self._static_sig is None:
            static = tuple(a for i, a in enumerate(args)
                           if i not in self._dynamic)
            self._static_sig = args_signature(static)
        return self._static_sig, dyn_sig

    def __call__(self, *args):
        static_sig, dyn_sig = self._split_sig(args)
        sig = (static_sig, dyn_sig)
        fn = self._table.get(sig)
        if fn is None:
            parts = dict(self._key_base())
            parts["donate_argnums"] = list(self._donate)
            parts["dynamic_argnums"] = list(self._dynamic)
            parts["static_args"] = signature_json(static_sig)
            parts["dynamic_args"] = signature_json(dyn_sig)
            fn, source = self._cache.get_or_compile(
                parts, lambda: self._jit.lower(*args).compile())
            if source == "compiled":
                self._n_compiles += 1
            self._table[sig] = fn
        return fn(*args)

    def _cache_size(self) -> int:
        """Actual compile events (monitor.check_compile contract); a disk
        hit deserializes without compiling and does not count."""
        return self._n_compiles

    @property
    def executables(self) -> Dict[Any, Any]:
        return self._table


def step_function(body: Callable, *, donate_argnums: Tuple[int, ...] = (),
                  key_base: Optional[Callable[[], Dict[str, Any]]] = None,
                  cache: Optional[PersistentExecutableCache] = None,
                  dynamic_argnums: Sequence[int] = ()):
    """The step-builder entry point: returns plain `jax.jit(body, ...)`
    when no persistent cache is in play, otherwise an `AotStepFunction`
    bridging compilation through the cache.  `key_base` is a zero-arg
    callable (evaluated lazily, at first dispatch) producing the model/
    config fingerprint parts of the disk key."""
    import jax
    if cache is None or key_base is None:
        return jax.jit(body, donate_argnums=tuple(donate_argnums))
    return AotStepFunction(body, donate_argnums=tuple(donate_argnums),
                           key_base=key_base, cache=cache,
                           dynamic_argnums=dynamic_argnums)
