"""Persistent compile layer + schedule autotuner (ROADMAP item 2).

Two ideas from TVM (PAPERS.md, arXiv 1802.04799), applied to this
framework's own config space:

* `persistent` — compiled executables as the persisted, shippable unit:
  an on-disk `PersistentExecutableCache` keyed by (environment, topology,
  model fingerprint, argument signature) with crc-checked atomic writes,
  so new processes (serving scale-out replicas, preempted-trainer
  restarts, bench runs) deserialize instead of recompiling.
* `autotune` — learned schedule search over {fused_steps, prefetch depth,
  zero1, donation, bucket ladder}, persisted as a JSON artifact next to
  the executable store and re-applied at build time via
  `load_schedule()`.

Opt-in: nothing persists unless a cache directory is configured — pass
`cache=`/`cache_dir=` explicitly, call `set_default_cache(dir)`, or set
`$DL4J_TPU_EXEC_CACHE`.
"""
from deeplearning4j_tpu.compile.autotune import (  # noqa: F401
    DEFAULT_SPACE, Schedule, ScheduleAutotuner, TileAutotuner,
    autotune_tiles, load_schedule, load_tile_table, save_schedule,
    save_tile_entry, schedule_path, tile_table_path)
from deeplearning4j_tpu.compile.fingerprint import (  # noqa: F401
    environment_fingerprint, kernel_tier_fingerprint, mesh_fingerprint,
    model_fingerprint, transform_fingerprint)
from deeplearning4j_tpu.compile.persistent import (  # noqa: F401
    PersistentExecutableCache, as_cache, default_cache, default_cache_dir,
    enable_jax_compilation_cache, set_default_cache)
from deeplearning4j_tpu.compile.step_cache import (  # noqa: F401
    AotStepFunction, step_function)
