"""Schedule autotuner: learned search over the execution-config space.

TVM's lesson (PAPERS.md, arXiv 1802.04799) applied to the knobs this
framework already exposes but makes users hand-tune: `fused_steps` (scan
block size), device prefetch depth, ZeRO-1 optimizer sharding on/off,
buffer donation, and the serving bucket ladder.  The autotuner measures
real steps/sec per candidate through a caller-supplied measure function
(bench.py provides one), searches with a coarse grid over the
highest-impact dimensions followed by greedy per-dimension refinement,
and persists the winner as a JSON artifact next to the executable store
— `load_schedule()` re-applies it at build time in any later process, so
a tuned config survives restarts the same way the compiled executables
do.

    sch = ScheduleAutotuner(measure).search()
    save_schedule(sch, cache_dir, model=net)
    ...                                   # any later process:
    sch = load_schedule(cache_dir, model=net)
    if sch: sch.apply(net)                # or ParallelWrapper / ModelServer
"""
from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.compile.fingerprint import (environment_fingerprint,
                                                    model_fingerprint)

SCHEDULE_FORMAT = "deeplearning4j_tpu.schedule.v1"


@dataclasses.dataclass
class Schedule:
    """One point in the execution-config space.

    Training knobs: `fused_steps` (k steps per compiled scan dispatch),
    `prefetch_depth` (device-staging depth for DevicePrefetchIterator),
    `zero1` (ZeRO-1 sharded weight update), `donation` (donate
    params/state/opt buffers to the step).  Serving knobs: `min_bucket` /
    `buckets` (the compile-cache bucket ladder).  `steps_per_sec` records
    the winning measurement for regression checks on re-apply."""

    fused_steps: int = 1
    prefetch_depth: int = 2
    zero1: bool = False
    donation: bool = True
    min_bucket: Optional[int] = None
    buckets: Optional[List[int]] = None
    steps_per_sec: Optional[float] = None
    source: str = "default"          # default | autotuned | loaded
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # ---- serialization ----
    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Schedule":
        known = {f.name for f in dataclasses.fields(Schedule)}
        return Schedule(**{k: v for k, v in d.items() if k in known})

    def config_key(self) -> tuple:
        """Identity of the *configuration* (measurement metadata excluded)
        — the autotuner's dedup key."""
        return (self.fused_steps, self.prefetch_depth, self.zero1,
                self.donation, self.min_bucket,
                tuple(self.buckets) if self.buckets else None)

    # ---- application hooks ----
    def apply(self, target) -> Any:
        """Apply this schedule to a build-time target, duck-typed:

        * MultiLayerNetwork / ComputationGraph / SameDiff — installs the
          schedule (iterator `fit` defaults to `fused_steps`, the step
          builders honor `donation`).
        * ParallelWrapper — toggles ZeRO-1 and applies to the wrapped
          model.
        * ModelServer / BucketedCompileCache — reconfigures the bucket
          ladder (prefer passing `schedule=` at construction).
        * ModelFleet — installs this as the fleet default schedule,
          applied to every replica on warm-pool admission (per-model
          schedules from `schedules_dir` still win).

        Returns `target` for chaining."""
        if hasattr(target, "set_default_schedule"):    # ModelFleet
            return target.set_default_schedule(self)
        if hasattr(target, "apply_schedule"):          # models + wrapper
            return target.apply_schedule(self)
        if hasattr(target, "cache") and hasattr(target.cache, "set_buckets"):
            if self.buckets or self.min_bucket:        # ModelServer
                target.cache.set_buckets(buckets=self.buckets,
                                         min_bucket=self.min_bucket)
            return target
        if hasattr(target, "set_buckets"):             # BucketedCompileCache
            if self.buckets or self.min_bucket:
                target.set_buckets(buckets=self.buckets,
                                   min_bucket=self.min_bucket)
            return target
        raise TypeError(
            f"don't know how to apply a Schedule to {type(target).__name__}")

    def wrap_iterator(self, iterator, **kwargs):
        """Stage `iterator` through a DevicePrefetchIterator at this
        schedule's prefetch depth (the input-pipeline application hook)."""
        from deeplearning4j_tpu.data.pipeline import DevicePrefetchIterator
        return DevicePrefetchIterator(iterator,
                                      depth=max(1, self.prefetch_depth),
                                      **kwargs)


# Coarse-grid dimensions first: block size and optimizer sharding dominate
# steps/sec; prefetch/donation/buckets are refined greedily from the grid
# winner.
DEFAULT_SPACE: Dict[str, List[Any]] = {
    "fused_steps": [1, 2, 4, 8, 16],
    "zero1": [False, True],
    "prefetch_depth": [1, 2, 4],
    "donation": [True, False],
}
GRID_DIMS = ("fused_steps", "zero1")


class ScheduleAutotuner:
    """Grid + greedy-refinement search over `Schedule` space.

    `measure(schedule) -> steps/sec` (higher is better) is the only
    contract; bench.py's `measure_training` builds one from a model
    factory, tests rig one analytically.  Measurements are memoized per
    config, every evaluation lands in `history`, and the returned
    schedule carries its winning steps/sec + search metadata."""

    def __init__(self, measure: Callable[[Schedule], float],
                 space: Optional[Dict[str, List[Any]]] = None,
                 base: Optional[Schedule] = None,
                 refine_rounds: int = 2,
                 on_candidate: Optional[Callable[[Schedule, float], None]]
                 = None):
        self.measure = measure
        self.space = dict(space if space is not None else DEFAULT_SPACE)
        self.base = base if base is not None else Schedule()
        self.refine_rounds = int(refine_rounds)
        self.on_candidate = on_candidate
        self.history: List[Dict[str, Any]] = []
        self._memo: Dict[tuple, float] = {}

    def _eval(self, cand: Schedule) -> float:
        key = cand.config_key()
        if key in self._memo:
            return self._memo[key]
        sps = float(self.measure(cand))
        self._memo[key] = sps
        self.history.append(dict(cand.to_json(), steps_per_sec=sps))
        if self.on_candidate is not None:
            self.on_candidate(cand, sps)
        return sps

    def search(self) -> Schedule:
        t0 = time.perf_counter()
        best = self.base
        best_sps = self._eval(best)

        # stage 1 — coarse grid over the dominant dimensions
        grid_dims = [d for d in GRID_DIMS if d in self.space]
        def grid(cands, dim_i):
            if dim_i == len(grid_dims):
                yield cands
                return
            for v in self.space[grid_dims[dim_i]]:
                yield from grid(dict(cands, **{grid_dims[dim_i]: v}),
                                dim_i + 1)
        for combo in grid({}, 0):
            cand = dataclasses.replace(best, **combo)
            sps = self._eval(cand)
            if sps > best_sps:
                best, best_sps = cand, sps

        # stage 2 — greedy per-dimension refinement from the grid winner
        for _ in range(self.refine_rounds):
            improved = False
            for dim, values in self.space.items():
                for v in values:
                    cand = dataclasses.replace(best, **{dim: v})
                    sps = self._eval(cand)
                    if sps > best_sps:
                        best, best_sps = cand, sps
                        improved = True
            if not improved:
                break

        return dataclasses.replace(
            best, steps_per_sec=best_sps, source="autotuned",
            meta={"evaluated": len(self._memo),
                  "search_wall_s": round(time.perf_counter() - t0, 3),
                  "baseline_steps_per_sec": self.history[0]["steps_per_sec"],
                  "env": environment_fingerprint()})


# ---------------------------------------------------------------------------
# Persistence (JSON artifact next to the executable store)
# ---------------------------------------------------------------------------

def _schedule_name(name: Optional[str], model) -> str:
    if name is not None:
        return name
    if model is not None:
        return model_fingerprint(model)[:16]
    return "default"


def schedule_path(directory: str, name: Optional[str] = None,
                  model=None) -> str:
    return os.path.join(os.path.expanduser(directory),
                        f"schedule-{_schedule_name(name, model)}.json")


def save_schedule(schedule: Schedule, directory: str,
                  name: Optional[str] = None, model=None) -> str:
    """Atomically persist `schedule` as
    `<directory>/schedule-<name|model-fingerprint>.json`; returns the
    path.  Same tmp+rename commit discipline as the executable entries."""
    directory = os.path.expanduser(directory)
    os.makedirs(directory, exist_ok=True)
    path = schedule_path(directory, name, model)
    doc = {"format": SCHEDULE_FORMAT,
           "schedule": schedule.to_json(),
           "env": environment_fingerprint(),
           "written_at": time.time()}
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-schedule-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_schedule(directory: str, name: Optional[str] = None,
                  model=None) -> Optional[Schedule]:
    """The persisted schedule for (directory, name-or-model), or None when
    absent/unreadable/wrong format.  Loaded schedules are marked
    `source="loaded"`; the recorded `steps_per_sec` rides along so callers
    can regression-check a re-application against the tuning measurement."""
    path = schedule_path(directory, name, model)
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != SCHEDULE_FORMAT:
            return None
        sch = Schedule.from_json(doc["schedule"])
    except (OSError, ValueError, KeyError, TypeError):
        return None
    sch.source = "loaded"
    sch.meta = dict(sch.meta, loaded_from=path)
    return sch


# ---------------------------------------------------------------------------
# Tile-size search for the fused-kernel tier (ops/pallas)
# ---------------------------------------------------------------------------
#
# Same shape as the schedule search one level down: a coarse grid over the
# dominant tile dimensions, greedy per-dimension refinement, memoized
# measurements — but the search space is a kernel's TileConfig and the
# persisted artifact is a per-device-kind tile table
# (`tiles-<device_kind>.json`) keyed by `<kernel>/<shape_class>`, living
# next to the schedule store.  Winners are installed into
# `ops.pallas.dispatch`, which folds them into `kernel_tier_fingerprint`
# so a tile change can never collide with a stale AOT executable.

from deeplearning4j_tpu.ops.pallas.tiles import (  # noqa: E402
    DEFAULT_TILES, TILE_FORMAT, TILE_GRID_DIMS, TILE_SPACES, TileConfig,
    iter_space)


class TileAutotuner:
    """Grid + greedy-refinement search over one kernel's TileConfig space.

    `measure(tile) -> rate` (higher is better — steps/sec, GFLOP/s,
    1/latency; any consistent unit).  Measurements are memoized per
    config; every evaluation lands in `history`; `search()` returns the
    winning TileConfig and records `best_rate` / `evaluated` on self."""

    def __init__(self, measure: Callable[[TileConfig], float],
                 kernel: str,
                 space: Optional[Dict[str, List[int]]] = None,
                 base: Optional[TileConfig] = None,
                 refine_rounds: int = 2,
                 on_candidate: Optional[Callable[[TileConfig, float], None]]
                 = None):
        self.measure = measure
        self.kernel = kernel
        self.space = dict(space if space is not None
                          else TILE_SPACES.get(kernel, {}))
        self.base = base if base is not None else DEFAULT_TILES.get(
            kernel, TileConfig())
        self.refine_rounds = int(refine_rounds)
        self.on_candidate = on_candidate
        self.history: List[Dict[str, Any]] = []
        self._memo: Dict[str, float] = {}
        self.best_rate: Optional[float] = None
        self.evaluated: int = 0

    def _eval(self, cand: TileConfig) -> float:
        key = cand.config_key()
        if key in self._memo:
            return self._memo[key]
        rate = float(self.measure(cand))
        self._memo[key] = rate
        self.history.append(dict(cand.to_json(), rate=rate))
        if self.on_candidate is not None:
            self.on_candidate(cand, rate)
        return rate

    def search(self) -> TileConfig:
        best = self.base
        best_rate = self._eval(best)

        grid_dims = [d for d in TILE_GRID_DIMS.get(self.kernel, ())
                     if d in self.space] or sorted(self.space)[:2]
        for combo in iter_space({d: self.space[d] for d in grid_dims}):
            cand = best.replace(**combo)
            rate = self._eval(cand)
            if rate > best_rate:
                best, best_rate = cand, rate

        for _ in range(self.refine_rounds):
            improved = False
            for dim in sorted(self.space):
                for v in self.space[dim]:
                    cand = best.replace(**{dim: v})
                    rate = self._eval(cand)
                    if rate > best_rate:
                        best, best_rate = cand, rate
                        improved = True
            if not improved:
                break

        self.best_rate = best_rate
        self.evaluated = len(self._memo)
        return best


def _device_kind_slug(device_kind: Optional[str] = None) -> str:
    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    return "".join(c if c.isalnum() else "-" for c in str(device_kind).lower())


def tile_table_path(directory: str,
                    device_kind: Optional[str] = None) -> str:
    return os.path.join(os.path.expanduser(directory),
                        f"tiles-{_device_kind_slug(device_kind)}.json")


def _load_tile_doc(directory: str,
                   device_kind: Optional[str] = None) -> Dict[str, Any]:
    try:
        with open(tile_table_path(directory, device_kind)) as f:
            doc = json.load(f)
        if doc.get("format") != TILE_FORMAT:
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}
    except (OSError, ValueError):
        return {}


def load_tile_table(directory: str, device_kind: Optional[str] = None
                    ) -> Dict[str, TileConfig]:
    """The persisted tile table as `{<kernel>/<shape_class>: TileConfig}`,
    or `{}` when absent/unreadable/wrong format — ready for
    `ops.pallas.dispatch.install_tile_table`."""
    out: Dict[str, TileConfig] = {}
    for key, entry in _load_tile_doc(directory, device_kind).items():
        try:
            out[key] = TileConfig.from_json(entry["tile"])
        except (KeyError, TypeError, ValueError):
            continue
    return out


def save_tile_entry(directory: str, kernel: str, shape_class: str,
                    tile: TileConfig, rate: Optional[float] = None,
                    meta: Optional[Dict[str, Any]] = None,
                    device_kind: Optional[str] = None) -> str:
    """Read-modify-write one `<kernel>/<shape_class>` entry into the
    per-device tile table, with the same tmp+rename commit discipline as
    the schedule artifact.  Returns the table path."""
    directory = os.path.expanduser(directory)
    os.makedirs(directory, exist_ok=True)
    path = tile_table_path(directory, device_kind)
    entries = _load_tile_doc(directory, device_kind)
    entries[f"{kernel}/{shape_class}"] = {
        "tile": tile.to_json(),
        "rate": rate,
        "meta": dict(meta or {}),
        "written_at": time.time(),
    }
    doc = {"format": TILE_FORMAT,
           "device_kind": _device_kind_slug(device_kind),
           "entries": entries,
           "env": environment_fingerprint()}
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-tiles-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def autotune_tiles(kernel: str, shape_class: str,
                   measure: Callable[[TileConfig], float],
                   directory: str,
                   space: Optional[Dict[str, List[int]]] = None,
                   base: Optional[TileConfig] = None,
                   refine_rounds: int = 2,
                   install: bool = True,
                   device_kind: Optional[str] = None
                   ) -> "tuple[TileConfig, Dict[str, Any]]":
    """Memoized tile search: serve `<kernel>/<shape_class>` from the
    persisted per-device tile table when present (zero re-search, counted
    as `autotune_tile_cache_hits_total`), otherwise run the grid+greedy
    `TileAutotuner`, persist the winner, and (by default) install it into
    `ops.pallas.dispatch` so subsequent dispatches — and AOT fingerprints
    — pick it up.  Returns `(tile, info)`."""
    from deeplearning4j_tpu.monitor.instrument import ops_instruments
    from deeplearning4j_tpu.ops.pallas import dispatch as _kd

    key = f"{kernel}/{shape_class}"
    entry = _load_tile_doc(directory, device_kind).get(key)
    if entry is not None:
        try:
            tile = TileConfig.from_json(entry["tile"])
        except (KeyError, TypeError, ValueError):
            tile = None
        if tile is not None:
            ops_instruments().record_tile_cache_hit()
            if install:
                _kd.set_tile(kernel, tile, shape_class)
            return tile, {"source": "cache", "evaluated": 0,
                          "rate": entry.get("rate"),
                          "path": tile_table_path(directory, device_kind)}

    t0 = time.perf_counter()
    tuner = TileAutotuner(measure, kernel, space=space, base=base,
                          refine_rounds=refine_rounds)
    tile = tuner.search()
    search_ms = (time.perf_counter() - t0) * 1000.0
    ops_instruments().record_tile_search_ms(search_ms)
    path = save_tile_entry(directory, kernel, shape_class, tile,
                           rate=tuner.best_rate,
                           meta={"evaluated": tuner.evaluated,
                                 "search_ms": round(search_ms, 3)},
                           device_kind=device_kind)
    if install:
        _kd.set_tile(kernel, tile, shape_class)
    return tile, {"source": "searched", "evaluated": tuner.evaluated,
                  "rate": tuner.best_rate,
                  "search_ms": search_ms, "path": path}
