"""Cache-key fingerprints for persisted compiled executables.

A serialized executable is only reusable when *everything* that shaped the
compiled program matches: the jax/jaxlib/XLA version that produced it, the
backend topology it was compiled for (platform, device kind and count,
mesh axes), the model program (config + parameter tree structure, shapes,
dtypes, plus any constants baked into the trace — on-device normalizer
stats, ZeRO-1 layout plans), and the concrete argument signature.  Each of
those becomes a component of one canonical-JSON key whose sha256 names the
on-disk entry (`PersistentExecutableCache`), so a version bump or topology
change *changes the key* — stale executables are unreachable rather than
detected after the fact.
"""
from __future__ import annotations

import hashlib
import json
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def canonical_json(obj: Any) -> str:
    """Deterministic JSON: sorted keys, no whitespace drift."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)


def digest(parts: Any) -> str:
    """sha256 hex of the canonical JSON of `parts` — the cache key."""
    return hashlib.sha256(canonical_json(parts).encode()).hexdigest()


_env_fp: Optional[Dict[str, Any]] = None


def environment_fingerprint() -> Dict[str, Any]:
    """Process-wide compile-environment identity: jax/jaxlib versions and
    the default backend's platform/device population.  Cached after first
    call (none of it changes within a process)."""
    global _env_fp
    if _env_fp is None:
        import jax
        try:
            import jaxlib
            jaxlib_ver = getattr(jaxlib, "__version__", "?")
        except Exception:       # pragma: no cover - jaxlib always present
            jaxlib_ver = "?"
        devs = jax.devices()
        _env_fp = {
            "jax": jax.__version__,
            "jaxlib": jaxlib_ver,
            "platform": devs[0].platform if devs else "none",
            "device_kind": devs[0].device_kind if devs else "none",
            "device_count": len(devs),
            "process_count": jax.process_count(),
        }
    return _env_fp


def _reset_environment_fingerprint() -> None:
    """Test hook: drop the cached fingerprint (e.g. after monkeypatching)."""
    global _env_fp
    _env_fp = None


def mesh_fingerprint(mesh) -> Optional[Dict[str, Any]]:
    """Topology identity of a `jax.sharding.Mesh` (None passes through):
    axis names/sizes plus the flat device-id order — two meshes with the
    same shape over *differently ordered* devices compile to different
    collectives."""
    if mesh is None:
        return None
    return {
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
        "device_ids": [int(d.id) for d in mesh.devices.flat],
    }


def tree_spec(tree: Any) -> List[Tuple[str, Tuple[int, ...], str]]:
    """(path, shape, dtype) for every leaf — the structural identity of a
    params/state pytree (values are runtime arguments, NOT part of the
    compiled program, so they stay out of the key)."""
    import jax
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out.append((jax.tree_util.keystr(path),
                    tuple(int(s) for s in np.shape(leaf)),
                    str(getattr(leaf, "dtype", type(leaf).__name__))))
    return out


def _closure_arrays(fn, depth: int = 0) -> List[np.ndarray]:
    """Arrays captured (possibly transitively) by a closure — how a
    DeviceNormalizer carries its fitted stats into the traced step."""
    out: List[np.ndarray] = []
    if depth > 4:
        return out
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:          # pragma: no cover - empty cell
            continue
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            out.append(np.asarray(v))
        elif callable(v):
            out.extend(_closure_arrays(v, depth + 1))
    return out


def _device_norm_fingerprint(dn) -> Optional[Dict[str, Any]]:
    """An attached DeviceNormalizer's stats are *baked into the executable
    as constants*, so the key must hash their values, not just shapes.
    The stats live in the apply closures; if none can be extracted the
    fingerprint degrades to a process-unique nonce — the disk cache then
    always misses for this model, which is slow but can never serve an
    executable with the wrong constants baked in."""
    if dn is None:
        return None
    if isinstance(dn, dict):        # ComputationGraph: input name -> norm
        if not dn:
            return None
        return {k: _device_norm_fingerprint(v)
                for k, v in sorted(dn.items())}
    arrays: List[np.ndarray] = []
    for fn in (getattr(dn, "_features", None), getattr(dn, "_labels", None)):
        if fn is not None:
            arrays.extend(_closure_arrays(fn))
    if not arrays:
        return {"kind": type(dn).__name__, "opaque_nonce": id(dn)}
    crcs = sorted(
        (str(a.dtype), list(a.shape),
         zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF)
        for a in arrays)
    return {"kind": type(dn).__name__, "stats": crcs}


def transform_fingerprint(zt) -> Optional[Dict[str, Any]]:
    """Identity of a ZeRO-1 step transform: the mesh topology plus every
    leaf's placement plan (kind/shape/pad/specs) — the plans decide which
    collectives the compiled step contains."""
    if zt is None:
        return None
    import jax
    plans = []
    for path, pl in jax.tree_util.tree_flatten_with_path(
            zt.plans, is_leaf=lambda x: hasattr(x, "store"))[0]:
        plans.append([jax.tree_util.keystr(path), pl.kind,
                      list(pl.shape), int(pl.pad),
                      str(pl.store), str(pl.update), str(pl.compute)])
    return {"axis": zt.axis, "mesh": mesh_fingerprint(zt.mesh),
            "plans": plans}


def model_fingerprint(model) -> str:
    """Stable identity of the *program* a model's forward/step traces to:
    configuration JSON (layers, updater, dtypes, regularization, remat),
    parameter/state tree structure+shapes+dtypes, baked-in normalizer
    stats, and the model class.  Two models with identical architecture
    but different weights share a fingerprint — weights are runtime
    arguments, so one cached executable serves both (that is what makes a
    version roll of retrained weights come up warm)."""
    parts: Dict[str, Any] = {"class": type(model).__name__}
    conf = getattr(model, "conf", None)
    if conf is not None and hasattr(conf, "to_json"):
        # the seed only picks initial weight values — runtime data, not
        # part of the traced program — so it must not split the key
        cd = json.loads(conf.to_json())
        cd.pop("seed", None)
        parts["conf"] = canonical_json(cd)
    elif hasattr(model, "_nodes"):     # SameDiff: the graph IS the config
        import dataclasses
        parts["nodes"] = [canonical_json(dataclasses.asdict(n))
                          for n in model._nodes.values()]
        tc = getattr(model, "training_config", None)
        parts["training_config"] = tc.to_json() if tc is not None else None
        parts["loss_variables"] = sorted(getattr(model, "_loss_names", []))
    params = getattr(model, "params_", None)
    if params is None:
        params = getattr(model, "variables_", None)
    parts["params"] = tree_spec(params)
    parts["state"] = tree_spec(getattr(model, "state_", None))
    parts["device_norm"] = _device_norm_fingerprint(
        getattr(model, "_device_norm", None))
    # a QuantizedModel folds its quant config + calibration-stat crc32s
    # into the key: an int8 program and its f32 base (or two quantizations
    # from different calibration data) must never collide on one
    # persisted executable
    qfp = getattr(model, "quant_fingerprint", None)
    if callable(qfp):
        parts["quant"] = qfp()
    # the fused-kernel tier changes the traced program: reference vs
    # Pallas lowering, and any installed TileConfig, must never share a
    # persisted executable with each other or with a stale tile choice
    parts["kernel_tier"] = kernel_tier_fingerprint()
    return digest(parts)


def kernel_tier_fingerprint() -> Dict[str, Any]:
    """The fused-kernel tier's contribution to program identity: dispatch
    mode, Pallas availability, and every installed TileConfig (see
    `ops.pallas.dispatch`).  Falls back to a reference-only stanza when
    the tier cannot import, so fingerprinting never depends on Pallas."""
    try:
        from deeplearning4j_tpu.ops.pallas import dispatch as _kd
        return _kd.kernel_tier_fingerprint()
    except Exception:
        return {"mode": "reference", "pallas": False, "tiles": {},
                "kv_dtype": "f32"}


def args_signature(args: Any) -> Tuple:
    """Hashable in-process signature of a call's argument pytree: tree
    structure + per-leaf (shape, dtype, weak_type).  Drives the in-memory
    executable dispatch table; `signature_json` renders the same content
    deterministically for the on-disk key."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(
        (tuple(int(s) for s in np.shape(l)),
         str(getattr(l, "dtype", type(l).__name__)),
         bool(getattr(l, "weak_type", False)))
        for l in leaves))


def signature_json(sig: Tuple) -> Dict[str, Any]:
    """Disk-key form of an `args_signature` tuple."""
    treedef, leaves = sig
    return {"tree": str(treedef),
            "leaves": [[list(s), d, w] for s, d, w in leaves]}
