"""Persistent on-disk cache of serialized compiled executables.

The TVM serving model (PAPERS.md, arXiv 1802.04799): the *compiled
artifact* is the persisted, shippable unit.  Every new process — a fresh
serving replica scaling out, a preempted FaultTolerantTrainer restarting,
a bench run — otherwise re-traces and re-compiles every executable from
scratch; with this cache the second process deserializes the bytes the
first one paid XLA to produce, so warm-pool scale-out and auto-resume
skip the multi-second compile stall entirely.

Entry format (one file per executable, `<sha256-key>.jexe`):

    DL4JXC1\n                       magic + format version
    {json header}\n                 crc32 of payload, byte count, the full
                                    key parts (env fingerprint included)
    <pickle payload>                (serialized bytes, in_tree, out_tree)
                                    from jax.experimental.serialize_executable

Writes are atomic in the style of `parallel/checkpoint.py`: tmp file +
`os.replace`, so a torn write never commits; loads verify the crc32 and
that the header's key parts match the request (a renamed/garbled entry is
treated as a miss and overwritten, never served).  Version/topology
invalidation is structural: the jax/jaxlib version, backend platform,
device population and mesh topology are hashed *into the key*, so a stale
executable is unreachable rather than detected late.

When a backend cannot serialize executables (`serialize` raises), the
cache degrades to the process-wide JAX compilation cache directory
(`jax_compilation_cache_dir` under `<dir>/xla-fallback`) — cold starts
then still skip XLA's optimization passes even though tracing re-runs.
"""
from __future__ import annotations

import json
import os
import pickle
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from deeplearning4j_tpu.compile.fingerprint import (canonical_json, digest,
                                                    environment_fingerprint)

MAGIC = b"DL4JXC1\n"
ENTRY_SUFFIX = ".jexe"

_ENV_DIR_VAR = "DL4J_TPU_EXEC_CACHE"


def _summarize(parts: Any, limit: int = 2000) -> Any:
    """Header-embedded copy of the key parts, with long string components
    truncated to their sha256 so the header stays a few KB even for huge
    config JSONs (the sha256 key is the authoritative identity; the header
    copy is for verification and debuggability)."""
    if isinstance(parts, dict):
        return {k: _summarize(v, limit) for k, v in parts.items()}
    if isinstance(parts, (list, tuple)):
        return [_summarize(v, limit) for v in parts]
    if isinstance(parts, str) and len(parts) > limit:
        return {"sha256": digest(parts), "len": len(parts)}
    return parts


class PersistentExecutableCache:
    """On-disk store of serialized compiled executables.

    `get_or_compile(parts, compile_fn)` is the whole API surface hot paths
    need: look the key up on disk, deserialize on a hit, otherwise call
    `compile_fn()` (which must return a `jax.stages.Compiled`) and persist
    the result.  All failure modes — corrupt bytes, version mismatch,
    unserializable backend — degrade to compiling, never to serving a
    wrong executable.
    """

    def __init__(self, directory: str,
                 env: Optional[Dict[str, Any]] = None,
                 fallback_compilation_cache: bool = True):
        self.directory = os.path.expanduser(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._env = env
        self._fallback = fallback_compilation_cache
        self._serialize_ok: Optional[bool] = None   # None = not yet probed
        self._lock = threading.Lock()
        from deeplearning4j_tpu.monitor.instrument import aot_instruments
        self._instr = aot_instruments()
        # per-instance tallies (registry counters are process-global; tests
        # and bench read these to assert on ONE cache's behaviour)
        self.stats: Dict[str, int] = {
            "disk_hits": 0, "disk_misses": 0, "compiles": 0, "stores": 0,
            "errors": 0, "bytes_read": 0, "bytes_written": 0}

    # ---- keying ----
    def environment(self) -> Dict[str, Any]:
        return self._env if self._env is not None \
            else environment_fingerprint()

    def _key_parts(self, parts: Dict[str, Any]) -> Dict[str, Any]:
        return {"env": self.environment(), "parts": parts}

    def key_for(self, parts: Dict[str, Any]) -> str:
        return digest(self._key_parts(parts))

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key + ENTRY_SUFFIX)

    # ---- load ----
    def load(self, parts: Dict[str, Any]):
        """The deserialized executable for `parts`, or None (miss).  Any
        defect — missing file, torn/corrupt bytes, header/key mismatch,
        deserialization failure — is a miss."""
        keyed = self._key_parts(parts)
        key = digest(keyed)
        path = self._path(key)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._record("disk_misses")
            self._instr.misses.inc()
            return None
        try:
            if not blob.startswith(MAGIC):
                raise ValueError("bad magic (not a cache entry / truncated)")
            head_end = blob.index(b"\n", len(MAGIC)) + 1
            header = json.loads(blob[len(MAGIC):head_end])
            payload = blob[head_end:]
            if len(payload) != int(header["payload_bytes"]):
                raise ValueError("payload length mismatch (torn write)")
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            if crc != int(header["crc32"]):
                raise ValueError(
                    f"crc mismatch: header {int(header['crc32']):#010x} vs "
                    f"payload {crc:#010x} (bytes corrupted after commit)")
            # header carries the (summarized) key parts: a collision or a
            # renamed entry must never deserialize as the wrong program
            if header.get("key") != key or \
                    header.get("parts") != _summarize(keyed):
                raise ValueError("header key/parts mismatch — entry does "
                                 "not belong to this request")
            serialized, in_tree, out_tree = pickle.loads(payload)
            from jax.experimental import serialize_executable as se
            fn = se.deserialize_and_load(serialized, in_tree, out_tree)
        except Exception as e:
            self._record("errors")
            self._record("disk_misses")
            self._instr.errors.inc()
            self._instr.misses.inc()
            self._instr.note_error(path, e)
            return None
        self._record("disk_hits")
        self._record("bytes_read", len(blob))
        self._instr.hits.inc()
        self._instr.bytes_read.inc(len(blob))
        self._instr.load_ms.observe((time.perf_counter() - t0) * 1000.0)
        return fn

    # ---- store ----
    def store(self, parts: Dict[str, Any], compiled) -> bool:
        """Serialize `compiled` and commit it atomically under the key for
        `parts`.  Returns False (and enables the XLA compilation-cache
        fallback tier once) when the backend cannot serialize."""
        if self._serialize_ok is False:
            return False
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as se
            serialized, in_tree, out_tree = se.serialize(compiled)
            payload = pickle.dumps((serialized, in_tree, out_tree),
                                   protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            # backend can't serialize executables: degrade to the
            # process-wide XLA compilation cache (tier 2)
            self._serialize_ok = False
            self._record("errors")
            self._instr.errors.inc()
            self._instr.note_error("serialize", e)
            if self._fallback:
                self.enable_fallback_tier()
            return False
        self._serialize_ok = True
        keyed = self._key_parts(parts)
        key = digest(keyed)
        header = canonical_json({
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
            "payload_bytes": len(payload),
            "key": key,
            "parts": _summarize(keyed),
            "written_at": time.time(),
        }).encode()
        blob = MAGIC + header + b"\n" + payload
        path = self._path(key)
        try:
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       prefix=".tmp-" + key[:8])
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)       # atomic commit
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError as e:
            self._record("errors")
            self._instr.errors.inc()
            self._instr.note_error(path, e)
            return False
        self._record("stores")
        self._record("bytes_written", len(blob))
        self._instr.stores.inc()
        self._instr.bytes_written.inc(len(blob))
        self._instr.store_ms.observe((time.perf_counter() - t0) * 1000.0)
        return True

    # ---- the one-call surface ----
    def get_or_compile(self, parts: Dict[str, Any],
                       compile_fn: Callable[[], Any]
                       ) -> Tuple[Any, str]:
        """(executable, source): source is "disk" for a deserialized hit,
        "compiled" for a fresh compile (persisted when possible)."""
        fn = self.load(parts)
        if fn is not None:
            return fn, "disk"
        compiled = compile_fn()
        self._record("compiles")
        self._instr.compiles.inc()
        self.store(parts, compiled)
        return compiled, "compiled"

    # ---- tier 2: process-wide XLA compilation cache ----
    def enable_fallback_tier(self) -> None:
        """Point jax's own persistent compilation cache at a sibling
        directory, once per process.  Executable *deserialization* beats
        it (no tracing at all), but on backends without serialization this
        still skips the XLA optimization passes across processes."""
        enable_jax_compilation_cache(
            os.path.join(self.directory, "xla-fallback"))

    # ---- maintenance ----
    def entries(self) -> Dict[str, Dict[str, Any]]:
        """key -> header for every committed entry (debug/tooling)."""
        out = {}
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(ENTRY_SUFFIX):
                continue
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    blob = f.read(65536)
                head_end = blob.index(b"\n", len(MAGIC)) + 1
                out[name[:-len(ENTRY_SUFFIX)]] = json.loads(
                    blob[len(MAGIC):head_end])
            except Exception:
                out[name[:-len(ENTRY_SUFFIX)]] = {"error": "unreadable"}
        return out

    def clear(self) -> int:
        """Remove every committed entry; returns the count removed."""
        n = 0
        for name in os.listdir(self.directory):
            if name.endswith(ENTRY_SUFFIX) or name.startswith(".tmp-"):
                try:
                    os.remove(os.path.join(self.directory, name))
                    n += 1
                except OSError:
                    pass
        return n

    def _record(self, stat: str, n: int = 1) -> None:
        with self._lock:
            self.stats[stat] += n


_jax_cc_enabled: Optional[str] = None


def enable_jax_compilation_cache(directory: str) -> None:
    """Enable jax's persistent compilation cache at `directory` (idempotent;
    first directory wins for the process — jax's cache dir is global)."""
    global _jax_cc_enabled
    if _jax_cc_enabled is not None:
        return
    import jax
    directory = os.path.expanduser(directory)
    os.makedirs(directory, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", directory)
        # cache even sub-second compiles: the point is cross-process reuse
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:       # pragma: no cover - knob name drift
            pass
        _jax_cc_enabled = directory
    except Exception:           # pragma: no cover - very old jax
        _jax_cc_enabled = ""


# ---------------------------------------------------------------------------
# Process default (opt-in via env var or set_default_cache)
# ---------------------------------------------------------------------------

_default_cache: Optional[PersistentExecutableCache] = None
_default_resolved = False


def default_cache_dir() -> Optional[str]:
    """The opt-in default directory: $DL4J_TPU_EXEC_CACHE, or None (the
    persistent layer is explicit-opt-in so tests/benches that count
    compiles see pristine behaviour unless they ask for the cache)."""
    d = os.environ.get(_ENV_DIR_VAR)
    return os.path.expanduser(d) if d else None


def default_cache() -> Optional[PersistentExecutableCache]:
    """Process-wide cache instance, created lazily from
    $DL4J_TPU_EXEC_CACHE (None when unset and never `set_default_cache`d)."""
    global _default_cache, _default_resolved
    if not _default_resolved:
        d = default_cache_dir()
        _default_cache = PersistentExecutableCache(d) if d else None
        _default_resolved = True
    return _default_cache


def set_default_cache(cache) -> Optional[PersistentExecutableCache]:
    """Install a process-wide default (a PersistentExecutableCache, a
    directory path, or None to disable).  Returns the installed cache."""
    global _default_cache, _default_resolved
    if isinstance(cache, str):
        cache = PersistentExecutableCache(cache)
    _default_cache = cache
    _default_resolved = True
    return _default_cache


def as_cache(cache) -> Optional[PersistentExecutableCache]:
    """Coerce a user-supplied `cache=` argument: a directory string becomes
    a PersistentExecutableCache, None falls through to the process default."""
    if cache is None:
        return default_cache()
    if isinstance(cache, str):
        return PersistentExecutableCache(cache)
    return cache
