"""Ring attention — sequence/context parallelism over the device mesh.

The reference has NO long-context story (SURVEY.md §5.7: attention exists
only as single-device ops; sequences are truncated).  This is the
capability-exceeding TPU-native addition: shard the sequence axis over mesh
axis `seq`; each step computes blockwise attention against the local KV
shard, then rotates KV around the ring with `ppermute` over ICI while the
online-softmax stats (acc, m, l) accumulate.  Communication overlaps the
next chunk's compute under XLA's scheduler.  (Liu et al. 2023 "Ring
Attention with Blockwise Transformers" — see PAPERS.md.)

Use inside shard_map:

    mesh = make_mesh({"data": 2, "seq": 4})
    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=P("data", None, "seq", None),
        out_specs=P("data", None, "seq", None))
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, mask=None):
    """[B, H, T_local, D] per device; returns the local output shard.

    Causal masking uses global positions: device i holds sequence chunk i
    (contiguous layout).  Per ring step the KV chunk's source device index
    is tracked so query/key global offsets stay correct.  ``mask``:
    optional [B, T_local] 1/0 keep-mask over the local KV chunk — it
    rotates around the ring with its K/V chunk, giving padded long-
    context batches the same semantics as `fused_attention`'s mask.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, T, D = q.shape
    qs = q * scale

    def chunk_scores(kc, mc, src):
        # f32 scores/stats regardless of input dtype — same accumulation
        # invariant as ops/attention_kernels.py (bf16 normalizer drift
        # grows with ring length, exactly where this path is used)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kc,
                       preferred_element_type=jnp.float32)
        if mc is not None:
            s = jnp.where(mc[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qpos = my * T + jnp.arange(T)[:, None]
            kpos = src * T + jnp.arange(kc.shape[2])[None, :]
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        return s

    def accumulate(acc, m, l, kc, vc, mc, src):
        s = chunk_scores(kc, mc, src)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1)
        acc_new = corr[..., None] * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    def step(i, carry):
        acc, m, l, kc, vc, mc = carry
        # rotate KV (+ its mask chunk) around the ring (ICI neighbour
        # exchange), then consume
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if mc is not None:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        acc, m, l = accumulate(acc, m, l, kc, vc, mc, (my - i) % n)
        return acc, m, l, kc, vc, mc

    # derive from q so the carries inherit shard_map's varying-axis type,
    # then promote to f32 accumulation
    acc = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full_like(q[..., 0], NEG_INF, dtype=jnp.float32)
    l = jnp.zeros_like(q[..., 0], dtype=jnp.float32)
    # step 0: local chunk, no communication; n-1 rotations total
    acc, m, l = accumulate(acc, m, l, k, v, mask, my)
    if mask is None:
        def step_unmasked(i, carry):
            acc_, m_, l_, kc, vc, _ = step(i, carry + (None,))
            return acc_, m_, l_, kc, vc

        acc, m, l, _, _ = jax.lax.fori_loop(
            1, n, step_unmasked, (acc, m, l, k, v))
    else:
        acc, m, l, _, _, _ = jax.lax.fori_loop(
            1, n, step, (acc, m, l, k, v, mask))
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention_flash(q, k, v, axis_name: str, causal: bool = False,
                         scale=None, block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         interpret: bool = False):
    """Ring attention whose INNER chunk-vs-chunk attention runs the
    Pallas flash kernel (`ops.attention_kernels.flash_attention_tpu`
    with ``return_lse``), merging per-chunk results by logsumexp:

        lse' = logaddexp(lse, lse_i)
        out' = exp(lse - lse')*out + exp(lse_i - lse')*out_i

    Causal needs NO per-step kernel variants with the contiguous chunk
    layout: at ring step i the incoming chunk (source device
    ``src = (my - i) mod n``) lies entirely BELOW the diagonal when
    ``src < my`` (keep everything) or entirely ABOVE it (``src > my``:
    suppress by forcing that chunk's lse to -inf so the merge no-ops);
    only step 0 — the diagonal chunk, whose global q/k offsets are equal
    — runs the causal kernel.  So every step launches the same plain
    kernel and the diagonal step launches the causal one once.

    Differentiable via custom_vjp: the backward delegates to the einsum
    ring's autodiff (mathematically the same function, so the gradients
    are exact); a fused flash-bwd ring is a future multi-chip-measured
    step.  Single-chip A/B is vacuous (axis size 1 = plain flash), so
    adoption into dispatch waits for multi-chip hardware; correctness is
    CPU-tested via interpret mode.

    ``block_q``/``block_k`` default to the kernel tier's installed
    attention :class:`TileConfig` (autotuned winners apply here too),
    clamped to divisors of the local chunk length via ``_pick_block``.
    """
    if block_q is None or block_k is None:
        from deeplearning4j_tpu.ops import pallas as _tier
        import deeplearning4j_tpu.ops.attention_kernels as _ak
        T = q.shape[2]
        tile = _tier.dispatch.get_tile("attention")
        if block_q is None:
            block_q = _ak._pick_block(T, min(tile.block_q, T)) or T
        if block_k is None:
            block_k = _ak._pick_block(T, min(tile.block_kv, T)) or T
    return _ring_flash(q, k, v, axis_name, causal, scale, block_q,
                       block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, scale, block_q, block_k,
                interpret):
    from deeplearning4j_tpu.ops.attention_kernels import (
        flash_attention_tpu)

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, T, D = q.shape

    def inner(kc, vc, diag):
        out, lse = flash_attention_tpu(
            q, kc, vc, causal=bool(causal and diag), scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            return_lse=True)
        return out.astype(jnp.float32), lse.reshape(B, H, T)

    def merge(out, lse, out_i, lse_i):
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_i - lse_new)[..., None]
        return w_old * out + w_new * out_i, lse_new

    def step(i, carry):
        out, lse, kc, vc = carry
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        out_i, lse_i = inner(kc, vc, diag=False)
        if causal:
            src = (my - i) % n
            lse_i = jnp.where(src < my, lse_i, NEG_INF)
        out, lse = merge(out, lse, out_i, lse_i)
        return out, lse, kc, vc

    out, lse = inner(k, v, diag=True)
    out, lse, _, _ = jax.lax.fori_loop(1, n, step, (out, lse, k, v))
    return out.astype(q.dtype)


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, block_q, block_k,
                    interpret):
    out = _ring_flash(q, k, v, axis_name, causal, scale, block_q,
                      block_k, interpret)
    return out, (q, k, v)


def _ring_flash_bwd(axis_name, causal, scale, block_q, block_k,
                    interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: ring_attention(q_, k_, v_,
                                          axis_name=axis_name,
                                          causal=causal, scale=scale),
        q, k, v)
    return vjp(g)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)
