"""ParallelWrapper / ParallelInference — data-parallel fit and serving.

Reference: `deeplearning4j-parallel-wrapper/.../parallelism/
{ParallelWrapper,ParallelInference,trainer/DefaultTrainer}.java`: per-device
trainer THREADS holding model replicas, synced by parameter averaging every
`averagingFrequency` batches or by async threshold-compressed gradient
sharing (`EncodedGradientsAccumulator`).

TPU-native inversion (SURVEY.md §3.4 note): no replicas, no threads, no
gossip.  The ONE compiled train step runs SPMD — the batch is sharded over
the mesh's `data` axis, params are replicated (or model-sharded, see
sharding.py), and XLA emits the gradient all-reduce over ICI.  Both
reference sync modes (averaging, gradient sharing) are semantically
*synchronous every-step gradient all-reduce* here; the semantic change from
async-compressed-delta is deliberate and documented (BASELINE north star).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sharding import ShardingRules, shard_model_params


def _shard_batch(x, mesh: Mesh, axis: str):
    """Place a host batch with its leading dim split over the data axis.
    Batch size must divide by the axis size (the reference likewise requires
    workers | batch, `ParallelWrapper.splitter`)."""
    def place(leaf):
        leaf = jnp.asarray(leaf)
        n = mesh.shape[axis]
        if leaf.shape[0] % n:
            raise ValueError(
                f"Batch size {leaf.shape[0]} not divisible by data-parallel "
                f"degree {n}")
        spec = P(*([axis] + [None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(place, x)


class ParallelWrapper:
    """Data-parallel trainer wrapping a MultiLayerNetwork or
    ComputationGraph.  API parity with the reference builder:

        pw = (ParallelWrapper.builder(net)
              .workers(8)                      # default: all devices
              .build())
        pw.fit(iterator, epochs=2)

    `prefetch_buffer`, `averaging_frequency` and `training_mode` are accepted
    for config parity; averaging/gradient-sharing both run as per-step
    all-reduce (see module docstring), prefetch is the data layer's job.
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 data_axis: str = "data",
                 sharding_rules: Optional[ShardingRules] = None,
                 training_mode: str = "SHARED_GRADIENTS"):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.training_mode = training_mode
        self._rules = sharding_rules
        self._placed = False

    # ---- builder (reference ParallelWrapper.Builder) ----
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers: Optional[int] = None
            self._mesh: Optional[Mesh] = None
            self._mode = "SHARED_GRADIENTS"
            self._rules: Optional[ShardingRules] = None

        def workers(self, n: int):
            self._workers = int(n); return self

        def mesh(self, m: Mesh):
            self._mesh = m; return self

        def training_mode(self, mode: str):
            # AVERAGING | SHARED_GRADIENTS | CUSTOM — all sync all-reduce
            self._mode = mode; return self

        def sharding_rules(self, r: ShardingRules):
            self._rules = r; return self

        def averaging_frequency(self, n: int):
            return self  # parity no-op: sync all-reduce has no averaging lag

        def prefetch_buffer(self, n: int):
            return self  # parity no-op: see data.AsyncDataSetIterator

        def build(self) -> "ParallelWrapper":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                if self._workers is not None:
                    devs = devs[: self._workers]
                mesh = make_mesh({"data": len(devs)}, devs)
            return ParallelWrapper(self._model, mesh,
                                   sharding_rules=self._rules,
                                   training_mode=self._mode)

    @staticmethod
    def builder(model) -> "ParallelWrapper.Builder":
        return ParallelWrapper.Builder(model)

    # ---- placement ----
    def _place_model(self):
        """Replicate (or TP-shard) params/state/opt-state over the mesh once;
        the jitted step keeps shardings on its outputs thereafter."""
        if self._placed:
            return
        m = self.model
        if self._rules is not None:
            m.params_ = shard_model_params(m.params_, self.mesh, self._rules)
        else:
            repl = NamedSharding(self.mesh, P())
            m.params_ = jax.device_put(m.params_, repl)
        repl = NamedSharding(self.mesh, P())
        m.state_ = jax.device_put(m.state_, repl)
        m.opt_state_ = jax.device_put(m.opt_state_, repl)
        self._placed = True

    # ---- training ----
    def fit(self, data, labels=None, *, epochs: int = 1):
        """fit(x, y) or fit(iterator, epochs=N): the model's own compiled
        step, run SPMD with the batch sharded over the data axis."""
        self._place_model()
        m = self.model
        if labels is not None:
            x = _shard_batch(data, self.mesh, self.data_axis)
            y = _shard_batch(labels, self.mesh, self.data_axis)
            with self.mesh:
                m.fit(x, y)
            return self
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                x = _shard_batch(ds.features, self.mesh, self.data_axis)
                y = _shard_batch(ds.labels, self.mesh, self.data_axis)
                with self.mesh:
                    m.fit(x, y)
            m.epoch += 1
        return self

    def average_updaters(self):
        return self  # parity no-op: single logical updater state

    def shutdown(self):
        return self  # parity no-op: no trainer threads to stop


class ParallelInference:
    """Replicated/sharded batched inference (reference `ParallelInference`:
    round-robin model replicas + dynamic batching threads).

    TPU-native: ONE jitted forward with the batch sharded over the data
    axis; "dynamic batching" survives as optional host-side batch
    aggregation (`output` on a list concatenates, pads to the DP degree,
    splits results back)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 data_axis: str = "data"):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        repl = NamedSharding(self.mesh, P())
        model.params_ = jax.device_put(model.params_, repl)
        model.state_ = jax.device_put(model.state_, repl)

    def output(self, x) -> np.ndarray:
        """Single-request or list-of-requests inference."""
        if isinstance(x, (list, tuple)):
            return self._output_batched(list(x))
        return np.asarray(self._run(np.asarray(x)))

    def _run(self, x: np.ndarray):
        n = self.mesh.shape[self.data_axis]
        pad = (-x.shape[0]) % n
        padded = np.concatenate([x, np.repeat(x[-1:], pad, 0)]) if pad else x
        xs = _shard_batch(padded, self.mesh, self.data_axis)
        with self.mesh:
            out = self.model.output(xs)
        if isinstance(out, (list, tuple)):   # ComputationGraph
            out = out[0]
        return out[: x.shape[0]]

    def _output_batched(self, requests: List[np.ndarray]) -> List[np.ndarray]:
        sizes = [r.shape[0] for r in requests]
        merged = np.concatenate(requests, axis=0)
        out = np.asarray(self._run(merged))
        res, off = [], 0
        for s in sizes:
            res.append(out[off: off + s])
            off += s
        return res
