"""ParallelWrapper / ParallelInference — data-parallel fit and serving.

Reference: `deeplearning4j-parallel-wrapper/.../parallelism/
{ParallelWrapper,ParallelInference,trainer/DefaultTrainer}.java`: per-device
trainer THREADS holding model replicas, synced by parameter averaging every
`averagingFrequency` batches or by async threshold-compressed gradient
sharing (`EncodedGradientsAccumulator`).

TPU-native inversion (SURVEY.md §3.4 note): no replicas, no threads, no
gossip.  The ONE compiled train step runs SPMD — the batch is sharded over
the mesh's `data` axis, params are replicated (or model-sharded, see
sharding.py), and XLA emits the gradient all-reduce over ICI.  Both
reference sync modes (averaging, gradient sharing) are semantically
*synchronous every-step gradient all-reduce* here; the semantic change from
async-compressed-delta is deliberate and documented (BASELINE north star).
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.monitor.instrument import ParallelInstruments
from deeplearning4j_tpu.parallel import zero
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sharding import ShardingRules, shard_model_params
from deeplearning4j_tpu.train.updaters import tree_map_like_params


def _shard_batch(x, mesh: Mesh, axis: str, batch_dim: int = 0):
    """Place a host batch with its batch dim split over the data axis.
    Batch size must divide by the axis size (the reference likewise requires
    workers | batch, `ParallelWrapper.splitter`).  `batch_dim=1` handles
    stacked `[k, batch, ...]` fit_steps blocks (steps axis leads)."""
    def place(leaf):
        leaf = jnp.asarray(leaf)
        n = mesh.shape[axis]
        if leaf.shape[batch_dim] % n:
            raise ValueError(
                f"Batch size {leaf.shape[batch_dim]} not divisible by "
                f"data-parallel degree {n}")
        spec = P(*([None] * batch_dim + [axis]
                   + [None] * (leaf.ndim - batch_dim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(place, x)


def _shard_opt_state_like(opt_state, params, mesh: Mesh):
    """Place optimizer state so param-shaped moments (Adam m/v, momentum
    buffers, ...) inherit each param's sharding; anything else (step counts,
    scalars, empty states) replicates.  Handles both layouts in the tree:
    `{layer: {"m": layer_params, ...}}` (MultiLayerNetwork/ComputationGraph
    per-layer updaters) and `{"m": params, "v": params}` (flat updaters) via
    the shared structural matcher (`train.updaters.tree_map_like_params`)."""
    repl = NamedSharding(mesh, P())
    return tree_map_like_params(
        lambda sub, psub: jax.tree_util.tree_map(
            lambda s, p: jax.device_put(s, p.sharding), sub, psub),
        opt_state, params,
        lambda sub: jax.device_put(sub, repl))


def _pad_tail(a, pad: int, mode: str) -> np.ndarray:
    """Append `pad` rows: repeats of the last row (features/labels — keeps
    shapes/dtypes and any categorical structure valid) or zeros (masks —
    padded rows contribute nothing to the masked loss mean)."""
    a = np.asarray(a)
    tail = (np.repeat(a[-1:], pad, axis=0) if mode == "repeat"
            else np.zeros((pad,) + a.shape[1:], a.dtype))
    return np.concatenate([a, tail], axis=0)


def _pad_partial_lists(feats, labels, lmasks, pad: int):
    """Pad a partial batch up to a DP-divisible size such that the step is
    EXACT: features/labels repeat their last row, label masks get zero rows
    (losses reduce as sum(per*mask)/max(sum(mask),1), so zero-mask rows
    change neither the loss nor any gradient).  Labels without a mask get a
    synthesized `[ones(b); zeros(pad)]` vector mask when they are 2-D (the
    shape every loss reduction accepts); for higher-rank unmasked labels
    there is no universally-correct mask shape — returns None and the
    caller drops the remainder with a one-time warning.  Caveat: repeated
    feature rows still flow through the forward pass, so BatchNorm batch
    statistics see them (running stats are perturbed by at most pad/batch;
    the loss/grads are not)."""
    new_lms = []
    for i, l in enumerate(labels):
        m = lmasks[i] if lmasks is not None else None
        if m is not None:
            new_lms.append(_pad_tail(m, pad, "zero"))
        elif np.ndim(l) == 2:
            b = int(np.shape(l)[0])
            new_lms.append(np.concatenate(
                [np.ones(b, np.float32), np.zeros(pad, np.float32)]))
        else:
            return None
    feats = [_pad_tail(f, pad, "repeat") for f in feats]
    labels = [_pad_tail(l, pad, "repeat") for l in labels]
    return feats, labels, new_lms


class ParallelWrapper:
    """Data-parallel trainer wrapping a MultiLayerNetwork or
    ComputationGraph.  API parity with the reference builder:

        pw = (ParallelWrapper.builder(net)
              .workers(8)                      # default: all devices
              .build())
        pw.fit(iterator, epochs=2)

    `prefetch_buffer`, `averaging_frequency` and `training_mode` are accepted
    for config parity; averaging/gradient-sharing both run as per-step
    all-reduce (see module docstring), prefetch is the data layer's job.
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 data_axis: str = "data",
                 sharding_rules: Optional[ShardingRules] = None,
                 training_mode: str = "SHARED_GRADIENTS",
                 optimizer_sharding: bool = False,
                 gradient_sharing=None):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.training_mode = training_mode
        self._rules = sharding_rules
        self._zero1 = bool(optimizer_sharding)
        self._sharing_cfg = gradient_sharing  # HierarchicalGradientSharing
        self._placed = False
        self._warned_drop = False
        self._instr: Optional[ParallelInstruments] = None
        self._schedule = None          # compile.Schedule (apply_schedule)

    def _instruments(self) -> ParallelInstruments:
        if self._instr is None:
            self._instr = ParallelInstruments()
        return self._instr

    # ---- builder (reference ParallelWrapper.Builder) ----
    class Builder:
        def __init__(self, model):
            self._model = model
            self._workers: Optional[int] = None
            self._mesh: Optional[Mesh] = None
            self._mode = "SHARED_GRADIENTS"
            self._rules: Optional[ShardingRules] = None
            self._zero1 = False
            self._sharing = None

        def workers(self, n: int):
            self._workers = int(n); return self

        def mesh(self, m: Mesh):
            self._mesh = m; return self

        def training_mode(self, mode: str):
            # AVERAGING | SHARED_GRADIENTS | CUSTOM — all sync all-reduce
            self._mode = mode; return self

        def sharding_rules(self, r: ShardingRules):
            self._rules = r; return self

        def optimizer_sharding(self, on: bool = True):
            """ZeRO-1 sharded weight update (arXiv:2004.13336): moments and
            the weight update sharded over the data axis — reduce-scatter
            grads, per-shard optimizer step, all-gather params.  Same math
            as the replicated update, ~N× less optimizer-state HBM."""
            self._zero1 = bool(on); return self

        def gradient_sharing(self, cfg=True):
            """Hierarchical compressed cross-host gradient all-reduce (the
            Aeron GradientSharing role at DCN scale): full-precision ICI
            all-reduce inside the compiled step, threshold-compressed
            TCP exchange of the ICI-reduced gradient across hosts
            (parallel.hierarchical).  Pass a `HierarchicalGradientSharing`
            config, True for env-resolved defaults, or None/False to keep
            the single-mesh path."""
            from deeplearning4j_tpu.parallel.hierarchical import (
                HierarchicalGradientSharing)
            if cfg is True:
                cfg = HierarchicalGradientSharing()
            elif cfg is False:
                cfg = None
            self._sharing = cfg; return self

        def averaging_frequency(self, n: int):
            return self  # parity no-op: sync all-reduce has no averaging lag

        def prefetch_buffer(self, n: int):
            return self  # parity no-op: see data.AsyncDataSetIterator

        def build(self) -> "ParallelWrapper":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                if self._workers is not None:
                    devs = devs[: self._workers]
                mesh = make_mesh({"data": len(devs)}, devs)
            return ParallelWrapper(self._model, mesh,
                                   sharding_rules=self._rules,
                                   training_mode=self._mode,
                                   optimizer_sharding=self._zero1,
                                   gradient_sharing=self._sharing)

    @staticmethod
    def builder(model) -> "ParallelWrapper.Builder":
        return ParallelWrapper.Builder(model)

    # ---- placement ----
    def optimizer_sharding(self, on: bool = True) -> "ParallelWrapper":
        """Toggle the ZeRO-1 sharded weight update (arXiv:2004.13336) at
        runtime; takes effect on the next fit call (the model is re-placed
        and its compiled step re-traced with the reduce-scatter/all-gather
        collectives baked in or removed)."""
        on = bool(on)
        if on == self._zero1:
            return self
        self._zero1 = on
        if not on:
            zero.disable_zero1(self.model)
        self._placed = False
        return self

    def gradient_sharing(self, cfg) -> "ParallelWrapper":
        """Runtime toggle for hierarchical compressed gradient sharing:
        a `HierarchicalGradientSharing` config (or True for env-resolved
        defaults) installs the split-step exchange on the wrapped model;
        None/False removes it.  Takes effect on the next fit call."""
        from deeplearning4j_tpu.parallel.hierarchical import (
            HierarchicalGradientSharing)
        if cfg is True:
            cfg = HierarchicalGradientSharing()
        elif cfg is False:
            cfg = None
        self._sharing_cfg = cfg
        if self._placed:
            self.model.set_gradient_sharing(cfg)
        return self

    def apply_schedule(self, schedule) -> "ParallelWrapper":
        """Apply an autotuned `compile.Schedule` at the wrapper level:
        `zero1` toggles the sharded weight update here, the rest
        (fused_steps default, donation) installs on the wrapped model via
        its own `apply_schedule`.  `fit_prefetched` then defaults its
        `fused_steps`/`prefetch_depth` from the installed schedule."""
        self.optimizer_sharding(schedule.zero1)
        if hasattr(self.model, "apply_schedule"):
            self.model.apply_schedule(schedule)
        self._schedule = schedule
        return self

    def _place_model(self):
        """Replicate (or TP-shard) params/state/opt-state over the mesh once;
        the jitted step keeps shardings on its outputs thereafter.  Optimizer
        moments are param-shaped, so they FOLLOW the param sharding — a
        TP-sharded layer keeps its Adam m/v sharded too (no HBM waste, no
        per-step reshard).  With `optimizer_sharding(True)` the moments (and
        the weight update itself) are additionally sharded over the data
        axis (parallel.zero); TP rules still win per-leaf."""
        if self._placed:
            return
        m = self.model
        if self._zero1:
            zero.enable_zero1(m, self.mesh, axis=self.data_axis,
                              rules=self._rules)
        else:
            zero.disable_zero1(m)
            if self._rules is not None:
                m.params_ = shard_model_params(m.params_, self.mesh,
                                               self._rules)
            else:
                m.params_ = jax.device_put(m.params_,
                                           NamedSharding(self.mesh, P()))
            m.state_ = jax.device_put(m.state_, NamedSharding(self.mesh, P()))
            if m.opt_state_ is not None:
                m.opt_state_ = _shard_opt_state_like(m.opt_state_, m.params_,
                                                     self.mesh)
        if self._sharing_cfg is not None:
            m.set_gradient_sharing(self._sharing_cfg)
        elif getattr(m, "_grad_sharing", None) is not None:
            m.set_gradient_sharing(None)
        self._placed = True
        ins = self._instruments()
        ins.replicas.set(self.mesh.shape[self.data_axis])
        if m.opt_state_ is not None:
            ins.record_opt_state_bytes(
                zero.opt_state_bytes_per_replica(m.opt_state_), self._zero1)

    # ---- training ----
    def _warn_drop(self, b: int, n: int):
        if not self._warned_drop:
            warnings.warn(
                f"dropping final partial batch of {b} rows: not divisible "
                f"by the data-parallel degree {n} and the labels take no "
                "mask (rank > 2 without an explicit labels_mask), so "
                "mask-padding cannot express it exactly; pass a labels "
                "mask or size batches to a multiple of the mesh",
                stacklevel=3)
            self._warned_drop = True

    def _fit_ds(self, ds):
        """Shard one DataSet/MultiDataSet (features, labels, masks) over the
        data axis and run the model's compiled step.  A final partial batch
        (batch % DP degree != 0) is padded with repeated rows + a zero
        labels-mask — exact under the masked loss mean (`_pad_partial_lists`)
        — or dropped with a one-time warning when no mask can express it."""
        m = self.model
        n = self.mesh.shape[self.data_axis]

        def shard(t):
            return None if t is None else _shard_batch(t, self.mesh,
                                                       self.data_axis)

        if hasattr(ds, "features_masks"):          # MultiDataSet (CG path)
            if ds.features_masks is not None and any(
                    mk is not None for mk in ds.features_masks):
                raise NotImplementedError(
                    "ComputationGraph training does not consume feature "
                    "masks (same as its compiled step); drop them or mask "
                    "inside the input pipeline")
            feats, labels = list(ds.features), list(ds.labels)
            lms = list(ds.labels_masks) if ds.labels_masks is not None \
                else None
            b = int(np.shape(feats[0])[0])
            pad = (-b) % n
            if pad:
                padded = _pad_partial_lists(feats, labels, lms, pad)
                if padded is None:
                    self._warn_drop(b, n)
                    return
                feats, labels, lms = padded
            x = [shard(f) for f in feats]
            y = [shard(l) for l in labels]
            lm = [shard(mk) for mk in lms] if lms is not None else None
            t0 = time.perf_counter()
            with self.mesh:
                m._fit_batch(m._as_input_dict(x), y, lm)
            self._instruments().record_dispatch(time.perf_counter() - t0)
        else:
            fm = getattr(ds, "features_mask", None)
            lm_host = getattr(ds, "labels_mask", None)
            feats, labels = ds.features, ds.labels
            b = int(np.shape(feats)[0])
            pad = (-b) % n
            if pad:
                padded = _pad_partial_lists(
                    [feats], [labels],
                    None if lm_host is None else [lm_host], pad)
                if padded is None:
                    self._warn_drop(b, n)
                    return
                (feats,), (labels,), (lm_host,) = padded
                if fm is not None:
                    fm = _pad_tail(fm, pad, "repeat")
            lm = shard(lm_host)
            t0 = time.perf_counter()
            with self.mesh:
                if hasattr(m, "_as_input_dict"):   # CG fed single-input DS
                    if fm is not None:
                        raise NotImplementedError(
                            "ComputationGraph training does not consume "
                            "feature masks")
                    m._fit_batch(m._as_input_dict(shard(feats)),
                                 m._as_list(shard(labels)),
                                 None if lm is None else [lm])
                else:
                    m.fit(shard(feats), shard(labels),
                          features_mask=shard(fm), labels_mask=lm)
            self._instruments().record_dispatch(time.perf_counter() - t0)

    def fit(self, data, labels=None, *, epochs: int = 1):
        """fit(x, y), fit(DataSet/MultiDataSet), or fit(iterator, epochs=N):
        the model's own compiled step, run SPMD with every batch array
        (multi-input features, labels, masks) sharded over the data axis."""
        self._place_model()
        m = self.model
        if labels is not None:
            x = _shard_batch(data, self.mesh, self.data_axis)
            y = _shard_batch(labels, self.mesh, self.data_axis)
            with self.mesh:
                for _ in range(epochs):
                    m.fit(x, y)
                    if epochs > 1:
                        m.epoch += 1
            return self
        if hasattr(data, "features"):              # bare DataSet/MultiDataSet
            for _ in range(epochs):
                self._fit_ds(data)
                m.epoch += 1
            return self
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                self._fit_ds(ds)
            m.epoch += 1
        return self

    def sharded_placement(self, batch_dim: int = 0):
        """Placement callable for `data.pipeline.DevicePrefetchIterator`:
        stages each array split over the mesh's data axis, so prefetched
        batches land pre-sharded and the SPMD step consumes them with zero
        resharding."""
        return lambda leaf: _shard_batch(leaf, self.mesh, self.data_axis,
                                         batch_dim=batch_dim)

    def fit_prefetched(self, iterator, *, epochs: int = 1,
                       fused_steps: Optional[int] = None,
                       prefetch_depth: Optional[int] = None,
                       zero1: Optional[bool] = None):
        """Async end-to-end SPMD training from a host iterator: batches are
        ETL'd in a producer thread, staged onto the mesh pre-sharded
        (`sharded_placement`) `prefetch_depth` batches ahead, and consumed
        by the model's fused `fit_steps` scan — the SPMD composition of the
        pipeline's three latency hiders (prefetch, on-device normalize via
        `model.set_normalizer`, fused dispatch).  `zero1=True` turns on the
        sharded weight update for this and subsequent fits (see
        `optimizer_sharding`).  Unset, `fused_steps`/`prefetch_depth`
        default from the applied schedule (`apply_schedule`), else 1/2."""
        from deeplearning4j_tpu.data.pipeline import DevicePrefetchIterator
        sch = self._schedule
        if fused_steps is None:
            fused_steps = sch.fused_steps if sch is not None else 1
        if prefetch_depth is None:
            prefetch_depth = sch.prefetch_depth if sch is not None else 2
        if zero1 is not None:
            self.optimizer_sharding(zero1)
        self._place_model()
        pf = DevicePrefetchIterator(iterator, depth=prefetch_depth,
                                    placement=self.sharded_placement())
        try:
            with self.mesh:
                self.model.fit(pf, epochs=epochs, fused_steps=fused_steps)
        finally:
            pf.close()
        return self

    def fit_steps(self, xs, ys, *, zero1: Optional[bool] = None):
        """SPMD fused dispatch: a `[k, batch, ...]` block trains as k data-
        parallel steps in ONE compiled dispatch — the model's `fit_steps`
        scan with the batch axis (axis 1) sharded over the data axis.
        Composes the two latency hiders: per-step all-reduce stays inside
        the compiled scan, and the host dispatches once per k steps.
        `zero1=True` turns on the sharded weight update (the reduce-
        scatter/step/all-gather runs inside the scan body too)."""
        if zero1 is not None:
            self.optimizer_sharding(zero1)
        self._place_model()
        xs = _shard_batch(xs, self.mesh, self.data_axis, batch_dim=1)
        ys = _shard_batch(ys, self.mesh, self.data_axis, batch_dim=1)
        t0 = time.perf_counter()
        with self.mesh:
            out = self.model.fit_steps(xs, ys)
        self._instruments().record_dispatch(time.perf_counter() - t0)
        return out

    def measure_replica_skew(self) -> float:
        """Opt-in BLOCKING diagnostic: wait for each addressable shard of
        the latest step output (falling back to the first param leaf) and
        report max-min arrival spread in ms, also recorded in the
        `parallel_replica_skew_ms` gauge.  Every shard is polled on its OWN
        thread (all started before any wait completes), so a replica
        finishing while another is being waited on is no longer credited a
        near-zero wait — the sequential-poll under-reporting is gone.
        Remaining caveat: waits are host wall-clock from poll start, not
        device-side completion timestamps, so thread scheduling and the
        GIL add a noise floor (~0.1-1 ms on a busy host) — treat this as
        an imbalance smoke signal, not a profiler.  Never call it inside
        the hot loop: it closes the async-dispatch window the step loop
        works to keep open."""
        arr = getattr(self.model, "_score", None)
        if arr is None or not hasattr(arr, "addressable_shards"):
            leaves = jax.tree_util.tree_leaves(self.model.params_)
            arr = leaves[0] if leaves else None
        if arr is None or not hasattr(arr, "addressable_shards"):
            return 0.0
        shards = list(arr.addressable_shards)
        waits = [0.0] * len(shards)

        def poll(i, data):
            t0 = time.perf_counter()
            jax.block_until_ready(data)
            waits[i] = (time.perf_counter() - t0) * 1000.0

        threads = [threading.Thread(target=poll, args=(i, sh.data))
                   for i, sh in enumerate(shards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        skew = max(waits) - min(waits) if waits else 0.0
        self._instruments().replica_skew_ms.set(skew)
        return skew

    def fit_host_local(self, features, labels):
        """Multi-host fit: every process passes its *local* slice of the
        global batch; slices are assembled into one global sharded array
        (parallel.multihost.shard_host_local_batch) and the same SPMD step
        runs across all hosts — the SharedTraining data path."""
        from deeplearning4j_tpu.parallel.multihost import (
            shard_host_local_batch)
        self._place_model()
        x = shard_host_local_batch(self.mesh, features, self.data_axis)
        y = shard_host_local_batch(self.mesh, labels, self.data_axis)
        with self.mesh:
            self.model.fit(x, y)
        return self

    def fit_steps_host_local(self, xs, ys):
        """Multi-host fused dispatch: every process passes its local slice
        of a `[k, local_batch, ...]` block; the global `[k, batch, ...]`
        array trains as k steps in ONE dispatch per host (scan + per-step
        all-reduce inside the executable — the SharedTraining data path
        with the r5 host-latency lever)."""
        from deeplearning4j_tpu.parallel.multihost import (
            shard_host_local_batch)
        self._place_model()
        xs = shard_host_local_batch(self.mesh, xs, self.data_axis,
                                    batch_dim=1)
        ys = shard_host_local_batch(self.mesh, ys, self.data_axis,
                                    batch_dim=1)
        with self.mesh:
            return self.model.fit_steps(xs, ys)

    def average_updaters(self):
        return self  # parity no-op: single logical updater state

    def shutdown(self):
        return self  # parity no-op: no trainer threads to stop


class ParallelInference:
    """Replicated/sharded batched inference (reference `ParallelInference`:
    round-robin model replicas + dynamic batching threads).

    TPU-native: ONE jitted forward with the batch sharded over the data
    axis; "dynamic batching" survives as optional host-side batch
    aggregation (`output` on a list concatenates, pads to the DP degree,
    splits results back)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 data_axis: str = "data"):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        repl = NamedSharding(self.mesh, P())
        model.params_ = jax.device_put(model.params_, repl)
        model.state_ = jax.device_put(model.state_, repl)

    def output(self, x) -> np.ndarray:
        """Single-request or list-of-requests inference."""
        if isinstance(x, (list, tuple)):
            return self._output_batched(list(x))
        return np.asarray(self._run(np.asarray(x)))

    def _run(self, x: np.ndarray):
        n = self.mesh.shape[self.data_axis]
        if x.shape[0] == 0:
            # zero-row request: repeat-padding from x[-1:] has no row to
            # repeat, so pad with zeros up to one full DP round and slice
            # everything off (still yields the correct trailing dims)
            padded = np.zeros((n,) + x.shape[1:], x.dtype)
        else:
            pad = (-x.shape[0]) % n
            padded = np.concatenate([x, np.repeat(x[-1:], pad, 0)]) \
                if pad else x
        xs = _shard_batch(padded, self.mesh, self.data_axis)
        with self.mesh:
            out = self.model.output(xs)
        if isinstance(out, (list, tuple)):   # ComputationGraph
            out = out[0]
        return out[: x.shape[0]]

    def _output_batched(self, requests: List[np.ndarray]) -> List[np.ndarray]:
        if not requests:
            return []
        requests = [np.asarray(r) for r in requests]
        trailing = requests[0].shape[1:]
        for i, r in enumerate(requests[1:], 1):
            if r.shape[1:] != trailing:
                raise ValueError(
                    f"heterogeneous request shapes: request 0 has trailing "
                    f"dims {trailing} but request {i} has {r.shape[1:]}; "
                    "ParallelInference batches same-shape requests only — "
                    "serving.ModelServer routes mixed shapes to per-shape "
                    "buckets")
        sizes = [r.shape[0] for r in requests]
        merged = np.concatenate(requests, axis=0)
        out = np.asarray(self._run(merged))
        res, off = [], 0
        for s in sizes:
            res.append(out[off: off + s])
            off += s
        return res


class DynamicBatchingInference:
    """DEPRECATED — use `deeplearning4j_tpu.serving.ModelServer`, which
    adds shape buckets with an AOT compile cache, per-request deadlines,
    priority, bounded-queue load shedding and SLO metrics.

    Kept as a thin compatibility wrapper over the serving runtime's
    `ContinuousBatcher` (ONE batching implementation in the codebase):
    `submit(x)` returns a `concurrent.futures.Future`; `output(x)` is the
    blocking convenience form.  Requests are grouped by trailing dims, so
    mixed-shape traffic no longer crashes the concatenate."""

    def __init__(self, inference: "ParallelInference", max_batch: int = 32,
                 timeout_ms: float = 10.0):
        import warnings
        warnings.warn(
            "DynamicBatchingInference is deprecated; use "
            "deeplearning4j_tpu.serving.ModelServer (bucketed AOT compile "
            "cache, deadlines, backpressure, SLO metrics)",
            DeprecationWarning, stacklevel=2)
        # local import: serving composes on top of parallel, so the
        # top-level serving package must not be imported at wrapper
        # import time
        from deeplearning4j_tpu.serving.batcher import ContinuousBatcher
        self.inference = inference
        self.max_batch = int(max_batch)
        self._batcher = ContinuousBatcher(
            lambda group, xs: inference._output_batched(xs),
            max_batch=max_batch, batch_timeout_ms=timeout_ms)

    def submit(self, x: np.ndarray):
        x = np.asarray(x)
        return self._batcher.submit(
            x, group=(tuple(x.shape[1:]), str(x.dtype)))

    def output(self, x: np.ndarray) -> np.ndarray:
        return self.submit(x).result()

    def shutdown(self):
        """Graceful and idempotent: drains queued requests, then stops."""
        self._batcher.shutdown(drain=True, timeout=10.0)
