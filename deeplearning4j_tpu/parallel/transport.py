"""TCP transport for compressed gradient exchange — the Aeron substitute.

Reference: `nd4j-serde/nd4j-aeron` + `nd4j-parameter-server-parent`
(SURVEY.md §2.4): workers publish threshold-encoded gradient streams over
an Aeron UDP mesh.  Here the *fast* path (intra-slice) is XLA all-reduce
over ICI and never touches this module; this transport exists for the
reference's remaining role — shipping `parallel.compression` streams
between hosts over a commodity network (DCN) — and for the
multi-process-on-localhost tests (SURVEY §4's Aeron-on-loopback analog).

Topology: star via rank 0 (the parameter-server-shaped rank), length-
prefixed binary frames, no pickling — streams are raw int32/float32 buffers
exactly as the C++ codec emits them.
"""
from __future__ import annotations

import socket
import struct
import time
from typing import List, Optional

import numpy as np


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed during receive")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


def pack_streams(streams: List[np.ndarray],
                 thresholds: List[float]) -> bytes:
    """[count | per-leaf: len, threshold, int32 stream] — language-neutral
    framing (the FlatBuffers-message role in the reference's Aeron path)."""
    out = [struct.pack("<I", len(streams))]
    for s, t in zip(streams, thresholds):
        s = np.ascontiguousarray(s, dtype=np.int32)
        out.append(struct.pack("<If", s.size, float(t)))
        out.append(s.tobytes())
    return b"".join(out)


def unpack_streams(payload: bytes):
    (count,) = struct.unpack_from("<I", payload, 0)
    off = 4
    streams, thresholds = [], []
    for _ in range(count):
        n, t = struct.unpack_from("<If", payload, off)
        off += 8
        streams.append(np.frombuffer(payload, np.int32, n, off).copy())
        off += 4 * n
        thresholds.append(t)
    return streams, thresholds


class TcpGradientMesh:
    """All-gather of opaque byte payloads across ranks (star via rank 0).

    Rank 0 binds, accepts `world-1` peers (each identifies itself with its
    rank), gathers one payload per rank per round, and broadcasts the full
    list — every rank then holds every rank's compressed stream, mirroring
    the reference mesh where each worker applies every peer's encoded
    delta."""

    def __init__(self, rank: int, world: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 60.0):
        self.rank = rank
        self.world = world
        self._peers: List[Optional[socket.socket]] = [None] * world
        self._server: Optional[socket.socket] = None
        if world == 1:
            return
        if rank == 0:
            srv = socket.create_server((host, port), backlog=world)
            srv.settimeout(timeout)
            self._server = srv
            for _ in range(world - 1):
                conn, _ = srv.accept()
                conn.settimeout(timeout)
                (peer_rank,) = struct.unpack("<I", _recv_exact(conn, 4))
                self._peers[peer_rank] = conn
        else:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    conn = socket.create_connection((host, port),
                                                    timeout=timeout)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            conn.settimeout(timeout)
            conn.sendall(struct.pack("<I", rank))
            self._peers[0] = conn

    def allgather(self, payload: bytes) -> List[bytes]:
        if self.world == 1:
            return [payload]
        if self.rank == 0:
            gathered: List[bytes] = [b""] * self.world
            gathered[0] = payload
            for r in range(1, self.world):
                gathered[r] = _recv_msg(self._peers[r])
            blob = struct.pack("<I", self.world) + b"".join(
                struct.pack("<Q", len(g)) + g for g in gathered)
            for r in range(1, self.world):
                _send_msg(self._peers[r], blob)
            return gathered
        _send_msg(self._peers[0], payload)
        blob = _recv_msg(self._peers[0])
        (world,) = struct.unpack_from("<I", blob, 0)
        off = 4
        gathered = []
        for _ in range(world):
            (n,) = struct.unpack_from("<Q", blob, off)
            off += 8
            gathered.append(blob[off: off + n])
            off += n
        return gathered

    def close(self) -> None:
        for s in self._peers:
            if s is not None:
                s.close()
        if self._server is not None:
            self._server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
