"""TCP transport for compressed gradient exchange — the Aeron substitute.

Reference: `nd4j-serde/nd4j-aeron` + `nd4j-parameter-server-parent`
(SURVEY.md §2.4): workers publish threshold-encoded gradient streams over
an Aeron UDP mesh.  Here the *fast* path (intra-slice) is XLA all-reduce
over ICI and never touches this module; this transport exists for the
reference's remaining role — shipping `parallel.compression` streams
between hosts over a commodity network (DCN) — and for the
multi-process-on-localhost tests (SURVEY §4's Aeron-on-loopback analog).

Topology: star via rank 0 (the parameter-server-shaped rank), length-
prefixed binary frames, no pickling — streams are raw int32/float32 buffers
exactly as the C++ codec emits them.

Failure posture (the Aeron session-timeout role): every socket carries a
timeout, connects retry with exponential backoff up to a deadline, and a
peer that dies mid-exchange surfaces as a `PeerUnreachableError` NAMING
the rank and address — training fails fast with an actionable message
instead of hanging the whole gang on a silent recv.
"""
from __future__ import annotations

import socket
import struct
import time
from typing import List, Optional

import numpy as np


class PeerUnreachableError(ConnectionError):
    """A gradient-mesh peer could not be reached (connect) or stopped
    responding (exchange).  The message names the rank and address."""


def _send_msg(sock: socket.socket, payload: bytes) -> int:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)
    return len(payload) + 8


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed during receive")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


def pack_streams(streams: List[np.ndarray],
                 thresholds: List[float]) -> bytes:
    """[count | per-leaf: len, threshold, int32 stream] — language-neutral
    framing (the FlatBuffers-message role in the reference's Aeron path)."""
    out = [struct.pack("<I", len(streams))]
    for s, t in zip(streams, thresholds):
        s = np.ascontiguousarray(s, dtype=np.int32)
        out.append(struct.pack("<If", s.size, float(t)))
        out.append(s.tobytes())
    return b"".join(out)


def unpack_streams(payload: bytes):
    (count,) = struct.unpack_from("<I", payload, 0)
    off = 4
    streams, thresholds = [], []
    for _ in range(count):
        n, t = struct.unpack_from("<If", payload, off)
        off += 8
        streams.append(np.frombuffer(payload, np.int32, n, off).copy())
        off += 4 * n
        thresholds.append(t)
    return streams, thresholds


def pack_dense(leaves: List[np.ndarray]) -> bytes:
    """Full-precision framing for the uncompressed A/B baseline:
    [count | per-leaf: ndim, dims..., raw f32] — self-describing, so
    `unpack_dense` needs no shape template."""
    out = [struct.pack("<I", len(leaves))]
    for a in leaves:
        # shape BEFORE ascontiguousarray: that call promotes 0-d to 1-d,
        # which would silently re-shape scalar leaves on the far side
        a = np.asarray(a, np.float32)
        out.append(struct.pack("<I", a.ndim))
        out.append(struct.pack(f"<{max(a.ndim, 1)}q",
                               *(a.shape if a.ndim else (1,))))
        out.append(np.ascontiguousarray(a).tobytes())
    return b"".join(out)


def unpack_dense(payload: bytes) -> List[np.ndarray]:
    (count,) = struct.unpack_from("<I", payload, 0)
    off = 4
    leaves = []
    for _ in range(count):
        (ndim,) = struct.unpack_from("<I", payload, off)
        off += 4
        dims = struct.unpack_from(f"<{max(ndim, 1)}q", payload, off)
        off += 8 * max(ndim, 1)
        shape = tuple(dims[:ndim]) if ndim else ()
        n = int(np.prod(shape)) if ndim else 1
        a = np.frombuffer(payload, np.float32, n, off).copy()
        off += 4 * n
        leaves.append(a.reshape(shape) if ndim else a[0].reshape(()))
    return leaves


class TcpGradientMesh:
    """All-gather of opaque byte payloads across ranks (star via rank 0).

    Rank 0 binds, accepts `world-1` peers (each identifies itself with its
    rank), gathers one payload per rank per round, and broadcasts the full
    list — every rank then holds every rank's compressed stream, mirroring
    the reference mesh where each worker applies every peer's encoded
    delta.

    `timeout` bounds every blocking socket op (accept, connect attempts,
    recv/send during an exchange); `bytes_sent`/`bytes_received` count the
    actual frames on the wire (the `comms_bytes_on_wire_total` source)."""

    def __init__(self, rank: int, world: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 60.0,
                 connect_backoff_base: float = 0.05,
                 connect_backoff_cap: float = 2.0):
        self.rank = rank
        self.world = world
        self.host = host
        self.port = port
        self.timeout = timeout
        self.bytes_sent = 0
        self.bytes_received = 0
        self._peers: List[Optional[socket.socket]] = [None] * world
        self._peer_addr: List[str] = ["?"] * world
        self._server: Optional[socket.socket] = None
        if world == 1:
            return
        if rank == 0:
            srv = socket.create_server((host, port), backlog=world)
            self._server = srv
            deadline = time.monotonic() + timeout
            connected: set = set()
            for _ in range(world - 1):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._raise_formation_timeout(connected)
                srv.settimeout(remaining)
                try:
                    conn, addr = srv.accept()
                except (socket.timeout, TimeoutError):
                    self._raise_formation_timeout(connected)
                conn.settimeout(timeout)
                (peer_rank,) = struct.unpack("<I", _recv_exact(conn, 4))
                if peer_rank <= 0 or peer_rank >= world \
                        or peer_rank in connected:
                    conn.close()
                    raise ConnectionError(
                        f"rank 0: peer at {addr[0]}:{addr[1]} identified "
                        f"as invalid/duplicate rank {peer_rank} "
                        f"(world={world}, already connected: "
                        f"{sorted(connected)})")
                self._peers[peer_rank] = conn
                self._peer_addr[peer_rank] = f"{addr[0]}:{addr[1]}"
                connected.add(peer_rank)
        else:
            deadline = time.monotonic() + timeout
            backoff = connect_backoff_base
            attempts = 0
            last_err: Optional[Exception] = None
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise PeerUnreachableError(
                        f"rank {rank}: gradient-mesh coordinator (rank 0) "
                        f"at {host}:{port} unreachable after {timeout:.1f}s "
                        f"/ {attempts} attempts: {last_err!r}")
                try:
                    conn = socket.create_connection(
                        (host, port), timeout=min(remaining, timeout))
                    break
                except OSError as e:
                    last_err = e
                    attempts += 1
                    time.sleep(min(backoff, max(remaining, 0.0)))
                    backoff = min(backoff * 2, connect_backoff_cap)
            conn.settimeout(timeout)
            conn.sendall(struct.pack("<I", rank))
            self._peers[0] = conn
            self._peer_addr[0] = f"{host}:{port}"

    def _raise_formation_timeout(self, connected: set) -> None:
        missing = sorted(set(range(1, self.world)) - connected)
        raise PeerUnreachableError(
            f"rank 0: gradient mesh formation timed out after "
            f"{self.timeout:.1f}s on {self.host}:{self.port} — rank(s) "
            f"{missing} never connected ({len(connected)}/{self.world - 1} "
            "peers arrived)")

    def _peer_error(self, r: int, op: str,
                    e: Exception) -> PeerUnreachableError:
        return PeerUnreachableError(
            f"rank {self.rank}: gradient exchange {op} with rank {r} "
            f"({self._peer_addr[r]}) failed after {self.timeout:.1f}s — "
            f"peer dead or stalled: {e!r}")

    def allgather(self, payload: bytes) -> List[bytes]:
        if self.world == 1:
            return [payload]
        if self.rank == 0:
            gathered: List[bytes] = [b""] * self.world
            gathered[0] = payload
            for r in range(1, self.world):
                try:
                    gathered[r] = _recv_msg(self._peers[r])
                except (socket.timeout, TimeoutError, OSError,
                        ConnectionError) as e:
                    raise self._peer_error(r, "recv", e) from e
                self.bytes_received += len(gathered[r]) + 8
            blob = struct.pack("<I", self.world) + b"".join(
                struct.pack("<Q", len(g)) + g for g in gathered)
            for r in range(1, self.world):
                try:
                    self.bytes_sent += _send_msg(self._peers[r], blob)
                except (socket.timeout, TimeoutError, OSError,
                        ConnectionError) as e:
                    raise self._peer_error(r, "send", e) from e
            return gathered
        try:
            self.bytes_sent += _send_msg(self._peers[0], payload)
            blob = _recv_msg(self._peers[0])
        except (socket.timeout, TimeoutError, OSError,
                ConnectionError) as e:
            raise self._peer_error(0, "exchange", e) from e
        self.bytes_received += len(blob) + 8
        (world,) = struct.unpack_from("<I", blob, 0)
        off = 4
        gathered = []
        for _ in range(world):
            (n,) = struct.unpack_from("<Q", blob, off)
            off += 8
            gathered.append(blob[off: off + n])
            off += n
        return gathered

    def close(self) -> None:
        for s in self._peers:
            if s is not None:
                s.close()
        if self._server is not None:
            self._server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
