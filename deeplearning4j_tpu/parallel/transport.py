"""TCP transport for compressed gradient exchange — the Aeron substitute.

Reference: `nd4j-serde/nd4j-aeron` + `nd4j-parameter-server-parent`
(SURVEY.md §2.4): workers publish threshold-encoded gradient streams over
an Aeron UDP mesh.  Here the *fast* path (intra-slice) is XLA all-reduce
over ICI and never touches this module; this transport exists for the
reference's remaining role — shipping `parallel.compression` streams
between hosts over a commodity network (DCN) — and for the
multi-process-on-localhost tests (SURVEY §4's Aeron-on-loopback analog).

Topology: star via rank 0 (the parameter-server-shaped rank), length-
prefixed binary frames, no pickling — streams are raw int32/float32 buffers
exactly as the C++ codec emits them.

Failure posture (the Aeron session-timeout role): every socket carries a
timeout, connects retry with exponential backoff up to a deadline, and a
peer that dies mid-exchange surfaces as a `PeerUnreachableError` NAMING
the rank and address — training fails fast with an actionable message
instead of hanging the whole gang on a silent recv.
"""
from __future__ import annotations

import collections
import json
import socket
import struct
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np


class PeerUnreachableError(ConnectionError):
    """A gradient-mesh peer could not be reached (connect) or stopped
    responding (exchange).  The message names the rank and address."""


class GangEvictedError(ConnectionError):
    """This rank was declared lost by the coordinator (e.g. it straggled
    past the failure deadline, then woke up).  Its membership is gone; the
    only way back in is a fresh JOIN at the current generation."""


class GangReformed(RuntimeError):
    """The gang membership changed: raised out of `allgather` on every
    surviving rank so the training layer can rebuild codec state and
    resume from the coordinated checkpoint.  NOT an error condition —
    control flow for elastic membership.

    Attributes mirror the REFORM frame: `generation` (new), `world` (new),
    `rank` (this process's new rank), `rank_map` (old rank -> new rank for
    survivors), `lost` (old ranks removed), `cause`
    (crash|partition|straggler|join), `resume_step` (the checkpoint step
    every member restores), `detection_ms` (silence observed on the lost
    peer at declaration, None for joins)."""

    def __init__(self, info: Dict[str, Any]):
        self.generation = int(info["generation"])
        self.world = int(info["world"])
        self.rank = int(info["rank"])
        self.rank_map = {int(k): int(v)
                         for k, v in dict(info["rank_map"]).items()}
        self.lost = [int(r) for r in info.get("lost", [])]
        self.cause = str(info.get("cause", "unknown"))
        self.resume_step = int(info.get("resume_step", 0))
        self.detection_ms = info.get("detection_ms")
        super().__init__(
            f"gang reformed (cause={self.cause}): generation "
            f"{self.generation}, world {self.world}, this rank -> "
            f"{self.rank}, lost {self.lost}, resume from step "
            f"{self.resume_step}")


def _send_msg(sock: socket.socket, payload: bytes) -> int:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)
    return len(payload) + 8


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed during receive")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


def pack_streams(streams: List[np.ndarray],
                 thresholds: List[float]) -> bytes:
    """[count | per-leaf: len, threshold, int32 stream] — language-neutral
    framing (the FlatBuffers-message role in the reference's Aeron path)."""
    out = [struct.pack("<I", len(streams))]
    for s, t in zip(streams, thresholds):
        s = np.ascontiguousarray(s, dtype=np.int32)
        out.append(struct.pack("<If", s.size, float(t)))
        out.append(s.tobytes())
    return b"".join(out)


def unpack_streams(payload: bytes):
    (count,) = struct.unpack_from("<I", payload, 0)
    off = 4
    streams, thresholds = [], []
    for _ in range(count):
        n, t = struct.unpack_from("<If", payload, off)
        off += 8
        streams.append(np.frombuffer(payload, np.int32, n, off).copy())
        off += 4 * n
        thresholds.append(t)
    return streams, thresholds


def pack_dense(leaves: List[np.ndarray]) -> bytes:
    """Full-precision framing for the uncompressed A/B baseline:
    [count | per-leaf: ndim, dims..., raw f32] — self-describing, so
    `unpack_dense` needs no shape template."""
    out = [struct.pack("<I", len(leaves))]
    for a in leaves:
        # shape BEFORE ascontiguousarray: that call promotes 0-d to 1-d,
        # which would silently re-shape scalar leaves on the far side
        a = np.asarray(a, np.float32)
        out.append(struct.pack("<I", a.ndim))
        out.append(struct.pack(f"<{max(a.ndim, 1)}q",
                               *(a.shape if a.ndim else (1,))))
        out.append(np.ascontiguousarray(a).tobytes())
    return b"".join(out)


def unpack_dense(payload: bytes) -> List[np.ndarray]:
    (count,) = struct.unpack_from("<I", payload, 0)
    off = 4
    leaves = []
    for _ in range(count):
        (ndim,) = struct.unpack_from("<I", payload, off)
        off += 4
        dims = struct.unpack_from(f"<{max(ndim, 1)}q", payload, off)
        off += 8 * max(ndim, 1)
        shape = tuple(dims[:ndim]) if ndim else ()
        n = int(np.prod(shape)) if ndim else 1
        a = np.frombuffer(payload, np.float32, n, off).copy()
        off += 4 * n
        leaves.append(a.reshape(shape) if ndim else a[0].reshape(()))
    return leaves


class TcpGradientMesh:
    """All-gather of opaque byte payloads across ranks (star via rank 0).

    Rank 0 binds, accepts `world-1` peers (each identifies itself with its
    rank), gathers one payload per rank per round, and broadcasts the full
    list — every rank then holds every rank's compressed stream, mirroring
    the reference mesh where each worker applies every peer's encoded
    delta.

    `timeout` bounds every blocking socket op (accept, connect attempts,
    recv/send during an exchange); `bytes_sent`/`bytes_received` count the
    actual frames on the wire (the `comms_bytes_on_wire_total` source)."""

    def __init__(self, rank: int, world: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 60.0,
                 connect_backoff_base: float = 0.05,
                 connect_backoff_cap: float = 2.0):
        self.rank = rank
        self.world = world
        self.host = host
        self.port = port
        self.timeout = timeout
        self.bytes_sent = 0
        self.bytes_received = 0
        self._peers: List[Optional[socket.socket]] = [None] * world
        self._peer_addr: List[str] = ["?"] * world
        self._server: Optional[socket.socket] = None
        self._closed = False
        if world == 1:
            return
        # any exception during formation must not leak the sockets opened
        # so far — a supervisor retrying elastic relaunches would otherwise
        # exhaust fds on repeatedly half-formed gangs
        try:
            if rank == 0:
                self._form_coordinator()
            else:
                self._form_peer(connect_backoff_base, connect_backoff_cap)
        except BaseException:
            self.close()
            raise

    def _form_coordinator(self) -> None:
        srv = socket.create_server((self.host, self.port),
                                   backlog=self.world)
        self._server = srv
        deadline = time.monotonic() + self.timeout
        connected: set = set()
        for _ in range(self.world - 1):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._raise_formation_timeout(connected)
            srv.settimeout(remaining)
            try:
                conn, addr = srv.accept()
            except (socket.timeout, TimeoutError):
                self._raise_formation_timeout(connected)
            try:
                conn.settimeout(self.timeout)
                (peer_rank,) = struct.unpack("<I", _recv_exact(conn, 4))
                if peer_rank <= 0 or peer_rank >= self.world \
                        or peer_rank in connected:
                    raise ConnectionError(
                        f"rank 0: peer at {addr[0]}:{addr[1]} identified "
                        f"as invalid/duplicate rank {peer_rank} "
                        f"(world={self.world}, already connected: "
                        f"{sorted(connected)})")
            except BaseException:
                conn.close()
                raise
            self._peers[peer_rank] = conn
            self._peer_addr[peer_rank] = f"{addr[0]}:{addr[1]}"
            connected.add(peer_rank)

    def _form_peer(self, backoff_base: float, backoff_cap: float) -> None:
        deadline = time.monotonic() + self.timeout
        backoff = backoff_base
        attempts = 0
        last_err: Optional[Exception] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise PeerUnreachableError(
                    f"rank {self.rank}: gradient-mesh coordinator (rank 0) "
                    f"at {self.host}:{self.port} unreachable after "
                    f"{self.timeout:.1f}s / {attempts} attempts: "
                    f"{last_err!r}")
            try:
                conn = socket.create_connection(
                    (self.host, self.port),
                    timeout=min(remaining, self.timeout))
                break
            except OSError as e:
                last_err = e
                attempts += 1
                time.sleep(min(backoff, max(remaining, 0.0)))
                backoff = min(backoff * 2, backoff_cap)
        try:
            conn.settimeout(self.timeout)
            conn.sendall(struct.pack("<I", self.rank))
        except BaseException:
            conn.close()
            raise
        self._peers[0] = conn
        self._peer_addr[0] = f"{self.host}:{self.port}"

    def _raise_formation_timeout(self, connected: set) -> None:
        missing = sorted(set(range(1, self.world)) - connected)
        raise PeerUnreachableError(
            f"rank 0: gradient mesh formation timed out after "
            f"{self.timeout:.1f}s on {self.host}:{self.port} — rank(s) "
            f"{missing} never connected ({len(connected)}/{self.world - 1} "
            "peers arrived)")

    def _peer_error(self, r: int, op: str,
                    e: Exception) -> PeerUnreachableError:
        return PeerUnreachableError(
            f"rank {self.rank}: gradient exchange {op} with rank {r} "
            f"({self._peer_addr[r]}) failed after {self.timeout:.1f}s — "
            f"peer dead or stalled: {e!r}")

    def allgather(self, payload: bytes) -> List[bytes]:
        # a mid-exchange failure means the gang is dead: release the
        # sockets before surfacing it, so the fds never outlive the
        # exchange that killed them (elastic relaunches would leak them)
        try:
            return self._allgather(payload)
        except PeerUnreachableError:
            self.close()
            raise

    def _allgather(self, payload: bytes) -> List[bytes]:
        if self.world == 1:
            return [payload]
        if self.rank == 0:
            gathered: List[bytes] = [b""] * self.world
            gathered[0] = payload
            for r in range(1, self.world):
                try:
                    gathered[r] = _recv_msg(self._peers[r])
                except (socket.timeout, TimeoutError, OSError,
                        ConnectionError) as e:
                    raise self._peer_error(r, "recv", e) from e
                self.bytes_received += len(gathered[r]) + 8
            blob = struct.pack("<I", self.world) + b"".join(
                struct.pack("<Q", len(g)) + g for g in gathered)
            for r in range(1, self.world):
                try:
                    self.bytes_sent += _send_msg(self._peers[r], blob)
                except (socket.timeout, TimeoutError, OSError,
                        ConnectionError) as e:
                    raise self._peer_error(r, "send", e) from e
            return gathered
        try:
            self.bytes_sent += _send_msg(self._peers[0], payload)
            blob = _recv_msg(self._peers[0])
        except (socket.timeout, TimeoutError, OSError,
                ConnectionError) as e:
            raise self._peer_error(0, "exchange", e) from e
        self.bytes_received += len(blob) + 8
        (world,) = struct.unpack_from("<I", blob, 0)
        off = 4
        gathered = []
        for _ in range(world):
            (n,) = struct.unpack_from("<Q", blob, off)
            off += 8
            gathered.append(blob[off: off + n])
            off += n
        return gathered

    def close(self) -> None:
        """Idempotent: safe to call repeatedly and from error paths mid-
        formation (partial peer lists, server bound but no peers)."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        for i, s in enumerate(self._peers):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
                self._peers[i] = None
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Elastic gang mesh: generation-fenced frames, heartbeats, coordinator-led
# re-formation
# ---------------------------------------------------------------------------

# Elastic frame: <Q payload-len><I generation><B kind> + payload.  EVERY
# frame carries the sender's generation; DATA from a stale generation is
# fenced (dropped + counted), never summed into gradients.  Heartbeats
# update liveness regardless of generation — a survivor that has not yet
# consumed the REFORM frame still proves it is alive.
#
# The same framing is the wire protocol of the serving-side fleet
# federation (`serving/federation.py`): a HostAgent JOINs the
# FederationRouter, heartbeats, carries dispatch traffic in DATA frames
# and replicated fleet-topology snapshots in SNAPSHOT frames — with the
# identical stale-generation fence, so a partitioned host's late replies
# are never returned to clients.
_ELASTIC_HDR = struct.Struct("<QIB")
KIND_DATA = 0        # gradient payload (gather leg or broadcast leg)
KIND_HB = 1          # heartbeat (empty payload)
KIND_REFORM = 2      # coordinator -> members: new (gen, world, rank map)
KIND_JOIN = 3        # member -> coordinator: formation / rejoin request
KIND_WELCOME = 4     # coordinator -> joiner: admission + resume point
KIND_SNAPSHOT = 5    # federation: replicated fleet-topology snapshot copy


class _FrameReader:
    """Incremental elastic-frame parser over a byte stream (recv chunks
    in, complete (generation, kind, payload) frames out)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[int, int, bytes]]:
        self._buf += data
        frames = []
        while True:
            if len(self._buf) < _ELASTIC_HDR.size:
                break
            n, gen, kind = _ELASTIC_HDR.unpack_from(self._buf, 0)
            end = _ELASTIC_HDR.size + n
            if len(self._buf) < end:
                break
            frames.append((gen, kind,
                           bytes(self._buf[_ELASTIC_HDR.size:end])))
            del self._buf[:end]
        return frames


def _frame_bytes(generation: int, kind: int, payload: bytes) -> bytes:
    return _ELASTIC_HDR.pack(len(payload), int(generation),
                             int(kind)) + payload


class ElasticGradientMesh:
    """Star all-gather with elastic gang membership.

    Same wire role as :class:`TcpGradientMesh` — one opaque payload per
    rank per round, gathered and re-broadcast through rank 0 — but the
    gang survives member loss:

    * every frame carries a **generation id**; DATA from a previous
      generation is fenced (dropped and counted in
      ``gang_stale_frames_total``), so a straggler waking up after a
      re-formation can never leak its gradient into the new gang;
    * every member **heartbeats** (`heartbeat_interval`); the coordinator
      declares a peer lost after `failure_deadline` of silence
      (partition), on EOF (crash), or when the peer heartbeats but ships
      no data past the deadline during a round (straggler) — a bounded
      detection instead of a hung socket op;
    * on detection the coordinator **re-forms**: bumps the generation,
      compacts surviving ranks (rank 0 stays 0; survivors keep their
      relative order), and pushes a REFORM frame carrying the new
      ``(generation, world, rank_map)`` plus the checkpoint step everyone
      must resume from (`resume_step_provider`).  Survivors raise
      :class:`GangReformed` out of `allgather`; the training layer
      rebuilds codec state and restores the named checkpoint;
    * a replacement worker connects with ``join=True``; it is parked
      until the coordinator's training layer admits it at a safe point
      (`admit_joiners`), which re-forms upward the same way.

    Rank 0 death remains gang-fatal (the star has no other hub): peers
    surface `PeerUnreachableError` within the deadline and the supervisor
    relaunches the gang, resuming from the shared checkpoint directory.
    """

    def __init__(self, rank: int, world: int, port: int,
                 host: str = "127.0.0.1", timeout: float = 60.0,
                 heartbeat_interval: float = 0.25,
                 failure_deadline: float = 5.0,
                 join: bool = False, join_timeout: float = 120.0,
                 resume_step_provider: Optional[Callable[[], int]] = None,
                 connect_backoff_base: float = 0.05,
                 connect_backoff_cap: float = 2.0):
        self.rank = int(rank)
        self.world = int(world)
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.heartbeat_interval = float(heartbeat_interval)
        self.failure_deadline = float(failure_deadline)
        self.join_timeout = float(join_timeout)
        self.resume_step_provider = resume_step_provider
        self.generation = 1
        self.bytes_sent = 0
        self.bytes_received = 0
        self.stale_frames = 0          # local mirror of the fence counter
        self.reformations = 0
        self.join_info: Optional[Dict[str, Any]] = None
        self._closed = False
        self._stop = threading.Event()
        self._hb_paused = threading.Event()    # chaos: simulate partition
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._pending_reform: Optional[Dict[str, Any]] = None
        self._reactor_thread: Optional[threading.Thread] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._server: Optional[socket.socket] = None
        # coordinator state (keyed by CURRENT rank)
        self._conns: Dict[int, socket.socket] = {}
        self._addr: Dict[int, str] = {}
        self._readers: Dict[int, _FrameReader] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._last_heard: Dict[int, float] = {}
        self._inbox: Dict[int, Deque[bytes]] = {}
        self._joiners: List[Tuple[socket.socket, str, _FrameReader]] = []
        self._handshaking: List[Tuple[socket.socket, str,
                                      _FrameReader]] = []
        # peer state
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._reader = _FrameReader()
        self._frames: Deque[Tuple[int, int, bytes]] = collections.deque()
        self._last_recv = time.monotonic()
        try:
            if join:
                self._join_gang(connect_backoff_base, connect_backoff_cap)
            elif self.rank == 0:
                self._form_coordinator()
            else:
                self._form_peer(connect_backoff_base, connect_backoff_cap)
        except BaseException:
            self.close()
            raise
        self._instr().record_membership(self.generation, self.world)

    # ------------------------------------------------------------------
    # formation
    # ------------------------------------------------------------------
    def _instr(self):
        from deeplearning4j_tpu.monitor.instrument import gang_instruments
        return gang_instruments()

    def _count_stale(self, n: int = 1) -> None:
        self.stale_frames += n
        self._instr().stale_frames.inc(n)

    def _form_coordinator(self) -> None:
        self._server = socket.create_server((self.host, self.port),
                                            backlog=max(self.world, 4))
        deadline = time.monotonic() + self.timeout
        connected: set = set()
        while len(connected) < self.world - 1:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                missing = sorted(set(range(1, self.world)) - connected)
                raise PeerUnreachableError(
                    f"rank 0: elastic gang formation timed out after "
                    f"{self.timeout:.1f}s on {self.host}:{self.port} — "
                    f"rank(s) {missing} never joined")
            self._server.settimeout(remaining)
            try:
                conn, addr = self._server.accept()
            except (socket.timeout, TimeoutError):
                continue
            try:
                conn.settimeout(min(remaining, self.timeout))
                gen, kind, payload = self._read_frames(conn,
                                                       _FrameReader())[0]
                d = json.loads(payload.decode("utf-8")) if payload else {}
                peer_rank = d.get("rank")
                if kind != KIND_JOIN or peer_rank is None \
                        or not (0 < int(peer_rank) < self.world) \
                        or int(peer_rank) in connected:
                    raise ConnectionError(
                        f"rank 0: bad formation JOIN from "
                        f"{addr[0]}:{addr[1]} (kind={kind}, "
                        f"rank={peer_rank!r})")
                peer_rank = int(peer_rank)
                welcome = json.dumps({"generation": self.generation,
                                      "world": self.world,
                                      "rank": peer_rank}).encode("utf-8")
                conn.sendall(_frame_bytes(self.generation, KIND_WELCOME,
                                          welcome))
            except BaseException:
                conn.close()
                raise
            conn.setblocking(False)
            self._register_peer(peer_rank, conn,
                                f"{addr[0]}:{addr[1]}")
            connected.add(peer_rank)
        self._reactor_thread = threading.Thread(
            target=self._reactor, name="gang-reactor", daemon=True)
        self._reactor_thread.start()

    def _register_peer(self, rank: int, conn: socket.socket,
                       addr: str) -> None:
        with self._lock:
            self._conns[rank] = conn
            self._addr[rank] = addr
            self._readers[rank] = _FrameReader()
            self._send_locks[rank] = threading.Lock()
            self._last_heard[rank] = time.monotonic()
            self._inbox[rank] = collections.deque()

    def _connect(self, backoff_base: float, backoff_cap: float,
                 budget: float) -> socket.socket:
        deadline = time.monotonic() + budget
        backoff = backoff_base
        attempts = 0
        last_err: Optional[Exception] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise PeerUnreachableError(
                    f"rank {self.rank}: gang coordinator at "
                    f"{self.host}:{self.port} unreachable after "
                    f"{budget:.1f}s / {attempts} attempts: {last_err!r}")
            try:
                return socket.create_connection(
                    (self.host, self.port), timeout=min(remaining,
                                                        budget))
            except OSError as e:
                last_err = e
                attempts += 1
                time.sleep(min(backoff, max(remaining, 0.0)))
                backoff = min(backoff * 2, backoff_cap)

    def _form_peer(self, backoff_base: float, backoff_cap: float) -> None:
        conn = self._connect(backoff_base, backoff_cap, self.timeout)
        try:
            conn.settimeout(self.timeout)
            hello = json.dumps({"rank": self.rank}).encode("utf-8")
            conn.sendall(_frame_bytes(0, KIND_JOIN, hello))
            frames = self._read_frames(conn, self._reader)
            gen, kind, payload = frames[0]
            self._frames.extend(frames[1:])
            if kind != KIND_WELCOME:
                raise ConnectionError(
                    f"rank {self.rank}: expected WELCOME, got kind {kind}")
            d = json.loads(payload.decode("utf-8"))
            self.generation = int(d["generation"])
            self.world = int(d["world"])
        except BaseException:
            conn.close()
            raise
        self._sock = conn
        self._last_recv = time.monotonic()
        self._start_heartbeats()

    def _join_gang(self, backoff_base: float, backoff_cap: float) -> None:
        """Replacement-worker path: connect, announce JOIN, and park until
        the coordinator's training layer admits us (safe point) — the
        WELCOME then carries our assigned rank, the new world and the
        checkpoint step to resume from."""
        conn = self._connect(backoff_base, backoff_cap, self.join_timeout)
        try:
            conn.settimeout(self.join_timeout)
            hello = json.dumps({"rank": None}).encode("utf-8")
            conn.sendall(_frame_bytes(0, KIND_JOIN, hello))
            d = None
            while d is None:
                for gen, kind, payload in self._read_frames(conn,
                                                            self._reader):
                    if kind in (KIND_HB, KIND_REFORM):
                        continue        # not a member yet
                    if kind != KIND_WELCOME:
                        raise ConnectionError(
                            f"joiner: expected WELCOME, got kind {kind}")
                    d = json.loads(payload.decode("utf-8"))
                    break
            self.generation = int(d["generation"])
            self.world = int(d["world"])
            self.rank = int(d["rank"])
            self.join_info = d
        except BaseException:
            conn.close()
            raise
        self._sock = conn
        self._last_recv = time.monotonic()
        self._start_heartbeats()

    @staticmethod
    def _read_frames(conn: socket.socket,
                     reader: _FrameReader) -> List[Tuple[int, int, bytes]]:
        """Blocking read of at least one complete frame (handshake paths
        — the socket still has a timeout set)."""
        while True:
            data = conn.recv(65536)
            if not data:
                raise ConnectionError("peer closed during handshake")
            frames = reader.feed(data)
            if frames:
                return frames

    # ------------------------------------------------------------------
    # heartbeats (member side)
    # ------------------------------------------------------------------
    def _start_heartbeats(self) -> None:
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name="gang-heartbeat", daemon=True)
        self._hb_thread.start()

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            if self._hb_paused.is_set():
                continue
            try:
                self._peer_send(KIND_HB, b"")
            except OSError:
                return          # main thread will surface the death

    def pause_heartbeats(self, paused: bool = True) -> None:
        """Chaos hook: stop/resume heartbeating WITHOUT closing the
        socket — to the coordinator this is indistinguishable from a
        network partition."""
        if paused:
            self._hb_paused.set()
        else:
            self._hb_paused.clear()

    def _peer_send(self, kind: int, payload: bytes,
                   generation: Optional[int] = None) -> None:
        gen = self.generation if generation is None else generation
        frame = _frame_bytes(gen, kind, payload)
        with self._send_lock:
            self._sock.sendall(frame)
        self.bytes_sent += len(frame)

    # ------------------------------------------------------------------
    # coordinator reactor: liveness, inbound frames, joiners
    # ------------------------------------------------------------------
    def _reactor(self) -> None:
        tick = min(0.005, self.heartbeat_interval / 4)
        next_hb = 0.0
        while not self._stop.wait(tick):
            now = time.monotonic()
            if now >= next_hb:
                self._coord_broadcast(KIND_HB, b"", best_effort=True)
                next_hb = now + self.heartbeat_interval
            self._pump_sockets()
            self._accept_new()
            self._check_deadlines()

    def _pump_sockets(self) -> None:
        with self._lock:
            socks = list(self._conns.items())
        dead: List[int] = []
        for r, conn in socks:
            try:
                while True:
                    data = conn.recv(1 << 16)
                    if not data:
                        dead.append(r)
                        break
                    self.bytes_received += len(data)
                    self._dispatch_frames(r, data)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                dead.append(r)
        if dead:
            self._reform(lost=set(dead), cause="crash")

    def _dispatch_frames(self, r: int, data: bytes) -> None:
        with self._lock:
            reader = self._readers.get(r)
            if reader is None:
                return
            for gen, kind, payload in reader.feed(data):
                self._last_heard[r] = time.monotonic()
                if kind == KIND_HB:
                    continue        # liveness only, any generation
                if kind == KIND_DATA:
                    if gen != self.generation:
                        self._count_stale()
                        continue
                    self._inbox[r].append(payload)
                    self._cond.notify_all()
                # REFORM/JOIN/WELCOME from an established peer: ignore

    def _accept_new(self) -> None:
        srv = self._server
        if srv is None:
            return
        srv.setblocking(False)
        try:
            while True:
                try:
                    conn, addr = srv.accept()
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    break
                conn.setblocking(False)
                with self._lock:
                    self._handshaking.append(
                        (conn, f"{addr[0]}:{addr[1]}", _FrameReader()))
        finally:
            pass
        # progress half-open handshakes: a JOIN frame parks the socket as
        # a pending joiner until the training layer admits it
        with self._lock:
            still = []
            for conn, addr, reader in self._handshaking:
                try:
                    data = conn.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    still.append((conn, addr, reader))
                    continue
                except OSError:
                    conn.close()
                    continue
                if not data:
                    conn.close()
                    continue
                frames = reader.feed(data)
                joined = False
                for gen, kind, payload in frames:
                    if kind == KIND_JOIN:
                        self._joiners.append((conn, addr, reader))
                        self._cond.notify_all()
                        joined = True
                        break
                if not joined:
                    if frames:      # spoke, but not a JOIN: reject
                        conn.close()
                    else:
                        still.append((conn, addr, reader))
            self._handshaking = still

    def _check_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            silent = {r for r, t in self._last_heard.items()
                      if now - t > self.failure_deadline}
        if silent:
            self._reform(lost=silent, cause="partition")

    def _coord_broadcast(self, kind: int, payload: bytes,
                         best_effort: bool = False,
                         generation: Optional[int] = None) -> List[int]:
        """Send one frame to every connected peer; returns ranks whose
        send failed (empty when best_effort and all well)."""
        gen = self.generation if generation is None else generation
        frame = _frame_bytes(gen, kind, payload)
        with self._lock:
            targets = list(self._conns.items())
        failed = []
        for r, conn in targets:
            lock = self._send_locks.get(r)
            if lock is None:
                continue
            try:
                with lock:
                    conn.sendall(frame)
                self.bytes_sent += len(frame)
            except OSError:
                failed.append(r)
        if failed and not best_effort:
            self._reform(lost=set(failed), cause="crash")
        return failed

    # ------------------------------------------------------------------
    # re-formation (coordinator)
    # ------------------------------------------------------------------
    def _resume_step(self) -> int:
        if self.resume_step_provider is None:
            return 0
        try:
            return int(self.resume_step_provider() or 0)
        except Exception:
            return 0

    def _reform(self, lost: set, cause: str,
                resume_step: Optional[int] = None) -> Dict[str, Any]:
        """Coordinator-side membership change: bump the generation,
        compact survivor ranks, fence stale inboxes, notify survivors.
        Thread-safe (reactor and allgather both call it)."""
        with self._lock:
            lost = {r for r in lost if r in self._conns}
            if not lost:
                return self._pending_reform or {}
            now = time.monotonic()
            detection_ms = max(
                (now - self._last_heard.get(r, now)) * 1000.0
                for r in lost)
            survivors = [0] + sorted(r for r in self._conns
                                     if r not in lost)
            rank_map = {old: new for new, old in enumerate(survivors)}
            self.generation += 1
            self.reformations += 1
            step = self._resume_step() if resume_step is None \
                else int(resume_step)
            info = {"generation": self.generation,
                    "world": len(survivors),
                    "rank": 0, "rank_map": rank_map,
                    "lost": sorted(lost), "cause": cause,
                    "resume_step": step, "detection_ms": detection_ms}
            # fence: anything buffered was sent under the old generation
            dropped = sum(len(q) for q in self._inbox.values())
            if dropped:
                self._count_stale(dropped)
            # eviction notice: a merely-partitioned/straggling peer whose
            # socket is still writable learns it was declared lost (its
            # rank is absent from the map -> GangEvictedError -> rejoin)
            notice = json.dumps({**info,
                                 "rank_map": {str(k): v for k, v
                                              in rank_map.items()}
                                 }).encode("utf-8")
            for r in lost:
                try:
                    self._conns[r].sendall(
                        _frame_bytes(self.generation, KIND_REFORM,
                                     notice))
                except OSError:
                    pass
                try:
                    self._conns[r].close()
                except OSError:
                    pass
            old_conns, old_addr = self._conns, self._addr
            old_locks = self._send_locks
            old_readers = self._readers
            self._conns, self._addr, self._send_locks = {}, {}, {}
            self._readers, self._last_heard, self._inbox = {}, {}, {}
            for old in survivors[1:]:
                new = rank_map[old]
                self._conns[new] = old_conns[old]
                self._addr[new] = old_addr[old]
                self._send_locks[new] = old_locks[old]
                self._readers[new] = old_readers[old]
                self._last_heard[new] = now
                self._inbox[new] = collections.deque()
            self.world = len(survivors)
            self._pending_reform = info
            self._cond.notify_all()
        # REFORM frames carry the NEW generation; survivors' in-flight
        # old-generation data is already fenced above
        payload = json.dumps({**info,
                              "rank_map": {str(k): v for k, v
                                           in info["rank_map"].items()}
                              }).encode("utf-8")
        self._coord_broadcast(KIND_REFORM, payload, best_effort=True)
        self._instr().record_reform(cause, info["detection_ms"],
                                    self.generation, self.world)
        return info

    def _raise_pending_reform(self) -> None:
        """Surface a reformation to the coordinator's own training loop
        (must hold the lock)."""
        info, self._pending_reform = self._pending_reform, None
        if info is not None:
            raise GangReformed(info)

    # ---- joiner admission (training layer calls at a safe point) ----
    def has_pending_joiner(self) -> bool:
        with self._lock:
            return bool(self._joiners)

    def wait_for_joiner(self, timeout: float) -> bool:
        """Block (coordinator) until a replacement worker is parked or
        `timeout` elapses.  Heartbeats keep flowing from the reactor, so
        survivors blocked in `allgather` do NOT false-positive on rank 0
        while it waits."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._joiners:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
            return True

    def admit_joiners(self, resume_step: int) -> Optional[Dict[str, Any]]:
        """Admit every parked joiner: bump the generation, grow the
        world, WELCOME the joiners with their new ranks and the resume
        step, and push REFORM to existing peers (who raise
        :class:`GangReformed` and restore the same checkpoint).  Returns
        the reform info (the coordinator handles its own rebuild inline —
        no exception), or None when no joiner is parked.  Coordinator
        only, between rounds."""
        if self.rank != 0:
            raise RuntimeError("admit_joiners is coordinator-only")
        with self._lock:
            joiners, self._joiners = self._joiners, []
            if not joiners:
                return None
            existing = list(self._conns.items())
            self.generation += 1
            self.reformations += 1
            base = self.world
            rank_map = {r: r for r in range(self.world)}
            new_ranks = []
            for i, (conn, addr, reader) in enumerate(joiners):
                new_ranks.append(base + i)
            self.world += len(joiners)
            info = {"generation": self.generation, "world": self.world,
                    "rank": 0, "rank_map": rank_map, "lost": [],
                    "cause": "join", "resume_step": int(resume_step),
                    "detection_ms": None, "joined": new_ranks}
            dropped = sum(len(q) for q in self._inbox.values())
            if dropped:
                self._count_stale(dropped)
            for q in self._inbox.values():
                q.clear()
            for (conn, addr, reader), nr in zip(joiners, new_ranks):
                welcome = json.dumps(
                    {"generation": self.generation, "world": self.world,
                     "rank": nr, "resume_step": int(resume_step),
                     "cause": "join"}).encode("utf-8")
                try:
                    conn.sendall(_frame_bytes(self.generation,
                                              KIND_WELCOME, welcome))
                except OSError:
                    conn.close()
                    self.world -= 1
                    info["world"] = self.world
                    continue
                self._conns[nr] = conn
                self._addr[nr] = addr
                self._readers[nr] = reader
                self._send_locks[nr] = threading.Lock()
                self._last_heard[nr] = time.monotonic()
                self._inbox[nr] = collections.deque()
        # REFORM goes to the PRE-EXISTING peers only — the joiners were
        # welcomed directly and must not see a reform for the generation
        # they just entered at
        payload = json.dumps({**info,
                              "rank_map": {str(k): v for k, v
                                           in info["rank_map"].items()}
                              }).encode("utf-8")
        frame = _frame_bytes(self.generation, KIND_REFORM, payload)
        for r, conn in existing:
            lock = self._send_locks.get(r)
            if lock is None:
                continue
            try:
                with lock:
                    conn.sendall(frame)
                self.bytes_sent += len(frame)
            except OSError:
                pass        # reactor will reform on the dead socket
        self._instr().record_reform("join", None, self.generation,
                                    self.world)
        return info

    def request_evict(self, rank: int, resume_step: Optional[int] = None,
                      cause: str = "shrink") -> Dict[str, Any]:
        """Externally-initiated shrink (the pod arbiter reclaiming a
        slice): evict `rank` exactly as if it had crashed, but at a
        COORDINATED resume step — the caller checkpoints at that step
        first, so the evicted worker's slice can be handed off while the
        survivors bitwise-resume.  The evicted peer receives the same
        eviction-notice REFORM frame a partitioned straggler would
        (-> GangEvictedError -> park/rejoin); the coordinator's own loop
        sees GangReformed on its next collective.  Coordinator only."""
        if self.rank != 0:
            raise RuntimeError("request_evict is coordinator-only")
        if rank == 0:
            raise ValueError("cannot evict the coordinator (rank 0)")
        return self._reform(lost={rank}, cause=cause,
                            resume_step=resume_step)

    # ------------------------------------------------------------------
    # allgather
    # ------------------------------------------------------------------
    def allgather(self, payload: bytes) -> List[bytes]:
        if self.rank == 0:
            return self._allgather_coordinator(payload)
        return self._allgather_peer(payload)

    def _allgather_coordinator(self, payload: bytes) -> List[bytes]:
        with self._lock:
            self._raise_pending_reform()
            peer_ranks = sorted(self._conns)
        if not peer_ranks:
            return [payload]
        deadline = time.monotonic() + self.failure_deadline
        gathered: Dict[int, bytes] = {}
        with self._cond:
            while True:
                self._raise_pending_reform()
                missing = [r for r in sorted(self._conns)
                           if not self._inbox.get(r)]
                if not missing:
                    break
                if time.monotonic() > deadline:
                    # alive (heartbeating) but shipping no data: straggler
                    stragglers = set(missing)
                    self._lock.release()
                    try:
                        self._reform(lost=stragglers, cause="straggler")
                    finally:
                        self._lock.acquire()
                    self._raise_pending_reform()
                self._cond.wait(0.05)
            for r in sorted(self._conns):
                gathered[r] = self._inbox[r].popleft()
        out: List[bytes] = [b""] * self.world
        out[0] = payload
        for r, g in gathered.items():
            out[r] = g
        blob = struct.pack("<I", self.world) + b"".join(
            struct.pack("<Q", len(g)) + g for g in out)
        failed = self._coord_broadcast(KIND_DATA, blob, best_effort=True)
        if failed:
            self._reform(lost=set(failed), cause="crash")
            with self._lock:
                self._raise_pending_reform()
        return out

    def _allgather_peer(self, payload: bytes) -> List[bytes]:
        # consume anything that arrived mid-compute FIRST: a REFORM must
        # win over sending data that would only be fenced as stale
        self._drain_nonblocking()
        self._process_buffered(expect_data=False)
        try:
            self._peer_send(KIND_DATA, payload)
        except OSError as e:
            self.close()
            raise PeerUnreachableError(
                f"rank {self.rank}: gang coordinator at "
                f"{self.host}:{self.port} send failed: {e!r}") from e
        while True:
            blob = self._process_buffered(expect_data=True)
            if blob is not None:
                break
            self._recv_tick()
        (world,) = struct.unpack_from("<I", blob, 0)
        off = 4
        gathered = []
        for _ in range(world):
            (n,) = struct.unpack_from("<Q", blob, off)
            off += 8
            gathered.append(blob[off: off + n])
            off += n
        return gathered

    def _recv_tick(self) -> None:
        """One bounded blocking read on the coordinator socket; enforces
        the failure deadline on total silence (heartbeats reset it, so a
        healthy-but-busy coordinator never trips it)."""
        self._sock.settimeout(min(0.1, self.heartbeat_interval))
        try:
            data = self._sock.recv(1 << 16)
        except (socket.timeout, TimeoutError):
            silence = time.monotonic() - self._last_recv
            if silence > self.failure_deadline:
                self.close()
                raise PeerUnreachableError(
                    f"rank {self.rank}: gang coordinator at "
                    f"{self.host}:{self.port} silent for "
                    f"{silence:.2f}s (deadline "
                    f"{self.failure_deadline:.2f}s) — coordinator dead "
                    "or partitioned")
            return
        except OSError as e:
            self.close()
            raise PeerUnreachableError(
                f"rank {self.rank}: gang coordinator connection failed: "
                f"{e!r}") from e
        if not data:
            self.close()
            raise PeerUnreachableError(
                f"rank {self.rank}: gang coordinator at "
                f"{self.host}:{self.port} closed the connection")
        self.bytes_received += len(data)
        self._last_recv = time.monotonic()
        self._frames.extend(self._reader.feed(data))

    def _drain_nonblocking(self) -> None:
        eof = False
        if self._sock is None:
            eof = True
        else:
            self._sock.setblocking(False)
            try:
                while True:
                    data = self._sock.recv(1 << 16)
                    if not data:
                        eof = True
                        break
                    self.bytes_received += len(data)
                    self._last_recv = time.monotonic()
                    self._frames.extend(self._reader.feed(data))
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                eof = True
            finally:
                if self._sock is not None:
                    self._sock.setblocking(True)
        if eof:
            # a buffered eviction/reform notice explains the close far
            # better than a bare connection error — surface it first
            self._process_buffered(expect_data=False)
            self.close()
            raise PeerUnreachableError(
                f"rank {self.rank}: gang coordinator at "
                f"{self.host}:{self.port} closed the connection")

    def _process_buffered(self,
                          expect_data: bool) -> Optional[bytes]:
        """Handle queued frames; returns the current-generation DATA
        broadcast when one is present (and `expect_data`)."""
        while self._frames:
            gen, kind, payload = self._frames.popleft()
            if kind == KIND_HB:
                continue
            if kind == KIND_REFORM:
                self._apply_reform(payload)        # raises
            if kind == KIND_DATA:
                if gen != self.generation:
                    self._count_stale()
                    continue
                if expect_data:
                    return payload
                self._count_stale()     # unexpected round data: fence it
        return None

    def _apply_reform(self, payload: bytes) -> None:
        d = json.loads(payload.decode("utf-8"))
        rank_map = {int(k): int(v) for k, v in d["rank_map"].items()}
        if self.rank not in rank_map:
            self.close()
            raise GangEvictedError(
                f"rank {self.rank}: declared lost in generation "
                f"{d['generation']} (cause={d.get('cause')}) — rejoin "
                "with join=True to re-enter the gang")
        self.generation = int(d["generation"])
        self.world = int(d["world"])
        self.rank = rank_map[self.rank]
        self._instr().record_membership(self.generation, self.world)
        raise GangReformed({**d, "rank": self.rank,
                            "rank_map": rank_map})

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {"rank": self.rank, "world": self.world,
                "generation": self.generation,
                "reformations": self.reformations,
                "stale_frames": self.stale_frames,
                "bytes_sent": self.bytes_sent,
                "bytes_received": self.bytes_received}

    def close(self) -> None:
        """Idempotent; stops the heartbeat/reactor threads and closes
        every socket (peers, server, parked joiners, half-open
        handshakes)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for t in (self._reactor_thread, self._hb_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=2.0)
        with self._lock:
            socks = list(self._conns.values())
            socks += [c for c, _, _ in self._joiners]
            socks += [c for c, _, _ in self._handshaking]
            self._conns, self._joiners = {}, []
            self._handshaking = []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
