"""Device-mesh construction and sharding helpers.

Replaces the reference's device topology plumbing (`AffinityManager`,
`MeshOrganizer` node-tree in `nd4j-parameter-server-node`): on TPU the
topology is the XLA device mesh, and "mesh formation" is just naming axes.
Axis convention (scaling-book style): `data` (DP), `model` (TP), `pipe`
(PP), `seq` (SP/context).  Multi-host control plane = `jax.distributed`
(the Aeron mesh's control role), not anything here.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                     # jax >= 0.6: top-level export
    from jax import shard_map as _jax_shard_map
    _SHARD_MAP_LEGACY = False
except ImportError:                      # older jax: experimental module,
    from jax.experimental.shard_map import (  # check_rep instead of
        shard_map as _jax_shard_map)          # check_vma
    _SHARD_MAP_LEGACY = True


def shard_map(f, *args, **kwargs):
    """`jax.shard_map` across jax versions (maps check_vma -> check_rep)."""
    if _SHARD_MAP_LEGACY and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _jax_shard_map(f, *args, **kwargs)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes, e.g. {'data': 4, 'model': 2}.  Axis order follows
    insertion order; sizes must multiply to the device count used."""

    axes: Dict[str, int]

    def total(self) -> int:
        n = 1
        for v in self.axes.values():
            n *= v
        return n


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh over `devices` (default: all).  With no axes given,
    a pure data-parallel mesh over every device — the ParallelWrapper
    default of one worker per device."""
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"data": len(devices)}
    spec = MeshSpec(dict(axes))
    if spec.total() != len(devices):
        raise ValueError(
            f"Mesh axes {axes} require {spec.total()} devices, "
            f"have {len(devices)}")
    arr = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading (batch) dim split over `axis`."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
