"""Multi-host training runtime — the Spark/Aeron scale-out replacement.

Reference: `deeplearning4j-scaleout/spark/dl4j-spark*` (TrainingMaster,
SharedTrainingMaster) + the Aeron mesh under `nd4j-parameter-server-parent/`
(SURVEY.md §2.4, §3.4): a JVM cluster forms a UDP mesh, workers push
threshold-compressed gradients, a master coordinates epochs.

TPU-native inversion: the *control plane* is `jax.distributed` (one
coordinator, N processes) and the *data plane* is XLA collectives over
ICI/DCN inside the one jitted SPMD step — there is no parameter server, no
gossip, no per-batch host hop.  What remains host-side is exactly what the
reference kept host-side: process bootstrap, global-mesh formation, and the
optional compressed-gradient DCN path (`parallel.transport` +
`parallel.compression`).

`LocalLauncher` is SURVEY §4's "multi-node without a cluster" story
(Aeron-on-loopback / Spark local[*]): N OS processes on localhost, each
with its own XLA CPU client, forming one global device mesh over the
`jax.distributed` coordination service with gloo collectives.
"""
from __future__ import annotations

import os
import random
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


# Env keys the launcher sets and `initialize()` reads (the moral equivalent
# of Spark's master URL + executor id).
ENV_COORD = "DL4J_TPU_COORDINATOR"
ENV_NPROC = "DL4J_TPU_NUM_PROCESSES"
ENV_PID = "DL4J_TPU_PROCESS_ID"
ENV_CKPT = "DL4J_TPU_CHECKPOINT_DIR"
# TCP port for the hierarchical compressed gradient exchange
# (parallel.hierarchical resolves its config from these; hierarchical
# multi-host mode needs NO jax.distributed — each host runs its own local
# mesh and the gradient mesh is the only coupling)
ENV_GRAD_PORT = "DL4J_TPU_GRADIENT_PORT"


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the training cluster (reference: SharedTrainingMaster worker
    bootstrap).  Arguments default to the `DL4J_TPU_*` env the launcher
    sets; on real TPU pods, call with no args — `jax.distributed.initialize`
    auto-detects the slice topology from the TPU metadata."""
    import jax
    coordinator_address = coordinator_address or os.environ.get(ENV_COORD)
    if num_processes is None and ENV_NPROC in os.environ:
        num_processes = int(os.environ[ENV_NPROC])
    if process_id is None and ENV_PID in os.environ:
        process_id = int(os.environ[ENV_PID])
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def global_mesh(axes: Optional[Dict[str, int]] = None):
    """Mesh over every device of every process (default: pure DP)."""
    import jax
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh(axes, jax.devices())


def shard_host_local_batch(mesh, batch, axis: str = "data",
                           batch_dim: int = 0):
    """Each process contributes its *local* slice of the global batch; the
    result is one global jax.Array sharded over `axis` (the SPMD analog of
    Spark partitioning an RDD of DataSets across executors).  All processes
    must feed equal-sized local batches.  `batch_dim=1` handles stacked
    `[k, batch, ...]` fit_steps blocks (steps axis leads, sharded on the
    batch axis)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    nproc = jax.process_count()

    def place(leaf):
        leaf = np.asarray(leaf)
        spec = P(*([None] * batch_dim + [axis]
                   + [None] * (leaf.ndim - batch_dim - 1)))
        global_shape = (leaf.shape[:batch_dim]
                        + (leaf.shape[batch_dim] * nproc,)
                        + leaf.shape[batch_dim + 1:])
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), leaf, global_shape)
    return jax.tree_util.tree_map(place, batch)


def allgather_params(tree):
    """Gather a (possibly sharded) param tree to replicated host numpy on
    every process — the checkpoint/eval hook (reference: params sync back
    to the Spark driver)."""
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(tree, tiled=False)


# ---------------------------------------------------------------------------
# localhost launcher (SURVEY §4: "multi-node without a cluster")
# ---------------------------------------------------------------------------

def free_port(max_tries: int = 16) -> int:
    """Pick a currently-free localhost port.

    The OS can hand the probed port to another process between the probe
    socket closing and the caller's bind — so verify the port is still
    bindable with a second bind and re-probe when it is not, instead of
    letting the caller's server raise EADDRINUSE."""
    last_err: Optional[OSError] = None
    for _ in range(max_tries):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        try:
            with socket.socket() as v:
                v.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                v.bind(("127.0.0.1", port))
            return port
        except OSError as e:
            last_err = e
    raise OSError(
        f"free_port: no bindable port after {max_tries} probes"
    ) from last_err


def child_env(coordinator: str, num_processes: int, process_id: int,
              devices_per_process: int = 1,
              platform: str = "cpu") -> Dict[str, str]:
    """Environment for a spawned worker: force the CPU platform with K
    virtual devices and scrub any single-chip TPU plugin state inherited
    from the parent (a tunnel-attached chip cannot be shared by N
    processes; the real multi-host TPU path initializes per-host chips
    from clean slice metadata instead)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TPU_", "PJRT_", "AXON_"))
           and k != "_AXON_REGISTERED"}
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # prepend (don't clobber) so parent-supplied deps stay importable; drop
    # only the single-chip plugin's own site dir, not arbitrary paths that
    # merely contain similar substrings
    inherited = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "/.axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join([repo_root] + inherited)
    env["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{devices_per_process}")
    env[ENV_COORD] = coordinator
    env[ENV_NPROC] = str(num_processes)
    env[ENV_PID] = str(process_id)
    return env


class ElasticLocalRunner:
    """Failure detection + elastic restart (SURVEY §5.3; reference analog:
    Spark task retry around SharedTraining workers).

    Failure DETECTION is the `jax.distributed` coordination service's
    heartbeat: when any rank dies, every surviving rank is killed with a
    "peer task died" fatal within the service timeout — exactly the
    reference Aeron mesh's session-timeout role.  This runner supervises
    on top: it relaunches the whole gang after a failure, and the worker
    script resumes from its latest checkpoint (checkpoint/resume is exact,
    utils.serialization), giving crash-restart elasticity without any
    parameter-server state."""

    def __init__(self, num_processes: int, devices_per_process: int = 1,
                 platform: str = "cpu", max_restarts: int = 2,
                 backoff_base_s: float = 1.0, backoff_cap_s: float = 30.0,
                 jitter_seed: Optional[int] = None):
        self.num_processes = num_processes
        self.devices_per_process = devices_per_process
        self.platform = platform
        self.max_restarts = max_restarts
        self.restarts = 0
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # decorrelated-jitter state: a seeded PRNG (NOT wall-clock) so
        # tests are deterministic while real fleets still spread out
        self._rng = random.Random(jitter_seed)
        self._prev_backoff: Optional[float] = None
        # (attempt, kind, message-tail) per failure — kind in
        # crash | hang | peer-loss (see _classify_failure)
        self.failure_history: List[tuple] = []

    @staticmethod
    def _classify_failure(message: str) -> str:
        """Failure taxonomy: `corrupt` = a rank failed restoring a
        checkpoint whose bytes don't match their recorded checksum
        (NON-retryable — a relaunch reads the same rotten bytes);
        `hang` = a rank hit the subprocess timeout (no exit);
        `peer-loss` = a rank died because the coordination service
        reported a peer's death (secondary casualty — the real fault is
        elsewhere); `crash` = a rank exited nonzero on its own."""
        low = message.lower()
        if "checksummismatch" in low.replace(" ", "") \
                or "checksumerror" in low:
            return "corrupt"
        if "<rank timed out>" in message:
            return "hang"
        if "peer task" in low or "coordination service" in low \
                or "heartbeat" in low:
            return "peer-loss"
        return "crash"

    def backoff_s(self, attempt: int) -> float:
        """Decorrelated-jitter backoff before restart `attempt`
        (1-based): sleep ~ U(base, 3 * previous-sleep), capped.  Unlike
        plain exponential, simultaneous relaunches on one host draw
        different sleeps and stop thundering-herding the coordinator
        port; the jitter PRNG is seeded (`jitter_seed`), so no
        wall-clock dependence leaks into tests."""
        if attempt <= 1 or self._prev_backoff is None:
            self._prev_backoff = self.backoff_base_s
            return self._prev_backoff
        v = self._rng.uniform(
            self.backoff_base_s,
            max(self._prev_backoff * 3.0, self.backoff_base_s))
        self._prev_backoff = min(v, self.backoff_cap_s)
        return self._prev_backoff

    def run(self, script: str, args: Sequence[str] = (),
            timeout: float = 300.0,
            checkpoint_dir: Optional[str] = None,
            gradient_mesh: bool = False) -> List[str]:
        """Run the gang, relaunching after retryable failures.  With
        `checkpoint_dir=` every (re)launch exports it to the workers as
        `DL4J_TPU_CHECKPOINT_DIR`, so a resilience-aware worker (e.g.
        tests/mh_worker_elastic.py via `train.resilience`) resumes from
        the last committed sharded checkpoint instead of step 0.  With
        `gradient_mesh=True` every (re)launch exports a FRESH
        `DL4J_TPU_GRADIENT_PORT` for the hierarchical compressed
        exchange (a new port per attempt — the dead gang's socket may
        linger in TIME_WAIT).  A `corrupt` failure (checksum-mismatch
        restore) aborts immediately: relaunching cannot fix rotten
        bytes."""
        import time as _time
        extra_env = {} if checkpoint_dir is None \
            else {ENV_CKPT: checkpoint_dir}
        last_error: Optional[RuntimeError] = None
        for attempt in range(self.max_restarts + 1):
            launcher = LocalLauncher(self.num_processes,
                                     self.devices_per_process,
                                     self.platform)
            try:
                return launcher.run(
                    script, args, timeout, extra_env=extra_env,
                    gradient_port=free_port() if gradient_mesh else None)
            except RuntimeError as e:
                last_error = e
                kind = self._classify_failure(str(e))
                self.failure_history.append((attempt, kind,
                                             str(e)[-500:]))
                if kind == "corrupt":
                    raise RuntimeError(
                        "checkpoint restore failed with a checksum "
                        "mismatch — non-retryable (a relaunch reads the "
                        "same corrupt bytes); restore an older intact "
                        "checkpoint or repair storage") from e
                self.restarts = min(attempt + 1, self.max_restarts)
                if attempt < self.max_restarts:
                    _time.sleep(self.backoff_s(attempt + 1))
        kinds = [k for _, k, _ in self.failure_history]
        raise RuntimeError(
            f"training failed after {self.max_restarts} restarts "
            f"(failure kinds: {kinds})") from last_error

    # ------------------------------------------------------------------
    # per-worker elastic supervision (gang survives member loss)
    # ------------------------------------------------------------------
    def run_elastic(self, script: str, args: Sequence[str] = (),
                    timeout: float = 600.0,
                    checkpoint_dir: Optional[str] = None,
                    policy: str = "shrink",
                    heartbeat_s: float = 0.25,
                    failure_deadline_s: float = 2.0,
                    max_replacements: int = 2,
                    relaunch: bool = True,
                    extra_env: Optional[Dict[str, str]] = None
                    ) -> Dict[str, Tuple[int, str]]:
        """Supervise an ELASTIC gang: per-worker monitoring instead of
        whole-gang relaunch.

        Workers run `HierarchicalGradientSharing(elastic=True)` +
        `ElasticTrainer`; when a non-coordinator worker dies the gang
        itself re-forms and keeps training (shrink-and-continue), and —
        with `relaunch=True` — this supervisor launches a REPLACEMENT
        worker after a jittered backoff with ``DL4J_TPU_JOIN=1`` on the
        SAME gradient port and checkpoint dir: it joins the coordinator's
        listening socket, parks until admitted (immediately under the
        ``"block"`` policy, at the next epoch boundary under
        ``"shrink"``), and enters at a fresh generation.  Coordinator
        (rank 0) death is gang-fatal — the star has no other hub — and
        raises with rank 0's output tail; use :meth:`run` around an
        elastic worker script when whole-gang restart is the desired
        recovery for that.

        Returns ``{label: (returncode, output)}`` per worker, labels
        ``"r<rank>"`` for the initial gang and ``"r<rank>+j<n>"`` for
        replacements.  The run succeeds when rank 0 exits 0 — peer
        deaths are recorded in `failure_history`, not fatal."""
        if policy not in ("shrink", "block"):
            raise ValueError(
                f"policy must be 'shrink' or 'block', got {policy!r}")
        port = free_port()
        base_env = {
            ENV_GRAD_PORT: str(port),
            "DL4J_TPU_HEARTBEAT_S": str(heartbeat_s),
            "DL4J_TPU_FAILURE_DEADLINE_S": str(failure_deadline_s),
            "DL4J_TPU_ELASTIC_POLICY": policy,
        }
        if checkpoint_dir is not None:
            base_env[ENV_CKPT] = checkpoint_dir
        if extra_env:
            base_env.update(extra_env)
        coordinator = f"127.0.0.1:{free_port()}"   # unused by elastic
        logdir = tempfile.mkdtemp(prefix="elastic-gang-")

        def spawn(rank: int, label: str, join: bool):
            env = child_env(coordinator, self.num_processes, rank,
                            self.devices_per_process, self.platform)
            env.update(base_env)
            if join:
                env["DL4J_TPU_JOIN"] = "1"
            path = os.path.join(logdir, f"{label}.log")
            f = open(path, "w")
            p = subprocess.Popen(
                [sys.executable, "-u", script, *map(str, args)],
                stdout=f, stderr=subprocess.STDOUT, text=True, env=env)
            return (p, f, path)

        alive: Dict[str, tuple] = {}
        for rank in range(self.num_processes):
            alive[f"r{rank}"] = spawn(rank, f"r{rank}", join=False)
        results: Dict[str, Tuple[int, str]] = {}
        replacements = 0
        rank0_rc: Optional[int] = None
        deadline = time.monotonic() + timeout
        grace_deadline: Optional[float] = None

        def reap(label: str, p, f, path) -> Tuple[int, str]:
            f.close()
            with open(path, "r") as rf:
                out = rf.read()
            results[label] = (p.returncode, out)
            return results[label]

        try:
            while alive:
                now = time.monotonic()
                if now > deadline or (grace_deadline is not None
                                      and now > grace_deadline):
                    for label, (p, f, path) in alive.items():
                        p.kill()
                        p.wait()
                        rc, out = reap(label, p, f, path)
                        results[label] = (rc, out + "\n<rank timed out>")
                    alive.clear()
                    if now > deadline:
                        raise RuntimeError(
                            f"elastic gang timed out after {timeout:.0f}s"
                            f" (still running: {sorted(results)})")
                    break
                exited = [(label, t) for label, t in alive.items()
                          if t[0].poll() is not None]
                for label, (p, f, path) in exited:
                    del alive[label]
                    rc, out = reap(label, p, f, path)
                    if label == "r0":
                        rank0_rc = rc
                        if rc != 0:
                            raise RuntimeError(
                                f"elastic gang coordinator (rank 0) "
                                f"failed (rc={rc}):\n{out[-4000:]}")
                        # coordinator done: peers must wind down on
                        # their own within the failure deadline
                        grace_deadline = time.monotonic() + max(
                            failure_deadline_s * 3, 5.0)
                    elif rc != 0:
                        kind = self._classify_failure(out)
                        self.failure_history.append(
                            (replacements, kind, out[-500:]))
                        if relaunch and rank0_rc is None \
                                and replacements < max_replacements:
                            replacements += 1
                            time.sleep(self.backoff_s(replacements))
                            jl = f"{label.split('+')[0]}+j{replacements}"
                            alive[jl] = spawn(
                                int(label.split('+')[0][1:]), jl,
                                join=True)
                time.sleep(0.05)
        finally:
            for label, (p, f, path) in alive.items():
                p.kill()
                p.wait()
                reap(label, p, f, path)
        self.restarts = replacements
        return results


class LocalLauncher:
    """Spawn an SPMD worker script across N localhost processes and wait.

    Each process sees `devices_per_process` XLA CPU devices; together they
    form an `N*devices_per_process`-device global mesh.  stdout/stderr are
    captured per rank; a nonzero exit raises with the failing rank's tail.
    """

    def __init__(self, num_processes: int, devices_per_process: int = 1,
                 platform: str = "cpu"):
        self.num_processes = num_processes
        self.devices_per_process = devices_per_process
        self.platform = platform

    def run(self, script: str, args: Sequence[str] = (),
            timeout: float = 300.0,
            extra_env: Optional[Dict[str, str]] = None,
            gradient_port: Optional[int] = None) -> List[str]:
        """`gradient_port=` exports `DL4J_TPU_GRADIENT_PORT` so workers
        using hierarchical gradient sharing form their TCP gradient mesh
        on a known port (pass `free_port()` for a fresh one per launch —
        an elastic relaunch must NOT reuse a port still in TIME_WAIT)."""
        coordinator = f"127.0.0.1:{free_port()}"
        procs = []
        for rank in range(self.num_processes):
            env = child_env(coordinator, self.num_processes, rank,
                            self.devices_per_process, self.platform)
            if gradient_port is not None:
                env[ENV_GRAD_PORT] = str(gradient_port)
            if extra_env:
                env.update(extra_env)
            procs.append(subprocess.Popen(
                [sys.executable, "-u", script, *map(str, args)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        outs: List[str] = []
        failed = None
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out += "\n<rank timed out>"
                failed = failed or (rank, out, -9)
            outs.append(out)
            if p.returncode not in (0, None) and failed is None:
                failed = (rank, out, p.returncode)
        if failed is not None:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            rank, out, rc = failed
            raise RuntimeError(
                f"multihost rank {rank} failed (rc={rc}):\n{out[-4000:]}")
        return outs


# ---------------------------------------------------------------------------
# Multi-host inference (reference: ParallelInference under
# SparkDl4jMultiLayer — replica inference across executors; here one SPMD
# forward over the global mesh, each process feeding/receiving its local
# slice)
# ---------------------------------------------------------------------------

class MultiHostParallelInference:
    """Sharded inference over a multi-process global mesh: every process
    submits a host-local request batch, the forward runs once as SPMD over
    the global `data` axis, and each process receives exactly its own
    rows back (no cross-process result shipping beyond XLA's own
    collectives)."""

    def __init__(self, model, mesh=None, data_axis: str = "data"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        self.model = model
        self.mesh = mesh if mesh is not None else global_mesh()
        self.data_axis = data_axis
        repl = NamedSharding(self.mesh, P())

        def replicate(leaf):
            import numpy as _np
            leaf = _np.asarray(leaf)
            return jax.make_array_from_process_local_data(repl, leaf,
                                                          leaf.shape)
        model.params_ = jax.tree_util.tree_map(replicate, model.params_)
        model.state_ = jax.tree_util.tree_map(replicate, model.state_)

    def output(self, x_local):
        """x_local: this process's [b_local, ...] request batch (equal
        sizes across processes).  Returns this process's [b_local, ...]
        predictions as numpy."""
        xg = shard_host_local_batch(self.mesh, np.asarray(x_local),
                                    self.data_axis)
        with self.mesh:
            out = self.model.output(xg)
        if isinstance(out, (list, tuple)):   # ComputationGraph
            out = out[0]
        # one shard per distinct batch slice: meshes with a non-data axis
        # replicate each slice across that axis's devices — keep one copy
        by_start = {}
        for s in out.addressable_shards:
            by_start.setdefault(s.index[0].start or 0, s)
        shards = [by_start[k] for k in sorted(by_start)]
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
