"""Distributed training/inference (reference L6 `deeplearning4j-scaleout/`).

The reference's three distribution mechanisms — `ParallelWrapper` (single-node
multi-GPU threads + parameter averaging), `SharedTrainingMaster` (async
threshold-compressed gradient gossip over Aeron UDP), and
`ParameterAveragingTrainingMaster` (Spark aggregate) — all collapse into ONE
TPU-native mechanism: shard the batch over a `jax.sharding.Mesh` axis and let
XLA's SPMD partitioner insert all-reduces over ICI.  See SURVEY.md §2.3/§2.4.
"""
from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec, data_sharding, make_mesh, replicated)
from deeplearning4j_tpu.parallel.wrapper import (  # noqa: F401
    DynamicBatchingInference, ParallelInference, ParallelWrapper)
from deeplearning4j_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules, shard_model_params)
from deeplearning4j_tpu.parallel.zero import (  # noqa: F401
    Zero1Transform, build_plans, disable_zero1, enable_zero1,
    opt_state_bytes_per_replica)
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply, sequential_apply, stack_stage_params)
from deeplearning4j_tpu.parallel.multihost import (  # noqa: F401
    ElasticLocalRunner, LocalLauncher)
from deeplearning4j_tpu.parallel.hierarchical import (  # noqa: F401
    HierarchicalAllReduce, HierarchicalGradientSharing)
from deeplearning4j_tpu.parallel.composed import (  # noqa: F401
    ComposedParallel)
from deeplearning4j_tpu.parallel.checkpoint import (  # noqa: F401
    ChecksumError, load_model_sharded, load_sharded, read_metadata,
    save_model_sharded, save_sharded, verify_checkpoint)
