"""Composed 3D parallelism: dp x tp x pp in ONE train step.

Reference: the reference composes its two distribution mechanisms in a
single job — Spark orchestration over nodes with ParallelWrapper + Aeron
gradient sharing inside each node (`dl4j-spark-parameterserver/`,
SURVEY.md §3.4).  The TPU-idiomatic form of that composed story is one
mesh with three axes and one jitted step:

- ``data``  — batch sharding, gradient psum (the DP role)
- ``model`` — Megatron-style tensor parallelism for the MLP
  (column-parallel W1, row-parallel W2) *with sequence parallelism on
  the same axis*: activations stay sequence-sharded, an ``all_gather``
  materializes the full sequence only for the TP matmuls and a
  ``psum_scatter`` returns partial sums to sequence shards — and the
  attention itself runs as a **ring** over this axis
  (`ring_attention`), so the long-context path lives inside the tp
  group (scaling-book §sequence-parallelism).
- ``pipe``  — GPipe stage parallelism: homogeneous transformer stages
  with params stacked on a leading [S, ...] axis, microbatches streamed
  through a scan of compute + ``ppermute`` ticks (same schedule as
  `pipeline.pipeline_apply`, inlined here so the block can use
  model-axis collectives).

`composed_oracle` is the single-device semantics the sharded step must
match bit-for-bit up to fp tolerance — the correctness contract the
multihost test and the dryrun both check.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import shard_map

from deeplearning4j_tpu.parallel.ring_attention import ring_attention


def init_stage_params(rng, n_stages: int, d_model: int, n_heads: int,
                      d_ff: int) -> Dict[str, jnp.ndarray]:
    """Per-stage transformer-block params stacked on a leading [S, ...]
    axis (the homogeneous-stage contract of the pipeline)."""
    import numpy as np
    def g(*s, scale=0.2):
        return jnp.asarray(rng.randn(*s).astype(np.float32) * scale)
    S, D, F = n_stages, d_model, d_ff
    return {
        "wqkv": g(S, D, 3 * D), "wo": g(S, D, D),
        "w1": g(S, D, F), "w2": g(S, F, D),
        "ln1_g": jnp.ones((S, D), jnp.float32),
        "ln1_b": jnp.zeros((S, D), jnp.float32),
        "ln2_g": jnp.ones((S, D), jnp.float32),
        "ln2_b": jnp.zeros((S, D), jnp.float32),
    }


def stage_specs(tp_axis: str = "model", pipe_axis: str = "pipe"):
    """PartitionSpecs for the stacked stage tree: every leaf is sharded
    on the stage axis; the MLP weights additionally shard on the tp axis
    (column-parallel W1 on its output dim, row-parallel W2 on its input
    dim).  Attention weights replicate across tp — the tp axis carries
    the sequence for attention (ring), not the heads."""
    return {
        "wqkv": P(pipe_axis, None, None), "wo": P(pipe_axis, None, None),
        "w1": P(pipe_axis, None, tp_axis), "w2": P(pipe_axis, tp_axis,
                                                   None),
        "ln1_g": P(pipe_axis, None), "ln1_b": P(pipe_axis, None),
        "ln2_g": P(pipe_axis, None), "ln2_b": P(pipe_axis, None),
    }


def _ln(x, g, b, eps=1e-5):
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.var(x, -1, keepdims=True)
    return (x - m) / jnp.sqrt(v + eps) * g + b


def _split_heads(x, n_heads):
    b, t, d = x.shape
    return x.reshape(b, t, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, t, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * dh)


def block_sp(p, h, n_heads: int, tp_axis: str):
    """One transformer block on a sequence-sharded activation
    [mb, T_local, D]; runs INSIDE shard_map with `tp_axis` manual."""
    # attention sublayer: ring over the tp axis (sequence-parallel)
    x = _ln(h, p["ln1_g"], p["ln1_b"])
    qkv = x @ p["wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    att = ring_attention(_split_heads(q, n_heads),
                         _split_heads(k, n_heads),
                         _split_heads(v, n_heads),
                         axis_name=tp_axis, causal=True)
    h = h + _merge_heads(att) @ p["wo"]
    # MLP sublayer: Megatron sequence-parallel TP — gather the sequence
    # for the sharded matmuls, scatter the partial sums back
    x = _ln(h, p["ln2_g"], p["ln2_b"])
    full = jax.lax.all_gather(x, tp_axis, axis=1, tiled=True)
    u = jax.nn.relu(full @ p["w1"])          # [mb, T, F_local]
    part = u @ p["w2"]                       # [mb, T, D] partial sum
    mlp = jax.lax.psum_scatter(part, tp_axis, scatter_dimension=1,
                               tiled=True)   # [mb, T_local, D]
    return h + mlp


def block_oracle(p, h, n_heads: int):
    """Single-device semantics of `block_sp` (full sequence)."""
    x = _ln(h, p["ln1_g"], p["ln1_b"])
    qkv = x @ p["wqkv"]
    q, k, v = (_split_heads(t, n_heads) for t in jnp.split(qkv, 3, -1))
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k,
                   preferred_element_type=jnp.float32)
    T = q.shape[2]
    causal = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(causal[None, None], s, -1e30)
    att = jnp.einsum("bhqk,bhkd->bhqd",
                     jax.nn.softmax(s, -1).astype(v.dtype), v)
    h = h + _merge_heads(att) @ p["wo"]
    x = _ln(h, p["ln2_g"], p["ln2_b"])
    return h + jax.nn.relu(x @ p["w1"]) @ p["w2"]


def composed_apply(stacked, x, mesh: Mesh, n_heads: int,
                   data_axis: str = "data", tp_axis: str = "model",
                   pipe_axis: str = "pipe", num_microbatches=None,
                   remat: bool = False):
    """Forward through S pipelined sequence-parallel TP blocks.

    x: [B, T, D] with B sharded over `data_axis` and T over `tp_axis`.
    stacked: `init_stage_params` tree (leaves [S, ...]).
    `remat=True` wraps the per-tick block in `jax.checkpoint` — at real
    scale the pipeline holds M+S-1 ticks of activations live through the
    backward pass, exactly where rematerialization pays (HBM for FLOPs).
    Returns [B, T, D] with the same sharding.
    """
    S = mesh.shape[pipe_axis]
    M = num_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} % {M} microbatches != 0")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    specs = stage_specs(tp_axis, pipe_axis)
    in_x = P(None, data_axis, tp_axis, None)     # [M, mb, T, D]

    block = block_sp
    if remat:
        block = jax.checkpoint(block_sp, static_argnums=(2, 3))

    @partial(shard_map, mesh=mesh, in_specs=(specs, in_x),
             out_specs=in_x, check_vma=False)
    def run(params, xs_loc):
        p_local = jax.tree_util.tree_map(lambda l: l[0], params)
        stage = jax.lax.axis_index(pipe_axis)
        zeros = jnp.zeros_like(xs_loc[0])

        def tick(carry, t):
            incoming, outputs = carry
            inject = xs_loc[jnp.minimum(t, M - 1)]
            act_in = jnp.where(stage == 0, inject, incoming)
            y = block(p_local, act_in, n_heads, tp_axis)
            out_idx = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y,
                                   outputs[jnp.maximum(out_idx, 0)]),
                jnp.maximum(out_idx, 0), 0)
            passed = jax.lax.ppermute(
                y, pipe_axis, [(i, (i + 1) % S) for i in range(S)])
            return (passed, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (zeros, jnp.zeros_like(xs_loc)), jnp.arange(M + S - 1))
        contrib = jnp.where(stage == S - 1, outputs,
                            jnp.zeros_like(outputs))
        # stay [M, mb_local, T_local, D]: the microbatch axis must merge
        # GLOBALLY (a local merge would interleave the data shards)
        return jax.lax.psum(contrib, pipe_axis)

    return run(stacked, xs).reshape(B, *x.shape[1:])


def composed_oracle(stacked, x, n_heads: int):
    """Sequential single-device semantics of `composed_apply`."""
    S = jax.tree_util.tree_leaves(stacked)[0].shape[0]

    def body(h, i):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked)
        return block_oracle(p_i, h, n_heads), None

    h, _ = jax.lax.scan(body, x, jnp.arange(S))
    return h


def composed_train_step(mesh: Mesh, n_heads: int, lr: float = 0.1,
                        remat: bool = False, **axes):
    """Build the jitted full train step: forward through the 3D-parallel
    stack, MSE loss, grads, SGD update.  Returns step(params, x, y) ->
    (new_params, loss)."""

    @jax.jit
    def step(params, x, y):
        def loss_fn(p):
            out = composed_apply(p, x, mesh, n_heads, remat=remat,
                                 **axes)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(lambda a, g: a - lr * g, params,
                                     grads)
        return new, loss

    return step


class ComposedParallel:
    """Facade over the 3D-parallel train step with optional hierarchical
    compressed gradient sharing across hosts.

    Without sharing: `fit_batch` is `composed_train_step` (one jitted
    dp×tp×pp step).  With a `HierarchicalGradientSharing` config the step
    splits the same way the nn models' does — a jitted grad half (all
    intra-mesh collectives included), the host-side compressed DCN
    exchange (`parallel.hierarchical`), and a jitted apply half — so a
    gang of these (one per host, each on its own local 3D mesh) trains
    with threshold-int streams as the only cross-host traffic."""

    def __init__(self, mesh: Mesh, n_heads: int, lr: float = 0.1,
                 remat: bool = False, gradient_sharing=None, **axes):
        self.mesh = mesh
        self.n_heads = n_heads
        self.lr = lr
        self._sharing = None
        if gradient_sharing is not None:
            from deeplearning4j_tpu.parallel.hierarchical import (
                HierarchicalAllReduce, HierarchicalGradientSharing)
            self._sharing = (gradient_sharing
                             if isinstance(gradient_sharing,
                                           HierarchicalAllReduce)
                             else HierarchicalAllReduce(gradient_sharing))
        self._step = composed_train_step(mesh, n_heads, lr=lr, remat=remat,
                                         **axes)

        @jax.jit
        def grad_fn(params, x, y):
            def loss_fn(p):
                out = composed_apply(p, x, mesh, n_heads, remat=remat,
                                     **axes)
                return jnp.mean((out - y) ** 2)
            return jax.value_and_grad(loss_fn)(params)

        @jax.jit
        def apply_fn(params, grads):
            return jax.tree_util.tree_map(lambda a, g: a - lr * g,
                                          params, grads)

        self._grad_fn = grad_fn
        self._apply_fn = apply_fn

    @property
    def gradient_sharing(self):
        return self._sharing

    def fit_batch(self, params, x, y):
        """(params, loss) after one step; with sharing active the grads
        cross the compressed DCN hop between the two jitted halves."""
        if self._sharing is None:
            with self.mesh:
                return self._step(params, x, y)
        with self.mesh:
            loss, grads = self._grad_fn(params, x, y)
        combined = self._sharing.exchange(grads)
        with self.mesh:
            return self._apply_fn(params, combined), loss

    def close(self) -> None:
        if self._sharing is not None:
            self._sharing.close()


def composed_train_steps(mesh: Mesh, n_heads: int, lr: float = 0.1,
                         remat: bool = False, **axes):
    """Fused k-step form of `composed_train_step`: the fused-dispatch
    lever (utils/scan_fit.py) composed WITH 3D parallelism — k
    dp×tp×pp steps (pipeline ticks, TP collectives, DP psum all inside)
    run as one `lax.scan` dispatch.  `xs`/`ys` carry a leading steps
    axis; returns (params, per-step losses)."""

    @jax.jit
    def steps(params, xs, ys):
        def tick(p, batch):
            x, y = batch

            def loss_fn(q):
                out = composed_apply(q, x, mesh, n_heads, remat=remat,
                                     **axes)
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            return jax.tree_util.tree_map(lambda a, g: a - lr * g, p,
                                          grads), loss

        return jax.lax.scan(tick, params, (xs, ys))

    return steps
