"""Pipeline parallelism: GPipe-style microbatched stage parallelism.

Reference scope: the reference has no pipeline engine (SURVEY §2.3 marks PP
optional — its scale-out is data-parallel only), so this is a TPU-native
extension following the public scaling-book recipe: place S identical
stages on S devices along a `pipe` mesh axis, stream M microbatches
through a `lax.scan` of compute+`ppermute` ticks under `shard_map`.

Key properties:
- SPMD-uniform: every device runs the same block_fn every tick (bubble
  ticks compute on garbage and are masked out), so one XLA program serves
  all stages.
- Differentiable: `jax.grad` through the scan/ppermute yields the reverse
  pipeline schedule automatically — no hand-written backward pass.
- Composable: the `pipe` axis is one axis of a larger mesh, so PP stacks
  with DP/TP axes the usual way.

Constraint (same as every SPMD pipeline): stages must be HOMOGENEOUS — a
stack of identical blocks with per-stage parameters stacked on a leading
[S, ...] axis (the transformer-encoder shape).  Heterogeneous prefixes
(embeddings, heads) run outside the pipelined region.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from deeplearning4j_tpu.parallel.mesh import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params: list):
    """[params_tree per stage] -> one tree with leaves stacked on axis 0."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(block_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, axis: str = "pipe",
                   num_microbatches: Optional[int] = None) -> jnp.ndarray:
    """Run `x` through S pipelined stages of `block_fn`.

    block_fn(stage_params, microbatch) -> microbatch (same shape).
    stacked_params: leaves [S, ...], S == mesh.shape[axis].
    x: [B, ...]; B must divide by num_microbatches (default S).

    Schedule: M + S - 1 ticks; at tick t stage s processes microbatch
    t - s (when in range).  Activations hop stages via ppermute each tick
    — the ICI-neighbor transfer pattern.
    """
    S = mesh.shape[axis]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != S:
        raise ValueError(
            f"{n_stages} stacked stages but mesh axis '{axis}' has {S} "
            "devices — stage count must equal the pipe-axis size")
    M = num_microbatches or S
    B = x.shape[0]
    if B % M:
        raise ValueError(f"Batch {B} not divisible by {M} microbatches")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_spec, P()), out_specs=P(),
             check_vma=False)
    def run(params, xs_rep):
        # params leaves arrive as [1, ...] local slices -> this stage's tree
        p_local = jax.tree_util.tree_map(lambda l: l[0], params)
        stage = jax.lax.axis_index(axis)
        zeros = jnp.zeros_like(xs_rep[0])

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 injects microbatch t (or garbage past the end)
            inject = xs_rep[jnp.minimum(t, M - 1)]
            act_in = jnp.where(stage == 0, inject, incoming)
            y = block_fn(p_local, act_in)
            # last stage emits microbatch t-(S-1) at tick t
            out_idx = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1, out_idx >= 0)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, outputs[
                    jnp.maximum(out_idx, 0)]),
                jnp.maximum(out_idx, 0), 0)
            # hand activations to the next stage (ring; wrap is harmless —
            # stage 0 overwrites with injection)
            passed = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)])
            return (passed, outputs), None

        outputs0 = jnp.zeros_like(xs_rep)
        (final_in, outputs), _ = jax.lax.scan(
            tick, (zeros, outputs0), jnp.arange(M + S - 1))
        # only the last stage holds real outputs; share them with everyone
        # (psum over one-hot contribution keeps the program SPMD-uniform)
        contrib = jnp.where(stage == S - 1, outputs,
                            jnp.zeros_like(outputs))
        outputs = jax.lax.psum(contrib, axis)
        return outputs.reshape(B, *x.shape[1:])

    return run(stacked_params, xs)


def sequential_apply(block_fn: Callable, stacked_params,
                     x: jnp.ndarray) -> jnp.ndarray:
    """The semantics pipeline_apply must match: apply the S stages in
    order, single-device (the correctness oracle and the S=1 fallback)."""
    S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def body(h, i):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked_params)
        return block_fn(p_i, h), None

    h, _ = jax.lax.scan(body, x, jnp.arange(S))
    return h
