"""Hierarchical compressed cross-host gradient all-reduce.

Reference: ParallelWrapper's Aeron threshold GradientSharing (SURVEY.md
§3.4) at DCN scale.  A TPU pod has two very different links: ICI inside a
slice (fast — XLA all-reduce belongs there, full precision, inside the
compiled step) and DCN between slices/hosts (slow — worth compressing).
The hierarchy:

    1. ICI phase (compiled "grad half"): every host's local mesh computes
       data-parallel gradients and reduces them over ICI exactly as the
       single-host step does.  Output: ONE gradient tree per host.
    2. DCN phase (this module, host-side): each host threshold-encodes its
       ICI-reduced tree (error-feedback residuals carried per host by the
       codecs), ships the sparse int32 streams over `TcpGradientMesh`,
       decodes every peer's stream, and sums.
    3. apply phase (compiled "apply half"): the summed (then averaged —
       `combine="mean"`) gradient feeds the normal updater loop, donated
       buffers and all.

Convergence parity comes from the error feedback: what a threshold cut
this step, the residual re-emits a later step, so the *sum over steps* of
applied gradients tracks the true sum (the reference's delta semantics).

The split-step threading lives in `nn/multilayer.py` / `nn/graph.py`
(`set_gradient_sharing`); this module owns the config, the host-side
exchange runtime, and the metric recording.  `world == 1` is fully
supported WITHOUT sockets — the encode/decode/residual path still runs,
which is what the in-process convergence tests and the single-host
default exercise.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

import jax
import numpy as np

ENV_PID = "DL4J_TPU_PROCESS_ID"
ENV_NPROC = "DL4J_TPU_NUM_PROCESSES"
ENV_GRAD_PORT = "DL4J_TPU_GRADIENT_PORT"
ENV_GRAD_HOST = "DL4J_TPU_GRADIENT_HOST"
ENV_HEARTBEAT = "DL4J_TPU_HEARTBEAT_S"
ENV_DEADLINE = "DL4J_TPU_FAILURE_DEADLINE_S"
ENV_JOIN = "DL4J_TPU_JOIN"

PyTree = Any


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return default if v in (None, "") else int(v)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class HierarchicalGradientSharing:
    """Config for the DCN-phase gradient exchange.

    `rank`/`world`/`port`/`host` default to the `DL4J_TPU_*` env the
    multihost launchers already export (resolved at `resolve()` time, not
    import time), so a worker script just passes the config through.
    `compressed=False` selects the dense f32 wire path — same topology,
    no codec — which is the bench's A/B baseline.  `combine="mean"`
    divides the cross-host sum by `world`, matching the global-mean
    gradient a single SPMD mesh over all devices would produce;
    `combine="sum"` keeps the reference accumulator's raw-sum semantics.
    """

    threshold: float = 1e-3
    adaptive_target_density: float = 1e-2
    compressed: bool = True
    combine: str = "mean"             # "mean" | "sum"
    rank: Optional[int] = None        # default: env, else 0
    world: Optional[int] = None       # default: env, else 1
    port: Optional[int] = None        # default: env, else 49152
    host: Optional[str] = None        # default: env, else 127.0.0.1
    timeout: float = 60.0
    # elastic gang membership (PR 9): heartbeat failure detection +
    # generation-fenced reformation instead of fail-stop
    elastic: bool = False
    heartbeat_interval_s: Optional[float] = None   # env, else 0.25
    failure_deadline_s: Optional[float] = None     # env, else 5.0
    join: Optional[bool] = None       # env DL4J_TPU_JOIN, else False

    def __post_init__(self):
        if self.combine not in ("mean", "sum"):
            raise ValueError(f"combine must be 'mean' or 'sum', "
                             f"got {self.combine!r}")

    def resolve(self) -> "HierarchicalGradientSharing":
        """Fill rank/world/port/host (and the elastic knobs) from the
        launcher env."""
        return dataclasses.replace(
            self,
            rank=self.rank if self.rank is not None
            else _env_int(ENV_PID, 0),
            world=self.world if self.world is not None
            else _env_int(ENV_NPROC, 1),
            port=self.port if self.port is not None
            else _env_int(ENV_GRAD_PORT, 49152),
            host=self.host if self.host is not None
            else os.environ.get(ENV_GRAD_HOST, "127.0.0.1"),
            heartbeat_interval_s=self.heartbeat_interval_s
            if self.heartbeat_interval_s is not None
            else _env_float(ENV_HEARTBEAT, 0.25),
            failure_deadline_s=self.failure_deadline_s
            if self.failure_deadline_s is not None
            else _env_float(ENV_DEADLINE, 5.0),
            join=self.join if self.join is not None
            else _env_bool(ENV_JOIN, False))


class HierarchicalAllReduce:
    """The host-side DCN exchange runtime one model instance owns.

    Lazily builds the per-leaf codecs (from the first gradient tree it
    sees — that fixes leaf count/shapes) and the TCP mesh (skipped when
    `world == 1`).  `exchange(grads)` is the whole DCN phase: device →
    host, encode (or dense-pack), all-gather, decode, sum, combine, and
    metric recording.  NOT thread-safe — one exchange per model at a
    time, which the per-step training loop guarantees.
    """

    def __init__(self, config: HierarchicalGradientSharing):
        self.config = config.resolve()
        self._exchange = None          # CompressedGradientExchange
        self._mesh = None              # TcpGradientMesh | ElasticGradientMesh
        self._ready = False
        self._instr = None
        self._template = None          # gradient tree shape template
        self._resume_step_provider = None
        self._last_wire_bytes = 0
        self._last_ratio = 1.0
        self.exchanges = 0

    @property
    def rank(self) -> int:
        # elastic reformation can remap the rank in place
        return self._mesh.rank if self._mesh is not None \
            else self.config.rank

    @property
    def world(self) -> int:
        return self._mesh.world if self._mesh is not None \
            else self.config.world

    @property
    def mesh(self):
        return self._mesh

    def set_resume_step_provider(self, fn) -> None:
        """Coordinator-side callable returning the checkpoint step every
        member must resume from after a reformation (wired by
        ElasticTrainer to `CheckpointManager.latest_step`)."""
        self._resume_step_provider = fn
        if self._mesh is not None and hasattr(self._mesh,
                                              "resume_step_provider"):
            self._mesh.resume_step_provider = fn

    def _ensure(self, grads: PyTree) -> None:
        if self._ready:
            return
        from deeplearning4j_tpu.monitor.instrument import comms_instruments
        self._instr = comms_instruments()
        self._template = jax.tree_util.tree_map(
            lambda g: np.zeros(np.shape(g), np.float32), grads)
        if self.config.compressed:
            self._build_exchange()
        if self.config.elastic:
            from deeplearning4j_tpu.parallel.transport import (
                ElasticGradientMesh, GangReformed)
            self._mesh = ElasticGradientMesh(
                rank=self.config.rank, world=self.config.world,
                port=self.config.port, host=self.config.host,
                timeout=self.config.timeout,
                heartbeat_interval=self.config.heartbeat_interval_s,
                failure_deadline=self.config.failure_deadline_s,
                join=bool(self.config.join),
                resume_step_provider=self._resume_step_provider)
            if self.config.join and self._mesh.join_info is not None:
                # a replacement worker learns its resume point only at
                # admission — surface it as a reformation so the trainer
                # restores the SAME checkpoint the survivors rewound to
                # (the pre-join restore may be stale by now)
                self._ready = True
                raise GangReformed({
                    "generation": self._mesh.generation,
                    "world": self._mesh.world,
                    "rank": self._mesh.rank,
                    "rank_map": {self._mesh.rank: self._mesh.rank},
                    "lost": [], "cause": "join",
                    "resume_step": self._mesh.join_info.get(
                        "resume_step", 0)})
        elif self.config.world > 1:
            from deeplearning4j_tpu.parallel.transport import TcpGradientMesh
            self._mesh = TcpGradientMesh(
                rank=self.config.rank, world=self.config.world,
                port=self.config.port, host=self.config.host,
                timeout=self.config.timeout)
        self._ready = True

    def _build_exchange(self) -> None:
        from deeplearning4j_tpu.parallel.compression import (
            CompressedGradientExchange)
        self._exchange = CompressedGradientExchange(
            self._template, threshold=self.config.threshold,
            adaptive_target_density=self.config.adaptive_target_density)

    def rebuild(self, flush_residuals: bool = False) -> None:
        """Reset codec state after a gang reformation.

        Default (`flush_residuals=False`) builds FRESH codecs — zero
        residuals, thresholds back at the configured start — which is
        what checkpoint-rewind resume requires: the parked residual and
        the adapted thresholds were accumulated from steps the rewind
        discards, and every survivor resetting identically is what makes
        the resumed run bitwise-match a clean run from that checkpoint.
        `flush_residuals=True` instead carries the old error-feedback
        mass into the new codecs (forward, non-rewind semantics — no
        gradient silently lost when membership changes without a
        rewind)."""
        if self._template is None or not self.config.compressed:
            return
        old = self._exchange
        self._build_exchange()
        if flush_residuals and old is not None:
            self._exchange.flush_into(old.residuals())

    def exchange(self, grads: PyTree) -> PyTree:
        """ICI-reduced gradient tree in, DCN-combined tree out (numpy
        leaves — the apply half re-places them on device)."""
        t0 = time.perf_counter()
        host_grads = jax.tree_util.tree_map(
            lambda g: np.asarray(g, np.float32), grads)
        self._ensure(host_grads)
        mesh = self._mesh
        sent0 = mesh.bytes_sent + mesh.bytes_received if mesh else 0
        if self.config.compressed:
            total = self._exchange_compressed(host_grads)
            ratio = self._last_ratio
        else:
            total = self._exchange_dense(host_grads)
            ratio = 1.0
        if mesh is not None:
            self._last_wire_bytes = (mesh.bytes_sent + mesh.bytes_received
                                     - sent0)
        w = self.world                 # dynamic under elastic membership
        if self.config.combine == "mean" and w > 1:
            inv = np.float32(1.0 / w)
            total = jax.tree_util.tree_map(lambda a: a * inv, total)
        self.exchanges += 1
        self._instr.record_exchange(
            time.perf_counter() - t0, self._last_wire_bytes, ratio,
            self.config.compressed)
        return total

    def _exchange_compressed(self, host_grads: PyTree) -> PyTree:
        from deeplearning4j_tpu.parallel.transport import (pack_streams,
                                                           unpack_streams)
        ex = self._exchange
        streams = ex.encode(host_grads)
        self._last_ratio = ex.compression_ratio(streams)
        if self._mesh is None:
            # single host: the codec round-trip (residual semantics
            # included) still runs — convergence behavior matches a
            # 1-host member of a larger mesh
            self._last_wire_bytes = sum(4 * (len(s) + 1) for s in streams)
            return ex.decode(streams, ex.thresholds())
        payload = pack_streams(streams, ex.thresholds())
        total = None
        for peer_payload in self._mesh.allgather(payload):
            peer_streams, peer_thr = unpack_streams(peer_payload)
            dense = ex.decode(peer_streams, peer_thr)
            total = dense if total is None else jax.tree_util.tree_map(
                lambda a, b: a + b, total, dense)
        return total

    def _exchange_dense(self, host_grads: PyTree) -> PyTree:
        if self._mesh is None:
            leaves = jax.tree_util.tree_leaves(host_grads)
            self._last_wire_bytes = sum(4 * l.size for l in leaves)
            return host_grads
        from deeplearning4j_tpu.parallel.compression import allreduce_dense
        return allreduce_dense(self._mesh, host_grads)

    # ---- elastic joiner admission passthroughs (coordinator only) ----
    def has_pending_joiner(self) -> bool:
        return self._mesh is not None and \
            getattr(self._mesh, "has_pending_joiner", lambda: False)()

    def wait_for_joiner(self, timeout: float) -> bool:
        if self._mesh is None or not hasattr(self._mesh,
                                             "wait_for_joiner"):
            return False
        return self._mesh.wait_for_joiner(timeout)

    def admit_joiners(self, resume_step: int):
        """Admit parked replacement workers (bumps the generation; the
        peers raise GangReformed).  Returns the reform info dict or None.
        The caller (ElasticTrainer) rebuilds codecs and restores the
        checkpoint inline on the coordinator."""
        if self._mesh is None or not hasattr(self._mesh, "admit_joiners"):
            return None
        return self._mesh.admit_joiners(resume_step)

    def request_evict(self, rank: int, resume_step=None,
                      cause: str = "shrink"):
        """Coordinated shrink: evict `rank` at an agreed resume step (the
        pod arbiter's scale-to-serving path).  Returns the reform info
        dict or None when not elastic."""
        if self._mesh is None or not hasattr(self._mesh, "request_evict"):
            return None
        return self._mesh.request_evict(rank, resume_step=resume_step,
                                        cause=cause)

    def stats(self) -> dict:
        """Last-exchange numbers (what BENCH_comms.json aggregates)."""
        mesh = self._mesh
        out = {
            "rank": self.rank,
            "world": self.world,
            "compressed": self.config.compressed,
            "exchanges": self.exchanges,
            "last_wire_bytes": self._last_wire_bytes,
            "last_compression_ratio": self._last_ratio,
            "bytes_sent_total": mesh.bytes_sent if mesh else 0,
            "bytes_received_total": mesh.bytes_received if mesh else 0,
        }
        if self.config.elastic and mesh is not None:
            out["generation"] = mesh.generation
            out["reformations"] = mesh.reformations
            out["stale_frames"] = mesh.stale_frames
        return out

    def close(self) -> None:
        if self._mesh is not None:
            self._mesh.close()
            self._mesh = None
        self._ready = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
