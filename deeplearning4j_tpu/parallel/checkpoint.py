"""Sharded multi-host checkpointing (orbax-style).

Reference role: `ModelSerializer` + the Spark `TrainingMaster`'s
driver-side model sync (SURVEY.md §5.4) — but at real multi-host scale a
single process cannot (and must not) gather the model: every process
writes exactly the shards it owns, and restore re-assembles each leaf for
whatever mesh the *new* job uses, which may differ from the mesh at save
time (elastic resume / topology change).

Format (one checkpoint = one directory, assumed on storage every process
can reach — shared FS or fused GCS mount on real pods):

- ``shards-{rank}.npz``  — per-process chunk payloads.  Each process
  writes only the addressable shards with ``replica_id == 0``, so every
  global chunk lands exactly once across the job.
- ``index-{rank}.json``  — for each written chunk: the flat leaf id and
  the global index window ``[[start, stop], ...]`` it covers.
- ``manifest.json``      — written by rank 0 AFTER a global barrier: flat
  leaf specs (global shape/dtype), tree structure token, user metadata
  (step counters, config).  Its presence commits the checkpoint — a
  loader never sees a torn write (the reference's CheckpointListener
  tmp-and-rename ritual, distributed).

Resharding on load: for every addressable shard the NEW sharding wants,
the loader assembles the window from whichever saved chunks intersect it
— restoring a dp=4 checkpoint into a dp=2×tp=2 job (or into one process)
is the same code path.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

MANIFEST = "manifest.json"


class ChecksumError(ValueError):
    """A chunk's payload does not match the crc32 recorded in its index —
    the checkpoint bytes were corrupted after commit (bit rot, torn copy).
    Non-retryable: restoring the same bytes again cannot succeed; fall
    back to an older intact checkpoint instead."""


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _window(index, shape) -> List[List[int]]:
    """jax shard .index (tuple of slices) -> [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(directory: str, tree: Any,
                 metadata: Optional[Dict[str, Any]] = None) -> None:
    """Write `tree` (params / opt state / anything pytree) as a sharded
    checkpoint.  Every process participates; host numpy leaves are treated
    as replicated (rank 0 writes them)."""
    import jax
    from jax.experimental import multihost_utils

    rank = jax.process_index()
    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)

    chunks: Dict[str, np.ndarray] = {}
    index: List[Dict[str, Any]] = []
    specs = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            specs.append({"shape": list(leaf.shape),
                          "dtype": str(leaf.dtype)})
            for j, shard in enumerate(leaf.addressable_shards):
                if shard.replica_id != 0:
                    continue
                key = f"leaf{i}_chunk{j}"
                chunks[key] = np.asarray(shard.data)
                index.append({"leaf": i, "key": key,
                              "window": _window(shard.index, leaf.shape),
                              "crc32": _crc32(chunks[key])})
        else:
            arr = np.asarray(leaf)
            specs.append({"shape": list(arr.shape),
                          "dtype": str(arr.dtype)})
            if rank == 0:
                key = f"leaf{i}_chunk0"
                chunks[key] = arr
                index.append({"leaf": i, "key": key,
                              "window": _window(
                                  (slice(None),) * arr.ndim, arr.shape),
                              "crc32": _crc32(arr)})

    np.savez(os.path.join(directory, f"shards-{rank}.npz"), **chunks)
    with open(os.path.join(directory, f"index-{rank}.json"), "w") as f:
        json.dump(index, f)

    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(f"ckpt-save:{directory}")
    if rank == 0:
        # Drop leftovers from a previous save with MORE ranks (elastic
        # resume into the same directory): without this, a loader that
        # globbed every index-*/shards-* file would merge stale chunks in
        # and could overwrite fresh parameters with old ones.
        n_now = jax.process_count()
        for name in os.listdir(directory):
            stale = None
            if name.startswith("index-") and name.endswith(".json"):
                stale = int(name[len("index-"):-len(".json")])
            elif name.startswith("shards-") and name.endswith(".npz"):
                stale = int(name[len("shards-"):-len(".npz")])
            if stale is not None and stale >= n_now:
                os.remove(os.path.join(directory, name))
        manifest = {"format": "deeplearning4j_tpu.sharded.v1",
                    "num_ranks_at_save": jax.process_count(),
                    "leaves": specs,
                    "treedef": str(treedef),
                    "metadata": metadata or {}}
        tmp = os.path.join(directory, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(directory, MANIFEST))
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(f"ckpt-commit:{directory}")


def read_metadata(directory: str) -> Dict[str, Any]:
    with open(os.path.join(directory, MANIFEST)) as f:
        return json.load(f)["metadata"]


class _ChunkStore:
    """Lazy reader over every rank's chunk files at save time."""

    def __init__(self, directory: str, num_ranks: Optional[int] = None):
        self.directory = directory
        self.by_leaf: Dict[int, List[Dict[str, Any]]] = {}
        self._files: Dict[int, Any] = {}
        self._verified: set = set()
        for name in sorted(os.listdir(directory)):
            if not (name.startswith("index-") and name.endswith(".json")):
                continue
            rank = int(name[len("index-"):-len(".json")])
            if num_ranks is not None and rank >= num_ranks:
                continue  # stale leftover from a larger previous job
            with open(os.path.join(directory, name)) as f:
                for entry in json.load(f):
                    entry = dict(entry, rank=rank)
                    self.by_leaf.setdefault(entry["leaf"], []).append(entry)

    def _file(self, rank: int):
        if rank not in self._files:
            self._files[rank] = np.load(
                os.path.join(self.directory, f"shards-{rank}.npz"))
        return self._files[rank]

    def _chunk(self, entry: Dict[str, Any]) -> np.ndarray:
        """One chunk payload, crc32-verified against its index entry (each
        distinct chunk is verified once; checkpoints written before crc32
        landed in the index load unverified)."""
        data = self._file(entry["rank"])[entry["key"]]
        want = entry.get("crc32")
        ident = (entry["rank"], entry["key"])
        if want is not None and ident not in self._verified:
            got = _crc32(data)
            if got != int(want):
                raise ChecksumError(
                    f"checksum mismatch for chunk {entry['key']} of rank "
                    f"{entry['rank']} in {self.directory}: index records "
                    f"crc32={int(want):#010x}, payload hashes {got:#010x} "
                    "— checkpoint bytes corrupted after commit")
            self._verified.add(ident)
        return data

    def assemble(self, leaf: int, window: Sequence[Sequence[int]],
                 dtype) -> np.ndarray:
        """Assemble the global index window [[start, stop], ...] of a leaf
        from every intersecting saved chunk (the resharding core)."""
        shape = tuple(stop - start for start, stop in window)
        out = np.empty(shape, dtype)
        filled = np.zeros(shape, bool)
        for entry in self.by_leaf.get(leaf, []):
            cw = entry["window"]
            inter = [(max(a0, b0), min(a1, b1))
                     for (a0, a1), (b0, b1) in zip(window, cw)]
            if any(lo >= hi for lo, hi in inter):
                continue
            data = self._chunk(entry)
            src = tuple(slice(lo - c0, hi - c0)
                        for (lo, hi), (c0, _) in zip(inter, cw))
            dst = tuple(slice(lo - w0, hi - w0)
                        for (lo, hi), (w0, _) in zip(inter, window))
            out[dst] = data[src]
            filled[dst] = True
        if not filled.all():
            raise ValueError(
                f"checkpoint is missing data for leaf {leaf} window "
                f"{window} — saved with an incompatible layout?")
        return out


def load_sharded(directory: str, like: Any) -> Any:
    """Restore a tree saved with `save_sharded`.

    `like` supplies the tree structure and the TARGET placement: each leaf
    may be a `jax.Array` (its sharding — possibly over a different mesh
    than at save time — is reused), a `jax.ShapeDtypeStruct` with a
    `.sharding`, or anything else (restored as host numpy).  Shapes and
    dtypes must match the manifest."""
    import jax

    if not os.path.exists(os.path.join(directory, MANIFEST)):
        raise FileNotFoundError(
            f"{directory}: no committed checkpoint (manifest.json absent)")
    with open(os.path.join(directory, MANIFEST)) as f:
        manifest = json.load(f)
    store = _ChunkStore(directory,
                        num_ranks=manifest.get("num_ranks_at_save"))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    saved_treedef = manifest.get("treedef")
    if saved_treedef is not None and str(treedef) != saved_treedef:
        raise ValueError(
            "template tree structure does not match the checkpoint — a "
            "same-shaped tree in a different structure/order would "
            "silently permute parameters.\n"
            f"  saved:    {saved_treedef}\n  template: {treedef}")
    if len(leaves) != len(manifest["leaves"]):
        raise ValueError(
            f"template has {len(leaves)} leaves but checkpoint has "
            f"{len(manifest['leaves'])}")

    out = []
    for i, (leaf, spec) in enumerate(zip(leaves, manifest["leaves"])):
        shape = tuple(spec["shape"])
        dtype = np.dtype(spec["dtype"])
        t_shape = tuple(getattr(leaf, "shape", shape))
        if t_shape != shape:
            raise ValueError(
                f"leaf {i}: template shape {t_shape} != saved {shape}")
        t_dtype = getattr(leaf, "dtype", dtype)
        if np.dtype(t_dtype) != dtype:
            raise ValueError(
                f"leaf {i}: template dtype {t_dtype} != saved {dtype} — "
                "cast after load for precision changes (a silent dtype "
                "swap would poison the first jitted step)")
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and isinstance(leaf, (jax.Array,
                                                      jax.ShapeDtypeStruct)):
            def cb(index, _leaf=i, _shape=shape, _dtype=dtype,
                   _store=store):
                win = _window(index, _shape)
                return _store.assemble(_leaf, win, _dtype)

            out.append(jax.make_array_from_callback(shape, sharding, cb))
        else:
            full = store.assemble(
                i, [[0, d] for d in shape], dtype)
            out.append(full)
    return jax.tree_util.tree_unflatten(treedef, out)


def verify_checkpoint(directory: str) -> None:
    """Integrity check of a committed checkpoint without a template tree:
    parse the manifest, then crc32-verify every indexed chunk against its
    payload.  Raises `FileNotFoundError` (uncommitted / missing),
    `ChecksumError` (payload corruption), or `ValueError` (structural rot:
    unparseable manifest/index, missing chunk files/keys).  Returning
    means every recorded chunk's bytes hash clean — the checkpoint is
    intact in the sense the resilience layer's fallback cares about."""
    if not os.path.exists(os.path.join(directory, MANIFEST)):
        raise FileNotFoundError(
            f"{directory}: no committed checkpoint (manifest.json absent)")
    try:
        with open(os.path.join(directory, MANIFEST)) as f:
            manifest = json.load(f)
        store = _ChunkStore(directory,
                            num_ranks=manifest.get("num_ranks_at_save"))
        for entries in store.by_leaf.values():
            for entry in entries:
                store._chunk(entry)
    except (ChecksumError, FileNotFoundError):
        raise
    except Exception as e:
        # zipfile.BadZipFile, json.JSONDecodeError, KeyError on a missing
        # chunk, truncated .npy payloads — all "this checkpoint is rotten"
        raise ValueError(f"{directory}: unreadable checkpoint: {e!r}") from e


# ---------------------------------------------------------------------------
# Model-level convenience (the multi-host ModelSerializer face)
# ---------------------------------------------------------------------------

def save_model_sharded(net, directory: str) -> None:
    """Sharded save of a MultiLayerNetwork/ComputationGraph: params, layer
    state, updater state, and counters; config travels in the manifest."""
    tree = {"params": net.params_, "state": net.state_,
            "opt": net.opt_state_}
    save_sharded(directory, tree, metadata={
        "config": net.conf.to_json(), "iteration": net.iteration,
        "epoch": net.epoch})


def load_model_sharded(net, directory: str):
    """Restore into an already-init()ed net whose current arrays define
    the target sharding (call under the NEW mesh).  Returns `net`."""
    like = {"params": net.params_, "state": net.state_,
            "opt": net.opt_state_}
    tree = load_sharded(directory, like)
    meta = read_metadata(directory)
    net.params_ = tree["params"]
    net.state_ = tree["state"]
    net.opt_state_ = tree["opt"]
    net.iteration = meta["iteration"]
    net.epoch = meta["epoch"]
    return net
