"""ZeRO-1 cross-replica sharded weight update (optimizer-state sharding).

Xu et al., *Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training* (arXiv:2004.13336): instead of every replica
all-reducing full gradients and redundantly running the full optimizer
step on a full replicated copy of the moments, shard the weight update —
reduce-scatter the gradients over the data axis, apply the updater on each
device's 1/N shard of params/moments, and all-gather the updated params
for the next forward.  Same math, ~N× less optimizer-state HBM per
replica, and the all-reduce decomposed into reduce-scatter + all-gather
that XLA can overlap with the backward pass.

GSPMD expression (no hand-written collectives): the step body computes the
usual data-parallel gradients and we pin *layouts* with
`jax.lax.with_sharding_constraint` —

    grads   (all-reduced, replicated)  --constrain P(axis)--> reduce-scatter
    updater runs elementwise on the local shard of params/moments
    new params (sharded)               --constrain P()------> all-gather

`with_sharding_constraint` is value-preserving, so parity with the
replicated path holds by construction; only the schedule changes.

Per-leaf policy (`build_plans`):
  * a TP rule hit (any non-None dim in its `ShardingRules` spec) takes
    precedence — that leaf keeps its tensor-parallel layout everywhere
    and its moments follow it (already distributed; ZeRO adds nothing);
  * leading dim >= N: shard dim 0 over the data axis.  Non-divisible
    leading dims are zero-padded to the next multiple of N *inside the
    step* (jax 0.4.x cannot materialize uneven NamedShardings, and an
    uneven constraint inside jit silently degrades to replicated).
    Padded leaves keep their PERSISTENT param storage replicated at the
    true shape; their moments are stored padded + sharded.  Zero pads
    are a fixed point of every elementwise updater (zero grad -> zero
    moment -> zero update), so the pad region never leaks into values;
  * tiny / scalar leaves (biases smaller than the axis): replicated —
    sharding them would save nothing and cost a collective.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.sharding import ShardingRules, _path_str
from deeplearning4j_tpu.train.updaters import tree_map_like_params

PyTree = Any


@dataclasses.dataclass(frozen=True)
class LeafPlan:
    """Per-param-leaf placement decision.

    `store` is the persistent layout of the param leaf between steps,
    `update` the layout during the optimizer step (where the moments live
    permanently), `compute` the layout for forward/backward."""

    kind: str                 # "shard" | "repl" | "tp"
    shape: Tuple[int, ...]    # true (unpadded) shape
    pad: int                  # zero rows appended to reach divisibility
    store: P
    update: P
    compute: P

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        if not self.shape:
            return self.shape
        return (self.shape[0] + self.pad,) + tuple(self.shape[1:])


def build_plans(params: PyTree, mesh: Mesh, axis: str = "data",
                rules: Optional[ShardingRules] = None) -> PyTree:
    """A `LeafPlan` for every param leaf (same tree structure, plans as
    leaves).  TP rules (when given) win per-leaf; otherwise leading dims
    that can cover the data axis are sharded, the rest replicated."""
    n = mesh.shape[axis]

    def plan(path, leaf):
        shape = tuple(np.shape(leaf))
        if rules is not None:
            spec = rules.spec_for(_path_str(path), shape, mesh)
            if any(s is not None for s in spec):
                return LeafPlan("tp", shape, 0, spec, spec, spec)
        if len(shape) >= 1 and shape[0] >= n:
            pad = (-shape[0]) % n
            store = P(axis) if pad == 0 else P()
            return LeafPlan("shard", shape, pad, store, P(axis), P())
        return LeafPlan("repl", shape, 0, P(), P(), P())

    return jax.tree_util.tree_map_with_path(plan, params)


class Zero1Transform:
    """The step-transform threaded through `_build_step_body()`.

    All methods are trace-time tree_maps emitting value-preserving
    `with_sharding_constraint`s, so they compose with jit donation, the
    `fit_steps` fused scan (layouts are a fixed point of one body
    application) and `compute_dtype` casts (the gather happens on the f32
    master copy; casting fuses after it)."""

    def __init__(self, mesh: Mesh, axis: str, plans: PyTree):
        self.mesh = mesh
        self.axis = axis
        self.plans = plans

    def _ns(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _sub(self, name: Optional[str]) -> PyTree:
        return self.plans if name is None else self.plans[name]

    # ---- inside-the-step layout moves ----
    def gather_all(self, params: PyTree) -> PyTree:
        """Params at store layout -> compute layout (the all-gather; a
        no-op for replicated leaves, TP leaves keep their TP layout)."""
        return jax.tree_util.tree_map(
            lambda pl, x: jax.lax.with_sharding_constraint(
                x, self._ns(pl.compute)),
            self.plans, params)

    def _to_update(self, pl: LeafPlan, x):
        if pl.pad:
            # jnp.pad, NOT concatenate: the SPMD partitioner miscompiles a
            # concat whose output is constrained onto one axis of a multi-
            # axis mesh (replicated operands get summed over the other
            # axis); the pad op partitions correctly
            x = jnp.pad(x, [(0, pl.pad)] + [(0, 0)] * (x.ndim - 1))
        return jax.lax.with_sharding_constraint(x, self._ns(pl.update))

    def scatter(self, name: Optional[str], grads: PyTree) -> PyTree:
        """All-reduced grads -> update layout (the reduce-scatter)."""
        return jax.tree_util.tree_map(self._to_update, self._sub(name),
                                      grads)

    def update_view(self, name: Optional[str], params: PyTree) -> PyTree:
        """Master params -> the padded/sharded view the updater runs on."""
        return jax.tree_util.tree_map(self._to_update, self._sub(name),
                                      params)

    def restore(self, name: Optional[str], new_params: PyTree) -> PyTree:
        """Updated shards -> persistent store layout (the all-gather for
        leaves whose storage is replicated; pads sliced off)."""
        def r(pl, x):
            if pl.pad:
                # gather at the (even) padded shape FIRST, slice replicated:
                # an uneven slice of the sharded dim hits the same multi-
                # axis-mesh partitioner miscompile as concat (see _to_update)
                x = jax.lax.with_sharding_constraint(x, self._ns(P()))
                x = x[: pl.shape[0]]
            return jax.lax.with_sharding_constraint(x, self._ns(pl.store))
        return jax.tree_util.tree_map(r, self._sub(name), new_params)

    def constrain_update(self, name: Optional[str], grads: PyTree) -> PyTree:
        """Pin an ALREADY-PADDED gradient tree to the update layout.

        The hierarchical-sharing apply-half feeds gradients back that came
        off the wire at the grad-half's output layout — padded leaves are
        padded already, so `scatter` (which pads again) would be wrong;
        this is the re-entry constraint only."""
        return jax.tree_util.tree_map(
            lambda pl, x: jax.lax.with_sharding_constraint(
                x, self._ns(pl.update)),
            self._sub(name), grads)

    def constrain_opt(self, name: Optional[str], opt_state: PyTree) -> PyTree:
        """Pin the new moments to the update layout so the donated output
        matches the input buffers (scalar step counts etc. pass through)."""
        def pin(sub, plan_sub):
            return jax.tree_util.tree_map(
                lambda s, pl: jax.lax.with_sharding_constraint(
                    s, self._ns(pl.update)),
                sub, plan_sub)
        return tree_map_like_params(
            pin, opt_state, self._sub(name), lambda s: s,
            shape_of=lambda pl: pl.padded_shape)


def _invalidate_steps(model) -> None:
    model._train_step = None
    model._scan_step = None
    # hierarchical-sharing split steps (only MLN/CG grow these attrs)
    if hasattr(model, "_grad_step"):
        model._grad_step = None
    if hasattr(model, "_apply_step"):
        model._apply_step = None


def _params_attr(model) -> str:
    return "variables_" if hasattr(model, "variables_") else "params_"


def _place_params(params: PyTree, plans: PyTree, mesh: Mesh) -> PyTree:
    return jax.tree_util.tree_map(
        lambda pl, leaf: jax.device_put(leaf, NamedSharding(mesh, pl.store)),
        plans, params)


def _place_opt_state(opt_state: PyTree, plans: PyTree, mesh: Mesh) -> PyTree:
    """Moments land padded (host-side zero pad — uneven device_put is
    unsupported) and sharded at their update layout; everything else
    (step counts, scalars, empty states) replicates."""
    repl = NamedSharding(mesh, P())

    def place_moments(sub, plan_sub):
        def one(s, pl):
            a = np.asarray(s)
            if pl.pad:
                a = np.concatenate(
                    [a, np.zeros((pl.pad,) + a.shape[1:], a.dtype)], axis=0)
            return jax.device_put(a, NamedSharding(mesh, pl.update))
        return jax.tree_util.tree_map(one, sub, plan_sub)

    return tree_map_like_params(
        place_moments, opt_state, plans,
        lambda sub: jax.device_put(sub, repl),
        shape_of=lambda pl: pl.shape)


def enable_zero1(model, mesh: Mesh, axis: str = "data",
                 rules: Optional[ShardingRules] = None) -> Zero1Transform:
    """Turn on the sharded weight update for a MultiLayerNetwork,
    ComputationGraph or SameDiff instance: build per-leaf plans, place
    params/moments accordingly, install the step transform and invalidate
    the compiled steps (they re-trace with the collectives baked in).
    Idempotent for an unchanged (mesh, axis).  For SameDiff, enable AFTER
    the graph (and training config) is final — plans snapshot the current
    variable set."""
    existing = getattr(model, "_step_transform", None)
    if existing is not None and existing.mesh is mesh \
            and existing.axis == axis:
        return existing
    attr = _params_attr(model)
    params = getattr(model, attr, None)
    if params is None:
        raise ValueError("model must be initialized before "
                         "optimizer sharding (call init() first)")
    if getattr(model, "opt_state_", None) is None:
        cfg = getattr(model, "training_config", None)
        if cfg is None or cfg.updater is None:
            raise ValueError("optimizer sharding needs an updater: call "
                             "set_training_config(...) first")
        model.opt_state_ = cfg.updater.init_state(params)
    plans = build_plans(params, mesh, axis=axis, rules=rules)
    zt = Zero1Transform(mesh, axis, plans)
    setattr(model, attr, _place_params(params, plans, mesh))
    model.opt_state_ = _place_opt_state(model.opt_state_, plans, mesh)
    if getattr(model, "state_", None) is not None:
        model.state_ = jax.device_put(model.state_,
                                      NamedSharding(mesh, P()))
    model._step_transform = zt
    _invalidate_steps(model)
    return zt


def disable_zero1(model) -> None:
    """Remove the step transform and un-pad the stored moments back to
    their true shapes (use before `save()` — padded moments are a device
    layout detail, not a portable checkpoint format).  No-op when ZeRO-1
    was never enabled."""
    zt = getattr(model, "_step_transform", None)
    if zt is None:
        return
    if getattr(model, "opt_state_", None) is not None:
        def unpad(sub, plan_sub):
            # via host: eager-slicing the sharded dim would re-enter the
            # partitioner (see Zero1Transform.restore); this is a rare
            # teardown/checkpoint path, the D2H copy is fine
            return jax.tree_util.tree_map(
                lambda s, pl: (jnp.asarray(np.asarray(s)[: pl.shape[0]])
                               if pl.pad else s),
                sub, plan_sub)
        model.opt_state_ = tree_map_like_params(
            unpad, model.opt_state_, zt.plans, lambda s: s,
            shape_of=lambda pl: pl.padded_shape)
    model._step_transform = None
    _invalidate_steps(model)


def reshard_zero1(model, new_mesh: Mesh, axis: str = "data",
                  rules: Optional[ShardingRules] = None) -> Zero1Transform:
    """Re-shard a ZeRO-1 model to a DIFFERENT mesh (elastic world-size
    change: a gang member left or joined, so the data axis shrank or
    grew).  Tears down the old transform through `disable_zero1` — which
    un-pads the moments to their true shapes, the portable layout — and
    re-enables on `new_mesh`, where `build_plans` re-derives shard/repl
    decisions and padding for the new axis size.  The same
    unpad-then-replan route the sharded-checkpoint loader takes when a
    restore lands on a differently-sized mesh, but in-process and without
    a disk round-trip.  Returns the new transform."""
    disable_zero1(model)
    zt = enable_zero1(model, new_mesh, axis=axis, rules=rules)
    # Step OUTPUTS (rng, device-resident counters) are committed to the
    # old mesh's devices; left in place they poison the re-traced step
    # with mixed device sets.  Pull them to host — the next step re-places
    # them on the new mesh like a fresh model's first step would.
    rng = getattr(model, "_rng", None)
    if rng is not None:
        model._rng = jnp.asarray(np.asarray(rng))
    for cached in ("_iter_dev", "_epoch_dev", "_iter_sync", "_epoch_sync"):
        if hasattr(model, cached):
            setattr(model, cached, None)
    return zt


def reshard_to_devices(model, devices, axis: str = "data",
                       rules: Optional[ShardingRules] = None
                       ) -> Optional[Zero1Transform]:
    """Externally-initiated world change (the pod arbiter handing a
    DeviceSlice to or from serving): re-shard the model's ZeRO-1 state
    to a fresh data-axis mesh over exactly `devices` — the surviving
    world after a shrink, or the grown world after a slice returns.
    Returns the new transform, or None (no-op) when ZeRO-1 was never
    enabled — a plain data-parallel model carries no sharded moments to
    move."""
    if getattr(model, "_step_transform", None) is None:
        return None
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({axis: len(devices)}, devices=list(devices))
    return reshard_zero1(model, mesh, axis=axis, rules=rules)


def opt_state_bytes_per_replica(opt_state: PyTree) -> int:
    """Optimizer-state bytes resident on ONE device: replicated leaves
    count in full, leaves sharded N ways count 1/N — the quantity the
    `training_opt_state_bytes{sharded=}` gauge reports."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            total += int(getattr(leaf, "nbytes", 0) or 0)
            continue
        dev0 = shards[0].device
        total += sum(int(s.data.nbytes) for s in shards
                     if s.device == dev0)
    return total
