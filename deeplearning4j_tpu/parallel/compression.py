"""Compressed gradient exchange for DCN/multi-slice hops.

Reference: `EncodedGradientsAccumulator` + Aeron publish/receive
(SURVEY.md §3.4): async threshold-quantized deltas between nodes.  On TPU
the intra-slice path is XLA all-reduce over ICI (never compressed); this
module keeps the reference's compression capability for the slow
cross-slice/DCN hop, as a HOST-side exchange: encode locally (C++ codec),
ship the sparse stream over whatever transport links slices (the launcher's
job), decode+apply remotely.  Synchronous-apply semantics — the async
staleness of the reference is deliberately dropped (north star).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.native_ops import ThresholdCodec


class CompressedGradientExchange:
    """Per-leaf threshold codecs over a gradient pytree."""

    def __init__(self, params_template, threshold: float = 1e-3,
                 adaptive_target_density: float = 1e-2):
        leaves, self._treedef = jax.tree_util.tree_flatten(params_template)
        self._shapes = [np.shape(l) for l in leaves]
        self.codecs: List[ThresholdCodec] = [
            ThresholdCodec(int(np.prod(s) or 1), threshold) for s in
            self._shapes]
        self.target_density = adaptive_target_density

    def encode(self, grads) -> List[np.ndarray]:
        """Pytree -> list of sparse int32 streams (residuals carried).

        Adaptive threshold (the ResidualPostProcessor role) adjusts AFTER
        each encode from the emitted stream's density — no second scan of
        the gradient."""
        leaves = jax.tree_util.tree_leaves(grads)
        out = []
        self._used_thresholds = []
        for codec, leaf in zip(self.codecs, leaves):
            self._used_thresholds.append(codec.threshold)
            stream = codec.encode(np.asarray(leaf))
            out.append(stream)
            d = len(stream) / codec.size
            if d > 2 * self.target_density:
                codec.threshold *= 1.2
            elif d < self.target_density / 2 and codec.threshold > 1e-6:
                codec.threshold /= 1.2
        return out

    def thresholds(self) -> List[float]:
        """Thresholds USED by the most recent encode (what decode needs)."""
        return getattr(self, "_used_thresholds",
                       [c.threshold for c in self.codecs])

    def decode(self, streams: List[np.ndarray],
               thresholds: Optional[List[float]] = None):
        """Sparse streams -> dense gradient pytree.  `thresholds` defaults
        to the most recent encode's ONLY when None — an explicit (possibly
        empty, for a zero-leaf tree) list is honored as given, and the
        per-call threshold never mutates codec state, so a decode of peer
        streams can run concurrently with the next local encode."""
        if thresholds is None:
            thresholds = self.thresholds()
        dense = []
        for codec, enc, shape, thr in zip(self.codecs, streams,
                                          self._shapes, thresholds):
            dense.append(codec.decode(enc, threshold=thr).reshape(shape))
        return jax.tree_util.tree_unflatten(self._treedef, dense)

    def compression_ratio(self, streams: List[np.ndarray]) -> float:
        dense_bytes = sum(4 * int(np.prod(s) or 1) for s in self._shapes)
        sparse_bytes = sum(4 * (len(s) + 1) for s in streams)
        return dense_bytes / max(sparse_bytes, 1)

    # ---- error-feedback residual management (elastic gang support) ----
    def residuals(self) -> List[np.ndarray]:
        """Per-leaf error-feedback residuals (live views, not copies)."""
        return [c.residual for c in self.codecs]

    def residual_norm(self) -> float:
        """Total l2 mass currently parked in error-feedback residuals —
        the gradient signal a membership change would strand."""
        return float(np.sqrt(sum(float(np.dot(c.residual, c.residual))
                                 for c in self.codecs)))

    def reset_residuals(self) -> None:
        """Zero the error-feedback state.  Used when a gang reformation
        rewinds to a checkpoint: the parked residual was accumulated from
        steps the rewind discards, so flushing it would double-count
        gradient mass the resumed run will recompute."""
        for c in self.codecs:
            c.residual[:] = 0.0

    def take_residuals(self) -> List[np.ndarray]:
        """Detach and return the residuals, zeroing the codec state.  A
        forward (non-rewind) membership change carries these into the
        next exchange via `flush_into` so no gradient mass is silently
        lost."""
        out = [c.residual.copy() for c in self.codecs]
        self.reset_residuals()
        return out

    def flush_into(self, residuals: List[np.ndarray]) -> None:
        """Add previously taken residuals into this exchange's codecs so
        the next encode emits them (shape-checked leafwise)."""
        for c, r in zip(self.codecs, residuals):
            if r.shape != c.residual.shape:
                raise ValueError(
                    f"residual shape {r.shape} != codec {c.residual.shape}")
            c.residual += r.astype(np.float32, copy=False)


def allreduce_compressed(exchange: CompressedGradientExchange,
                         transport, grads):
    """Sum a gradient pytree across ranks through the compressed path:
    encode locally (residuals carried), all-gather the sparse streams over
    `transport` (a `transport.TcpGradientMesh`), decode every rank's stream,
    sum dense.  This is the reference's EncodedGradientsAccumulator
    apply-peer-updates loop made synchronous (SURVEY.md §3.4 north star)."""
    from deeplearning4j_tpu.parallel.transport import (pack_streams,
                                                       unpack_streams)
    streams = exchange.encode(grads)
    payload = pack_streams(streams, exchange.thresholds())
    total = None
    for peer_payload in transport.allgather(payload):
        peer_streams, peer_thr = unpack_streams(peer_payload)
        dense = exchange.decode(peer_streams, peer_thr)
        total = dense if total is None else jax.tree_util.tree_map(
            lambda a, b: a + b, total, dense)
    return total


def allreduce_dense(transport, grads):
    """Sum a gradient pytree across ranks shipping FULL-PRECISION f32
    leaves — the uncompressed baseline the `bench.py --comms` A/B measures
    the threshold path against.  Same star all-gather, no codec, no
    residuals; bytes on wire scale with the dense parameter count."""
    from deeplearning4j_tpu.parallel.transport import (pack_dense,
                                                       unpack_dense)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    payload = pack_dense([np.asarray(l) for l in leaves])
    total = None
    for peer_payload in transport.allgather(payload):
        peer = unpack_dense(peer_payload)
        total = peer if total is None else [a + b
                                            for a, b in zip(total, peer)]
    return jax.tree_util.tree_unflatten(treedef, total)
