"""Parameter sharding rules (tensor parallelism).

The reference has NO tensor parallelism (SURVEY.md §2.3) — this is the
capability-exceeding TPU-native addition: weight matrices annotated with
`PartitionSpec`s over the `model` mesh axis; XLA inserts the all-gathers /
reduce-scatters.  Rules are (param-path-suffix -> spec) with a sensible
default: split the output dim of 2-D kernels over `model` when divisible,
replicate everything else.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


@dataclasses.dataclass
class ShardingRules:
    """Ordered (regex -> PartitionSpec) rules applied to param-tree paths
    (first match wins).  `None` entries in a spec mean replicate that dim."""

    rules: List[Tuple[str, P]] = dataclasses.field(default_factory=list)
    model_axis: str = "model"

    def add(self, pattern: str, spec: P) -> "ShardingRules":
        self.rules.append((pattern, spec))
        return self

    def spec_for(self, path: str, shape: Tuple[int, ...],
                 mesh: Mesh) -> P:
        for pattern, spec in self.rules:
            if re.search(pattern, path):
                return spec
        return self._default_spec(path, shape, mesh)

    def _default_spec(self, path: str, shape, mesh: Mesh) -> P:
        """Megatron-style default: split 2-D kernel output dim over `model`
        when the axis exists and divides; biases/scalars replicated."""
        if self.model_axis not in mesh.axis_names:
            return P()
        size = mesh.shape[self.model_axis]
        if len(shape) >= 2 and shape[-1] % size == 0 and shape[-1] >= size:
            return P(*([None] * (len(shape) - 1) + [self.model_axis]))
        return P()


def shard_model_params(params: Any, mesh: Mesh,
                       rules: Optional[ShardingRules] = None) -> Any:
    """device_put every param leaf with its rule's NamedSharding.  The jitted
    train step then computes sharded — computation follows data."""
    rules = rules or ShardingRules()

    def place(path, leaf):
        spec = rules.spec_for(_path_str(path), np.shape(leaf), mesh)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)
