"""Pallas fused LayerNorm — the platform-helper pattern beyond attention.

Reference analog: `libnd4j/include/ops/declarable/platform/cudnn/**` —
vendor-tuned kernels behind a dispatch check.  XLA already fuses layer-norm
chains well; this kernel exists for the long-sequence transformer path
where keeping the (mean, rstd) statistics in VMEM between forward and
backward avoids an HBM round-trip, and as the second instance (after
`attention_kernels.fused_attention`) of the measured-dispatch pattern:
`fused_layer_norm` uses the Pallas kernel only when shapes tile cleanly on
TPU, else the plain jnp composition.

custom_vjp wires the Pallas backward; gradients match the jnp reference
(tests run the kernel in interpret mode on CPU)."""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # collection-time guard: missing pallas degrades to the reference
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - reference-only environments
    pl = None


def layer_norm_reference(x, gain, bias=None, eps: float = 1e-5):
    """The canonical jnp layer norm over the last axis (the plain impl the
    registry op and the Pallas kernel both validate against — standalone so
    the op can dispatch here without a circular import)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps) * gain
    return y if bias is None else y + bias


# -- forward kernel ---------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    # Mosaic constraint (found on real v5e, not representable in interpret
    # mode): one kernel may not mix 2D and 1D outputs — the stats are
    # therefore (blk, 1) blocks (full lane cover exempts the 128-divisibility
    # rule), squeezed by the caller.
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd * g_ref[...] + b_ref[...]
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _ln_bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref,
                   dx_ref, dg_ref, db_ref):
    # dg/db partials: a (1, F) block violates Mosaic's 8-sublane rule, so
    # each grid step broadcasts its partial over an (8, F) block; the caller
    # reads sublane 0 of each.
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...]
    mean = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    dg_ref[...] = jnp.broadcast_to(
        jnp.sum(dy * xhat, axis=0)[None, None, :], dg_ref.shape)
    db_ref[...] = jnp.broadcast_to(
        jnp.sum(dy, axis=0)[None, None, :], db_ref.shape)
    wdy = dy * g
    c1 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy, axis=-1, keepdims=True)
    dx = (wdy - xhat * c1 - c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _rows_of(x):
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    return rows


def layer_norm_tpu(x, gain, bias=None, eps: float = 1e-5,
                   block_rows: int = 256, interpret: bool = False):
    """Pallas layer norm over the last axis.  x: [..., F]."""
    F = x.shape[-1]
    bias_ = jnp.zeros((F,), jnp.float32) if bias is None else bias
    rows = _rows_of(x)
    x2 = x.reshape(rows, F)
    blk = min(block_rows, rows)
    if rows % blk:
        raise ValueError(f"rows {rows} not divisible by block {blk}")
    grid = (rows // blk,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((blk, F), lambda i: (i, 0)),
                  pl.BlockSpec((F,), lambda i: (0,)),
                  pl.BlockSpec((F,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((blk, F), lambda i: (i, 0)),
                   pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                   pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, F), x.dtype),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)],
        interpret=interpret,
    )(x2, gain.astype(jnp.float32), bias_.astype(jnp.float32))
    return y.reshape(x.shape), mean[:, 0], rstd[:, 0]


def layer_norm_bwd_tpu(x, gain, mean, rstd, dy, block_rows: int = 256,
                       interpret: bool = False):
    F = x.shape[-1]
    rows = _rows_of(x)
    x2 = x.reshape(rows, F)
    dy2 = dy.reshape(rows, F)
    blk = min(block_rows, rows)
    if rows % blk:
        raise ValueError(f"rows {rows} not divisible by block {blk}")
    grid = (rows // blk,)
    dx, dg_part, db_part = pl.pallas_call(
        _ln_bwd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((blk, F), lambda i: (i, 0)),
                  pl.BlockSpec((F,), lambda i: (0,)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, F), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((blk, F), lambda i: (i, 0)),
                   pl.BlockSpec((1, 8, F), lambda i: (i, 0, 0)),
                   pl.BlockSpec((1, 8, F), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, F), x.dtype),
                   jax.ShapeDtypeStruct((grid[0], 8, F), jnp.float32),
                   jax.ShapeDtypeStruct((grid[0], 8, F), jnp.float32)],
        interpret=interpret,
    )(x2, gain.astype(jnp.float32), mean[:, None], rstd[:, None], dy2)
    return (dx.reshape(x.shape), dg_part[:, 0].sum(0).astype(gain.dtype),
            db_part[:, 0].sum(0))


# -- custom_vjp dispatcher --------------------------------------------------

# Measured on v5e-1 (TUNNEL_VALIDATION stage 4, 2026-07-31): fused LN
# fwd+bwd beats XLA's fused chain 1.07x at 8k rows and 1.06x at 64k rows
# (D=768 BERT shapes).  Below ~1k rows dispatch overhead dominates.
_LN_MIN_ROWS = 1024


def _can_tile(x, block_rows: int = 256) -> bool:
    """Kernel-lowering feasibility (also the interpret-mode gate)."""
    rows = _rows_of(x)
    return rows % min(block_rows, rows) == 0 and x.shape[-1] % 128 == 0


def _worth_it(x) -> bool:
    """Dispatch heuristic: big enough to beat XLA's fused chain."""
    return _rows_of(x) >= _LN_MIN_ROWS


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ln(x, gain, bias, eps, interpret):
    y, _, _ = layer_norm_tpu(x, gain, bias, eps, interpret=interpret)
    return y


def _fused_ln_fwd(x, gain, bias, eps, interpret):
    y, mean, rstd = layer_norm_tpu(x, gain, bias, eps, interpret=interpret)
    return y, (x, gain, bias, mean, rstd)


def _fused_ln_bwd(eps, interpret, res, dy):
    x, gain, bias, mean, rstd = res
    dx, dg, db = layer_norm_bwd_tpu(x, gain, mean, rstd, dy,
                                    interpret=interpret)
    return dx, dg, db.astype(bias.dtype)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(x, gain, bias=None, eps: float = 1e-5,
                     interpret: Optional[bool] = None):
    """Measured-dispatch layer norm (the `fused_attention` pattern): Pallas
    kernel when on TPU (or interpret=True) and shapes tile; jnp reference
    otherwise."""
    if pl is None:                # pallas unavailable: reference only
        return layer_norm_reference(x, gain, bias, eps)
    if interpret is None:
        on_tpu = jax.default_backend() == "tpu"
        if not on_tpu or not _can_tile(x) or not _worth_it(x):
            return layer_norm_reference(x, gain, bias, eps)
        interpret = False
    elif not _can_tile(x):        # interpret mode: correctness gate only
        return layer_norm_reference(x, gain, bias, eps)
    bias_arg = jnp.zeros((x.shape[-1],), jnp.float32) if bias is None \
        else bias
    return _fused_ln(x, gain, bias_arg, eps, interpret)
