"""Pallas conv-backward-filter (wgrad) prototype.

VERDICT r3 #3: ResNet-50's conv backward is 45% of step time at ~40% MXU
(bench_artifacts/PERF_ANALYSIS.md); the prescribed experiment is a Pallas
wgrad (or dgrad) kernel for the 3x3 stride-1 SAME shapes, A/B'd against
XLA's lowering ON CHIP — a measured win adopts it, a measured loss gets a
committed negative-result table (tunnel_playbook.py stage 6).

Formulation: for a 3x3 stride-1 SAME conv,

    dW[i, j, ci, co] = sum_{b, oh, ow} x_pad[b, oh+i, ow+j, ci]
                                     * dy[b, oh, ow, co]

i.e. NINE [Ci, K] x [K, Co] matmuls over the same K = B*H*W reduction,
each with a shifted view of x.  XLA lowers this as one big filter-grad
conv; the kernel instead keeps an x row-stripe resident in VMEM and
reuses it for all nine taps (the data-reuse XLA's tiling does not get
credit for at these shapes).

Halo handling: Pallas blocked indexing cannot express overlapping row
blocks, so the three row shifts are materialized OUTSIDE the kernel as
three row-aligned views of the padded input (x_pad[:, i:i+H] for
i in 0,1,2) — each partitions cleanly into row stripes; the two column
shifts stay inside the stripe because the full padded width is loaded.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _wgrad_kernel(xt_ref, xm_ref, xb_ref, dy_ref, out_ref, *, bh, W, Ci,
                  Co):
    step = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    dy = dy_ref[0].reshape(bh * W, Co).astype(jnp.float32)
    for i, xs_ref in enumerate((xt_ref, xm_ref, xb_ref)):
        xs = xs_ref[0]                          # [bh, W+2, Ci]
        for j in range(3):
            xij = xs[:, j:j + W, :].reshape(bh * W, Ci).astype(
                jnp.float32)
            acc = jax.lax.dot_general(
                xij, dy, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            out_ref[i * 3 + j] += acc


def conv3x3_wgrad_tpu(x, dy, block_rows: int = 0,
                      interpret: bool = False):
    """Filter gradient of a 3x3 stride-1 SAME NHWC conv.

    x: [B, H, W, Ci] activations, dy: [B, H, W, Co] output cotangent
    -> dw [3, 3, Ci, Co] float32.
    """
    B, H, W, Ci = x.shape
    Co = dy.shape[-1]
    if dy.shape[:3] != (B, H, W):
        raise ValueError(f"dy {dy.shape} mismatches x {x.shape}")
    bh = block_rows or max(d for d in (1, 2, 4, 7, 8, 14, 16, 28, 32)
                           if H % d == 0)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    # three row-shifted, stripe-partitionable views (see module docstring)
    xt = xp[:, 0:H]
    xm = xp[:, 1:H + 1]
    xb = xp[:, 2:H + 2]
    grid = (B, H // bh)

    x_spec = pl.BlockSpec((1, bh, W + 2, Ci),
                          lambda b, i: (b, i, 0, 0))
    out = pl.pallas_call(
        functools.partial(_wgrad_kernel, bh=bh, W=W, Ci=Ci, Co=Co),
        grid=grid,
        in_specs=[x_spec, x_spec, x_spec,
                  pl.BlockSpec((1, bh, W, Co), lambda b, i: (b, i, 0, 0))],
        out_specs=pl.BlockSpec((9, Ci, Co), lambda b, i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((9, Ci, Co), jnp.float32),
        interpret=interpret,
    )(xt, xm, xb, dy)
    return out.reshape(3, 3, Ci, Co)


def conv3x3_wgrad_xla(x, dy):
    """XLA reference: filter grad via autodiff of the forward conv."""
    w0 = jnp.zeros((3, 3, x.shape[-1], dy.shape[-1]), jnp.float32)

    def loss(w):
        y = jax.lax.conv_general_dilated(
            x.astype(jnp.float32), w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum(y * dy.astype(jnp.float32))

    return jax.grad(loss)(w0)


# ---------------------------------------------------------------------------
# dgrad: conv-backward-data (VERDICT r4 #5 — wgrad alone cannot close the
# 13.2 ms conv backward; dgrad is the other half).
#
# For a 3x3 stride-1 SAME conv, dx = SAME-conv(dy, Wt) where
# Wt[i, j, co, ci] = W[2-i, 2-j, ci, co] (spatial rot180 + channel
# transpose).  Same shifted-view trick as wgrad: the three row shifts of
# the padded dy are materialized as stripe-partitionable views outside
# the kernel; inside, each stripe does NINE [bh*W, Co] x [Co, Ci]
# matmuls against the pre-flipped filter taps and accumulates in f32 —
# the dy stripe stays resident in VMEM across all nine taps.
# ---------------------------------------------------------------------------

def _dgrad_kernel(dyt_ref, dym_ref, dyb_ref, wf_ref, out_ref, *, bh, W,
                  Ci, Co):
    wf = wf_ref[...]                             # [9, Co, Ci]
    acc = jnp.zeros((bh * W, Ci), jnp.float32)
    for i, ds_ref in enumerate((dyt_ref, dym_ref, dyb_ref)):
        ds = ds_ref[0]                           # [bh, W+2, Co]
        for j in range(3):
            dij = ds[:, j:j + W, :].reshape(bh * W, Co).astype(
                jnp.float32)
            acc += jax.lax.dot_general(
                dij, wf[i * 3 + j], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    out_ref[0] = acc.reshape(bh, W, Ci)


def conv3x3_dgrad_tpu(dy, w, block_rows: int = 0,
                      interpret: bool = False):
    """Input gradient of a 3x3 stride-1 SAME NHWC conv.

    dy: [B, H, W, Co] output cotangent, w: [3, 3, Ci, Co] filter
    -> dx [B, H, W, Ci] float32.
    """
    B, H, W, Co = dy.shape
    Ci = w.shape[2]
    if w.shape != (3, 3, Ci, Co):
        raise ValueError(f"w {w.shape} is not [3, 3, Ci, {Co}]")
    bh = block_rows or max(d for d in (1, 2, 4, 7, 8, 14, 16, 28, 32)
                           if H % d == 0)
    dyp = jnp.pad(dy, ((0, 0), (1, 1), (1, 1), (0, 0)))
    dyt = dyp[:, 0:H]
    dym = dyp[:, 1:H + 1]
    dyb = dyp[:, 2:H + 2]
    # rot180 + channel transpose, one tap per row: wf[i*3+j] = Wt[i, j]
    wf = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2).reshape(9, Co, Ci)
    grid = (B, H // bh)

    dy_spec = pl.BlockSpec((1, bh, W + 2, Co),
                           lambda b, i: (b, i, 0, 0))
    return pl.pallas_call(
        functools.partial(_dgrad_kernel, bh=bh, W=W, Ci=Ci, Co=Co),
        grid=grid,
        in_specs=[dy_spec, dy_spec, dy_spec,
                  pl.BlockSpec((9, Co, Ci), lambda b, i: (0, 0, 0))],
        out_specs=pl.BlockSpec((1, bh, W, Ci),
                               lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, W, Ci), jnp.float32),
        interpret=interpret,
    )(dyt, dym, dyb, wf)


def conv3x3_dgrad_xla(dy, w):
    """XLA reference: input grad via autodiff of the forward conv."""
    B, H, W, Co = dy.shape
    x0 = jnp.zeros((B, H, W, w.shape[2]), jnp.float32)

    def loss(x):
        y = jax.lax.conv_general_dilated(
            x, w.astype(jnp.float32), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum(y * dy.astype(jnp.float32))

    return jax.grad(loss)(x0)


# ---------------------------------------------------------------------------
# Measured-dispatch adoption hook (the flash/fused-LN pattern): a
# custom_vjp 3x3-s1-SAME conv whose BACKWARD routes to the Pallas
# wgrad/dgrad kernels when the corresponding flag is on.  Default off —
# `tunnel_playbook.py` stage 8 A/Bs the full train step with the flags
# enabled and a measured win flips them (one line, or the
# DL4J_TPU_CONV_BWD_PALLAS env var).
# ---------------------------------------------------------------------------

import os as _os

CONV_BWD_PALLAS = {
    "wgrad": "w" in _os.environ.get("DL4J_TPU_CONV_BWD_PALLAS", ""),
    "dgrad": "d" in _os.environ.get("DL4J_TPU_CONV_BWD_PALLAS", ""),
    #: interpret-mode for tests on CPU
    "interpret": False,
}


def conv3x3_eligible(x_shape, w_shape, b, stride, padding, dilation) -> bool:
    """The shapes this hook covers: 3x3, stride 1, SAME, no dilation,
    NHWC, bias-free (the ResNet body conv)."""
    return (any(CONV_BWD_PALLAS[k] for k in ("wgrad", "dgrad"))
            and b is None
            and tuple(stride) == (1, 1) and tuple(dilation) == (1, 1)
            and padding == "SAME"
            and len(w_shape) == 4 and w_shape[:2] == (3, 3)
            and len(x_shape) == 4)


@jax.custom_vjp
def conv3x3_same(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _c33_fwd(x, w):
    return conv3x3_same(x, w), (x, w)


def _c33_bwd(res, dy):
    x, w = res
    itp = CONV_BWD_PALLAS["interpret"]
    # XLA's own cotangents for whichever side stays on the XLA path —
    # the unused one is dead-code-eliminated under jit
    _, pullback = jax.vjp(
        lambda x_, w_: jax.lax.conv_general_dilated(
            x_, w_, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")), x, w)
    dx_xla, dw_xla = pullback(dy)
    dx = (conv3x3_dgrad_tpu(dy, w, interpret=itp).astype(x.dtype)
          if CONV_BWD_PALLAS["dgrad"] else dx_xla)
    dw = (conv3x3_wgrad_tpu(x, dy, interpret=itp).astype(w.dtype)
          if CONV_BWD_PALLAS["wgrad"] else dw_xla)
    return dx, dw


conv3x3_same.defvjp(_c33_fwd, _c33_bwd)


# ---------------------------------------------------------------------------
# Quantized inference conv (quant/ subsystem hot path)
# ---------------------------------------------------------------------------

def quantized_conv2d(x, qt, stride=(1, 1), padding="SAME",
                     dilation=(1, 1), acc_dtype=None,
                     feature_group_count=1):
    """NHWC/HWIO conv against int8 weights with per-output-channel scales:
    the conv consumes `qt.q` cast to the accumulating dtype and the scales
    apply to the product — `conv(x, dequant(W)) == conv(x, W_q) * s[co]`
    exactly, because each output channel is a sum over one channel's
    weights only.  The int8 HWIO buffer is what stays device-resident;
    no f32 copy of the filter exists in the compiled program."""
    if qt.axis != qt.ndim - 1:
        raise ValueError(
            f"quantized_conv2d needs per-output-channel scales "
            f"(axis={qt.ndim - 1}), got axis={qt.axis}")
    acc = jnp.dtype(acc_dtype) if acc_dtype is not None else x.dtype
    y = jax.lax.conv_general_dilated(
        x.astype(acc), qt.q.astype(acc),
        tuple(stride), padding, rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=feature_group_count,
        preferred_element_type=acc)
    return y * qt.scale.astype(acc).reshape(1, 1, 1, -1)
