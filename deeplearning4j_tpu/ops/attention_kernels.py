"""Fused attention kernels.

Replaces the reference's `dotProductAttention`/`multiHeadDotProductAttention`
declarable ops (`libnd4j .../generic/nn/dot_product_attention.cpp` — naive
materialized [T,T] scores) with flash-attention-style computation, the role
cuDNN fused attention plays for the reference's platform helpers:

- `mha_reference`: naive jnp (ground truth for tests; O(T^2) memory).
- `blockwise_attention`: online-softmax `lax.scan` over KV blocks — O(T)
  memory, XLA-fusable everywhere (CPU tests, any accelerator), and the
  building block ring attention reuses across chips.
- `flash_attention_tpu` + `flash_attention_bwd_tpu`: Pallas TPU kernels,
  3D grid (batch*heads, Q blocks, KV blocks) with online-softmax state in
  VMEM scratch; the forward saves per-row logsumexp and the backward is a
  true FlashAttention-2-style pair of kernels (dQ, then dK/dV) recomputing
  P from the logsumexp — no [T,T] materialization in either direction.
- `fused_attention`: measured dispatcher — XLA-fused naive path for short
  sequences (fastest on v5e below ~2k), Pallas kernels for long unmasked
  tiling shapes, blockwise scan for the rest; differentiable everywhere.

Layouts: [B, H, T, D] (heads separated — the TPU-native layout; the nn/
attention layers reshape from [B, T, F]).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # collection-time guard: a missing pallas degrades the Pallas paths
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - reference-only environments
    pl = None
    pltpu = None

NEG_INF = -1e30


def mha_reference(q, k, v, mask=None, causal=False, scale=None):
    """Naive attention (ground truth).  mask: [B, T] of 1/0 over KV
    positions."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T, S = q.shape[2], k.shape[2]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(S)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _blockwise_fwd(q, k, v, mask, causal, scale, block_k):
    """Online-softmax scan over KV blocks; returns (out, (m, l))."""
    B, H, T, D = q.shape
    S = k.shape[2]
    nblocks = S // block_k
    qs = q * scale

    kb = k.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    if mask is not None:
        mb = mask.reshape(B, nblocks, block_k).transpose(1, 0, 2)
    else:
        mb = jnp.ones((nblocks, B, block_k), q.dtype)

    def step(carry, blk):
        acc, m, l, j = carry
        kj, vj, mj = blk
        # online-softmax statistics in f32 regardless of input dtype
        # (matches the Pallas kernel; bf16 accumulation across blocks
        # degrades the softmax normalizer)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kj,
                       preferred_element_type=jnp.float32)  # [B,H,T,bk]
        s = jnp.where(mj[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qi = jnp.arange(T)[:, None]
            ki = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1)
        acc_new = corr[..., None] * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vj.dtype), vj,
            preferred_element_type=jnp.float32)
        return (acc_new, m_new, l_new, j + 1), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, m, l, _), _ = jax.lax.scan(step, (acc0, m0, l0, 0), (kb, vb, mb))
    return (acc / l[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def blockwise_attention(q, k, v, mask=None, causal=False, scale=None,
                        block_k=128):
    """O(T)-memory attention via lax.scan (the 'flash' recurrence in pure
    JAX).  Differentiable with recompute-based backward."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bk = min(block_k, k.shape[2])
    if k.shape[2] % bk:
        return mha_reference(q, k, v, mask, causal, scale)
    return _blockwise_fwd(q, k, v, mask, causal, scale, bk)


def _bw_fwd(q, k, v, mask, causal, scale, block_k):
    out = blockwise_attention(q, k, v, mask, causal, scale, block_k)
    return out, (q, k, v, mask)


def _bw_bwd(causal, scale, block_k, res, g):
    """Flash-style backward: recompute attention under jax.grad of the
    scan — XLA rematerializes blockwise, never storing [T,T]."""
    q, k, v, mask = res

    def f(q_, k_, v_):
        if scale is None:
            s = q_.shape[-1] ** -0.5
        else:
            s = scale
        bk = min(block_k, k_.shape[2])
        if k_.shape[2] % bk:
            out = mha_reference(q_, k_, v_, mask, causal, s)
        else:
            out = _blockwise_fwd(q_, k_, v_, mask, causal, s, bk)
        return jnp.sum(out * g)

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    return dq, dk, dv, None


blockwise_attention.defvjp(_bw_fwd, _bw_bwd)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, *rest,
                  block_q: int, block_k: int, nkv: int, causal: bool,
                  scale: float, has_mask: bool):
    """3D grid (batch*head, q-block, kv-block): Pallas pipelines the KV
    block fetches (double-buffered HBM→VMEM) while online-softmax state
    lives in VMEM scratch across the kv dimension.  Emits per-row
    logsumexp for the backward kernels.  With ``has_mask`` an additive
    f32 bias block [1, 1, bk] (0 keep / NEG_INF drop over KV positions)
    precedes the outputs."""
    if has_mask:
        bias_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
    else:
        o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
        bias_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    # causal: kv blocks fully above the diagonal contribute nothing
    live = (j * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0]                                       # [bq, D]
        kj = k_ref[0]                                      # [bk, D]
        vj = v_ref[0]
        s = jnp.dot(q, kj.T, preferred_element_type=jnp.float32) * scale
        if has_mask:
            s = s + bias_ref[0]                            # [1,bk] → rows
        if causal:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0))
            cols = (j * block_k
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        m = m_sc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_sc[...] = corr * l_sc[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = corr * acc_sc[...] + jnp.dot(
            p.astype(vj.dtype), vj, preferred_element_type=jnp.float32)
        m_sc[...] = m_new

    @pl.when(j == nkv - 1)
    def _finalize():
        l = l_sc[...]
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_sc[...] + jnp.log(l)                # [bq, 1]


def _mask_bias3(mask, B, S):
    """[B, S] 1/0 keep-mask → additive f32 bias [B, 1, S] for the kernels."""
    return jnp.where(mask.reshape(B, S) > 0, 0.0, NEG_INF).astype(
        jnp.float32).reshape(B, 1, S)


def flash_attention_tpu(q, k, v, causal=False, scale=None,
                        block_q=256, block_k=256, interpret=False,
                        return_lse=False, mask=None):
    """Pallas flash-attention forward.  [B, H, T, D]; T divisible by the
    block sizes (dispatcher checks).  With ``return_lse`` also returns the
    row logsumexp [B*H, T] (f32) for the backward kernels.  ``mask``:
    optional [B, S] 1/0 keep-mask over KV positions (padding/segment
    mask), shared across heads."""
    B, H, T, D = q.shape
    S = k.shape[2]
    if scale is None:
        scale = D ** -0.5
    bq = min(block_q, T)
    bk = min(block_k, S)
    nkv = S // bk
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    has_mask = mask is not None
    kernel = functools.partial(_flash_kernel, block_q=bq, block_k=bk,
                               nkv=nkv, causal=causal, scale=scale,
                               has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
    ]
    inputs = [qf, kf, vf]
    if has_mask:
        # bias [B, 1, S]: per-batch, shared across the H heads folded into
        # grid dim 0 — the index map divides the head out
        in_specs.append(pl.BlockSpec((1, 1, bk),
                                     lambda b, i, j, H=H: (b // H, 0, j)))
        inputs.append(_mask_bias3(mask, B, S))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, T // bq, nkv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            # lse rides a trailing singleton lane dim — (1, bq, 1) blocks
            # satisfy the TPU (8, 128)-or-full tiling rule
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    out = out.reshape(B, H, T, D)
    return (out, lse.reshape(B * H, T)) if return_lse else out


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_q: int, block_k: int,
                         nkv: int, causal: bool, scale: float,
                         has_mask: bool):
    """dQ over grid (batch*head, q-block, kv-block): recompute P from the
    saved logsumexp (no [T,T] materialization), accumulate dS·K in
    scratch."""
    if has_mask:
        bias_ref, dq_ref, dq_sc = rest
    else:
        dq_ref, dq_sc = rest
        bias_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    live = (j * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0]                                       # [bq, D]
        do = do_ref[0]
        lse = lse_ref[0]                                   # [bq, 1]
        delta = delta_ref[0]
        kj = k_ref[0]                                      # [bk, D]
        vj = v_ref[0]
        s = jnp.dot(q, kj.T, preferred_element_type=jnp.float32) * scale
        if has_mask:
            s = s + bias_ref[0]
        if causal:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0))
            cols = (j * block_k
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk] f32
        dp = jnp.dot(do, vj.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_sc[...] += jnp.dot(ds.astype(kj.dtype), kj,
                              preferred_element_type=jnp.float32)

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, block_q: int,
                          block_k: int, nq: int, causal: bool, scale: float,
                          has_mask: bool):
    """dK/dV over grid (batch*head, kv-block, q-block): recompute P,
    accumulate P^T·dO and dS^T·Q in scratch."""
    if has_mask:
        bias_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
    else:
        dk_ref, dv_ref, dk_sc, dv_sc = rest
        bias_ref = None
    ji = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    # causal: q blocks strictly above the kv block's diagonal see nothing
    live = (i * block_q + block_q - 1 >= ji * block_k) if causal else True

    @pl.when(live)
    def _step():
        kj = k_ref[0]                                      # [bk, D]
        vj = v_ref[0]
        qi = q_ref[0]                                      # [bq, D]
        doi = do_ref[0]
        lse_i = lse_ref[0]                                 # [bq, 1]
        delta_i = delta_ref[0]
        s = jnp.dot(qi, kj.T, preferred_element_type=jnp.float32) * scale
        if has_mask:
            s = s + bias_ref[0]
        if causal:
            rows = (i * block_q
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0))
            cols = (ji * block_k
                    + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse_i)                             # [bq, bk]
        dv_sc[...] += jnp.dot(p.T.astype(doi.dtype), doi,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(doi, vj.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i)
        dk_sc[...] += jnp.dot(ds.T.astype(qi.dtype), qi,
                              preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def flash_attention_bwd_tpu(q, k, v, out, lse, g, causal=False, scale=None,
                            block_q=256, block_k=256, interpret=False,
                            mask=None):
    """Pallas flash-attention backward (FlashAttention-2 style): delta
    precomputed on-device, then separate dQ and dK/dV kernels so both
    matmul passes stay on the MXU without [T,T] materialization."""
    B, H, T, D = q.shape
    S = k.shape[2]
    if scale is None:
        scale = D ** -0.5
    bq = min(block_q, T)
    bk = min(block_k, S)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    gf = g.reshape(B * H, T, D)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise reduce, XLA-fused
    delta = jnp.sum(gf.astype(jnp.float32)
                    * out.reshape(B * H, T, D).astype(jnp.float32), axis=-1)
    lse3 = lse.reshape(B * H, T, 1)
    delta3 = delta.reshape(B * H, T, 1)
    nkv = S // bk
    nq = T // bq
    has_mask = mask is not None
    extra_in, extra_specs_ij, extra_specs_ji = [], [], []
    if has_mask:
        extra_in = [_mask_bias3(mask, B, S)]
        extra_specs_ij = [pl.BlockSpec((1, 1, bk),
                                       lambda b, i, j, H=H: (b // H, 0, j))]
        extra_specs_ji = [pl.BlockSpec((1, 1, bk),
                                       lambda b, j, i, H=H: (b // H, 0, j))]

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_q=bq,
                                  block_k=bk, nkv=nkv, causal=causal,
                                  scale=scale, has_mask=has_mask)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, nq, nkv),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ] + extra_specs_ij,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3, *extra_in)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=bq,
                                   block_k=bk, nq=nq, causal=causal,
                                   scale=scale, has_mask=has_mask)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, nkv, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ] + extra_specs_ji,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse3, delta3, *extra_in)
    return (dq.reshape(B, H, T, D), dk.reshape(B, H, S, D),
            dv.reshape(B, H, S, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_diff(q, k, v, mask, causal, scale, block_q=256,
                          block_k=256, interpret=False):
    return flash_attention_tpu(q, k, v, causal, scale, block_q, block_k,
                               mask=mask, interpret=interpret)


def _fa_fwd(q, k, v, mask, causal, scale, block_q, block_k,
            interpret=False):
    out, lse = flash_attention_tpu(q, k, v, causal, scale, block_q, block_k,
                                   return_lse=True, mask=mask,
                                   interpret=interpret)
    return out, (q, k, v, mask, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, mask, out, lse = res
    dq, dk, dv = flash_attention_bwd_tpu(q, k, v, out, lse, g, causal, scale,
                                         block_q, block_k, mask=mask,
                                         interpret=interpret)
    return dq, dk, dv, None


_flash_attention_diff.defvjp(_fa_fwd, _fa_bwd)


def _pick_block(x: int, prefer: int) -> Optional[int]:
    for b in (prefer, 512, 256, 128):
        if b <= prefer and x % b == 0:
            return b
    return None


# Empirical v5e-1 policy (fwd+bwd, bf16, D=64), confirmed on-chip in
# TUNNEL_VALIDATION stage 3 (2026-07-31): XLA's attention fusion wins at
# seq 1024 (flash 0.78x), parity at 2048 (0.998x), flash ahead at 4096
# (1.03x) and increasingly beyond — and flash is the only O(T)-memory
# option once [T,T] scores stop fitting HBM.
_FLASH_MIN_SEQ = 2048
_XLA_SCORE_BYTES_MAX = 2 << 30   # beyond ~2GB of scores, never take XLA path


def fused_attention(q, k, v, mask=None, causal=False, scale=None):
    """Dispatcher (the platform-helper pattern — cuDNN-attention role):

    - kernel tier (`ops/pallas/dispatch`): Pallas flash kernels (fwd +
      true FlashAttention-2-style bwd, O(T) memory) with TileConfig-driven
      blocks and masked-tail padding for ragged shapes, on TPU/GPU when
      the measured heuristics say flash wins (long seq, lane-multiple D),
      or whenever the tier is forced to `pallas`.
    - short seq / small scores → XLA-fused naive path (measured fastest
      on v5e below ~2k).
    - the rest → blockwise scan (O(T) memory).

    Differentiable everywhere."""
    B, H, T, D = q.shape
    S = k.shape[2]
    try:
        from deeplearning4j_tpu.ops import pallas as _tier
        impl = _tier.dispatch.resolve("attention", q, k, v, mask=mask,
                                      causal=causal)
    except Exception:
        _tier, impl = None, "reference"
    if impl == "pallas":
        from deeplearning4j_tpu.ops.pallas import attention as _pa
        sc = _tier.shape_class(t=T, s=S, d=D)
        return _pa.flash_attention(
            q, k, v, mask=mask, causal=causal, scale=scale,
            tile=_tier.dispatch.get_tile("attention", sc),
            interpret=_tier.dispatch.interpret_mode())
    score_bytes = B * H * T * S * q.dtype.itemsize
    if score_bytes <= _XLA_SCORE_BYTES_MAX:
        return mha_reference(q, k, v, mask, causal, scale)
    return blockwise_attention(q, k, v, mask, causal, scale)


# ---------------------------------------------------------------------------
# Quantized inference projections (quant/ subsystem hot path)
# ---------------------------------------------------------------------------

def quantized_projection(x, qt, b=None, acc_dtype=None):
    """[B, T, F] @ int8 [F, O] projection with per-output-channel scales —
    the q/k/v/out projections are where an attention block's weight bytes
    live, so they are what quantization shrinks; the [T, T] score math
    keeps the accumulating dtype untouched.  Dequantization (the scale
    multiply) happens after the matmul, inside the jitted program."""
    from deeplearning4j_tpu.ops.quant_kernels import quantized_matmul
    y = quantized_matmul(x, qt, acc_dtype=acc_dtype)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def quantized_mha(x, w_qkv, w_out, n_heads: int, b_qkv=None, b_out=None,
                  mask=None, causal=False, acc_dtype=None):
    """Self-attention with all four projections served from int8 weights
    (`w_qkv`: QTensor [F, 3F']; `w_out`: QTensor [F', F_out]) and the
    score/softmax/value math in the accumulating dtype via
    `fused_attention` — the quantized counterpart of the nn attention
    layers' forward for serving."""
    B, T, _ = x.shape
    qkv = quantized_projection(x, w_qkv, b=b_qkv, acc_dtype=acc_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    d = q.shape[-1] // n_heads

    def heads(a):          # [B, T, H*D] -> [B, H, T, D]
        return a.reshape(B, T, n_heads, d).transpose(0, 2, 1, 3)

    o = fused_attention(heads(q), heads(k), heads(v), mask=mask,
                        causal=causal)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, n_heads * d)
    return quantized_projection(o, w_out, b=b_out, acc_dtype=acc_dtype)
