"""Fused attention kernels.

Replaces the reference's `dotProductAttention`/`multiHeadDotProductAttention`
declarable ops (`libnd4j .../generic/nn/dot_product_attention.cpp` — naive
materialized [T,T] scores) with flash-attention-style computation, the role
cuDNN fused attention plays for the reference's platform helpers:

- `mha_reference`: naive jnp (ground truth for tests; O(T^2) memory).
- `blockwise_attention`: online-softmax `lax.scan` over KV blocks — O(T)
  memory, XLA-fusable everywhere (CPU tests, any accelerator), and the
  building block ring attention reuses across chips.
- `flash_attention`: Pallas TPU kernel, grid over (batch*heads, Q blocks),
  inner fori_loop over KV blocks with online softmax in VMEM; backward =
  recomputed blockwise gradient (flash-style recompute instead of storing
  the [T,T] probability matrix).
- `fused_attention`: dispatcher — Pallas kernel on TPU when shapes tile
  cleanly, blockwise scan otherwise; custom_vjp either way.

Layouts: [B, H, T, D] (heads separated — the TPU-native layout; the nn/
attention layers reshape from [B, T, F]).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def mha_reference(q, k, v, mask=None, causal=False, scale=None):
    """Naive attention (ground truth).  mask: [B, T] of 1/0 over KV
    positions."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T, S = q.shape[2], k.shape[2]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(S)[None, :]
        scores = jnp.where(qi >= ki, scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _blockwise_fwd(q, k, v, mask, causal, scale, block_k):
    """Online-softmax scan over KV blocks; returns (out, (m, l))."""
    B, H, T, D = q.shape
    S = k.shape[2]
    nblocks = S // block_k
    qs = q * scale

    kb = k.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    if mask is not None:
        mb = mask.reshape(B, nblocks, block_k).transpose(1, 0, 2)
    else:
        mb = jnp.ones((nblocks, B, block_k), q.dtype)

    def step(carry, blk):
        acc, m, l, j = carry
        kj, vj, mj = blk
        # online-softmax statistics in f32 regardless of input dtype
        # (matches the Pallas kernel; bf16 accumulation across blocks
        # degrades the softmax normalizer)
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kj,
                       preferred_element_type=jnp.float32)  # [B,H,T,bk]
        s = jnp.where(mj[:, None, None, :] > 0, s, NEG_INF)
        if causal:
            qi = jnp.arange(T)[:, None]
            ki = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(qi >= ki, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1)
        acc_new = corr[..., None] * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vj.dtype), vj,
            preferred_element_type=jnp.float32)
        return (acc_new, m_new, l_new, j + 1), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, m, l, _), _ = jax.lax.scan(step, (acc0, m0, l0, 0), (kb, vb, mb))
    return (acc / l[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def blockwise_attention(q, k, v, mask=None, causal=False, scale=None,
                        block_k=128):
    """O(T)-memory attention via lax.scan (the 'flash' recurrence in pure
    JAX).  Differentiable with recompute-based backward."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    bk = min(block_k, k.shape[2])
    if k.shape[2] % bk:
        return mha_reference(q, k, v, mask, causal, scale)
    return _blockwise_fwd(q, k, v, mask, causal, scale, bk)


def _bw_fwd(q, k, v, mask, causal, scale, block_k):
    out = blockwise_attention(q, k, v, mask, causal, scale, block_k)
    return out, (q, k, v, mask)


def _bw_bwd(causal, scale, block_k, res, g):
    """Flash-style backward: recompute attention under jax.grad of the
    scan — XLA rematerializes blockwise, never storing [T,T]."""
    q, k, v, mask = res

    def f(q_, k_, v_):
        if scale is None:
            s = q_.shape[-1] ** -0.5
        else:
            s = scale
        bk = min(block_k, k_.shape[2])
        if k_.shape[2] % bk:
            out = mha_reference(q_, k_, v_, mask, causal, s)
        else:
            out = _blockwise_fwd(q_, k_, v_, mask, causal, s, bk)
        return jnp.sum(out * g)

    dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    return dq, dk, dv, None


blockwise_attention.defvjp(_bw_fwd, _bw_bwd)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    """One (batch*head, q-block) program: online softmax over KV blocks.
    Block shapes: q [1, bq, D], k/v [1, S, D] — KV stays whole in VMEM per
    program (fine for the T ≤ 4k this kernel targets; ring attention covers
    longer)."""
    bq = q_ref.shape[1]
    S = k_ref.shape[1]
    D = q_ref.shape[2]
    qi = pl.program_id(1)

    q = q_ref[0] * scale                                  # [bq, D]
    acc = jnp.zeros((bq, D), jnp.float32)
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)

    nkv = S // block_k

    def body(j, carry):
        acc, m, l = carry
        kj = k_ref[0, pl.ds(j * block_k, block_k), :]      # [bk, D]
        vj = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, kj.T, preferred_element_type=jnp.float32)
        if causal:
            rows = (qi * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0))
            cols = (j * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.dot(p.astype(vj.dtype), vj,
                                       preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, nkv, body, (acc, m, l))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def flash_attention_tpu(q, k, v, causal=False, scale=None,
                        block_q=256, block_k=256, interpret=False):
    """Pallas flash-attention forward.  [B, H, T, D]; T divisible by the
    block sizes (dispatcher checks)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    if scale is None:
        scale = D ** -0.5
    bq = min(block_q, T)
    bk = min(block_k, S)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    kernel = functools.partial(_flash_kernel, block_k=bk, causal=causal,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_diff(q, k, v, causal, scale, block_q=256, block_k=256):
    return flash_attention_tpu(q, k, v, causal, scale, block_q, block_k)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    return (flash_attention_tpu(q, k, v, causal, scale, block_q, block_k),
            (q, k, v))


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res

    def f(q_, k_, v_):
        s = scale if scale is not None else q_.shape[-1] ** -0.5
        return jnp.sum(_blockwise_fwd(q_, k_, v_, None, causal, s,
                                      min(128, k_.shape[2])) * g)

    return jax.grad(f, argnums=(0, 1, 2))(q, k, v)


_flash_attention_diff.defvjp(_fa_fwd, _fa_bwd)


def _pick_block(x: int) -> Optional[int]:
    for b in (256, 128):
        if x % b == 0:
            return b
    return None


def fused_attention(q, k, v, mask=None, causal=False, scale=None):
    """Dispatcher: Pallas kernel on TPU for cleanly tiling unmasked shapes
    (T/S multiples of 128, head dim multiple of 64 — covers BERT's D=64),
    blockwise scan otherwise.  Differentiable everywhere."""
    on_tpu = jax.default_backend() == "tpu"
    T, S, D = q.shape[2], k.shape[2], q.shape[3]
    bq, bk = _pick_block(T), _pick_block(S)
    if on_tpu and mask is None and bq and bk and D % 64 == 0:
        return _flash_attention_diff(q, k, v, causal, scale, bq, bk)
    return blockwise_attention(q, k, v, mask, causal, scale)
