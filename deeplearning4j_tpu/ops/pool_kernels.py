"""Taps-based max-pool backward — the select-and-scatter replacement.

XLA lowers max-pool's gradient to `select-and-scatter`, a serial
window-walk that costs 0.88 ms/step in the ResNet-50 profile
(bench_artifacts/PERF_ANALYSIS.md r5) — the same per-window scan shape
the reference delegates to cuDNN's `PoolingBackward`
(`deeplearning4j-cuda/.../CudnnSubsamplingHelper.java` role).

The TPU-shaped alternative: recompute the max match on the OUTPUT grid
with kh*kw shifted strided views (the same tap machinery as
`conv_kernels`' wgrad), then accumulate `dy * [x == y] / ties` back into
the input with kh*kw strided `.at[].add` slices — pure elementwise +
slicing that XLA fuses, no serial scatter.

Semantics note: ties split the gradient evenly (a valid subgradient that
preserves sum(dx) == sum(dy)); XLA's select-and-scatter gives the whole
gradient to the FIRST max in window order.  The two differ only on exact
float ties (e.g. multiple relu zeros in one window), so adoption is
flag-gated (`POOL_BWD_TAPS`) and decided on measurement, like
CONV_BWD_PALLAS.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

# measured adoption only (tunnel_playbook stage 11); the env override
# mirrors CONV_BWD_PALLAS's discipline in conv_kernels.py
import os as _os

POOL_BWD_TAPS = {
    "enabled": _os.environ.get("DL4J_TPU_POOL_BWD_TAPS", "") == "1",
}


def _resolve_pad(padding, H, W, kernel, stride, Ho, Wo):
    """Per-dim (lo, hi) pads matching lax.reduce_window's semantics."""
    kh, kw = kernel
    sh, sw = stride
    if padding == "VALID":
        return (0, 0), (0, 0)
    if padding == "SAME":
        th = max((Ho - 1) * sh + kh - H, 0)
        tw = max((Wo - 1) * sw + kw - W, 0)
        return (th // 2, th - th // 2), (tw // 2, tw - tw // 2)
    (plh, phh), (plw, phw) = padding
    return (plh, phh), (plw, phw)


def _pool_fwd_raw(x, kernel, stride, padding):
    pad = padding
    if not isinstance(pad, str):
        pad = ((0, 0), tuple(pad[0]), tuple(pad[1]), (0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max,
                             (1,) + tuple(kernel) + (1,),
                             (1,) + tuple(stride) + (1,), pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool2d_taps(x, kernel, stride, padding="VALID"):
    """NHWC max pool whose VJP avoids select-and-scatter (see module
    docstring).  `padding`: "SAME" | "VALID" | ((lo,hi),(lo,hi))."""
    return _pool_fwd_raw(x, kernel, stride, padding)


def _fwd(x, kernel, stride, padding):
    y = _pool_fwd_raw(x, kernel, stride, padding)
    return y, (x, y)


def _bwd(kernel, stride, padding, resid, dy):
    x, y = resid
    B, H, W, C = x.shape
    kh, kw = kernel
    sh, sw = stride
    Ho, Wo = y.shape[1], y.shape[2]
    (plh, _), (plw, _) = _resolve_pad(padding, H, W, kernel, stride, Ho, Wo)
    Lh = (Ho - 1) * sh + kh            # padded window coverage
    Lw = (Wo - 1) * sw + kw
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (plh, max(Lh - H - plh, 0)),
                  (plw, max(Lw - W - plw, 0)), (0, 0)),
                 constant_values=-jnp.inf)[:, :Lh, :Lw, :]
    yf = y.astype(jnp.float32)

    taps, ties = [], 0.
    for ti in range(kh):
        for tj in range(kw):
            v = xp[:, ti:ti + (Ho - 1) * sh + 1:sh,
                   tj:tj + (Wo - 1) * sw + 1:sw, :]
            eq = (v == yf).astype(jnp.float32)
            taps.append(eq)
            ties = ties + eq
    scale = dy.astype(jnp.float32) / ties

    dxp = jnp.zeros((B, Lh, Lw, C), jnp.float32)
    i = 0
    for ti in range(kh):
        for tj in range(kw):
            dxp = dxp.at[:, ti:ti + (Ho - 1) * sh + 1:sh,
                         tj:tj + (Wo - 1) * sw + 1:sw, :].add(
                taps[i] * scale)
            i += 1
    dx = dxp[:, plh:plh + H, plw:plw + W, :]
    if dx.shape[1] < H or dx.shape[2] < W:     # VALID with cropped tail
        dx = jnp.pad(dx, ((0, 0), (0, H - dx.shape[1]),
                          (0, W - dx.shape[2]), (0, 0)))
    return (dx.astype(x.dtype),)


max_pool2d_taps.defvjp(_fwd, _bwd)


def max_pool2d(x, kernel, stride, padding="VALID"):
    """Dispatcher: taps VJP when POOL_BWD_TAPS['enabled'], else the
    XLA reduce_window path (select-and-scatter backward)."""
    if POOL_BWD_TAPS["enabled"]:
        return max_pool2d_taps(x, tuple(kernel), tuple(stride), padding)
    return _pool_fwd_raw(x, kernel, stride, padding)
