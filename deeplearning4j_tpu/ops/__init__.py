from deeplearning4j_tpu.ops.activations import ACTIVATIONS, get_activation  # noqa: F401
from deeplearning4j_tpu.ops.initializers import init_weights  # noqa: F401
from deeplearning4j_tpu.ops.losses import LOSSES, get_loss  # noqa: F401
from deeplearning4j_tpu.ops.norm_kernels import (  # noqa: F401
    fused_layer_norm, layer_norm_reference)
from deeplearning4j_tpu.ops.quant_kernels import (  # noqa: F401
    QTensor, dequant_epilogue, dequantize, quantization_error,
    quantize_tensor, quantized_dense, quantized_matmul,
    quantized_matmul_static, range_hostility)
from deeplearning4j_tpu.ops import pallas  # noqa: F401  (registers the
# fused-kernel tier; `pallas.dispatch` is the tier's selection layer)
