"""Activation function inventory.

Covers the reference's `org.nd4j.linalg.activations.Activation` enum
(IActivation implementations under `org/nd4j/linalg/activations/impl/`).
Every entry is a pure jax function so XLA fuses it into the surrounding
matmul/conv — the TPU replacement for libnd4j's standalone transform kernels
(`libnd4j/include/loops/transform_float.h` etc.), which on GPU each cost a
kernel launch and an HBM round trip.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Activation = Callable[[jnp.ndarray], jnp.ndarray]


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x):
    return jax.nn.gelu(x, approximate=True)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # Reference: RationalTanh — 1.7159 * tanh(2x/3) approximated rationally;
    # we use the exact closed form (XLA tanh is cheap on TPU).
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def swish(x):
    return jax.nn.swish(x)


def mish(x):
    return jax.nn.mish(x)


def cube(x):
    return x * x * x


def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS: Dict[str, Activation] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "gelu_tanh": gelu_tanh,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "softplus": softplus,
    "softsign": softsign,
    "swish": swish,
    "mish": mish,
    "cube": cube,
    "thresholdedrelu": thresholdedrelu,
}


def get_activation(name_or_fn) -> Activation:
    """Resolve an activation by enum-style name (case-insensitive) or pass
    through a callable (the IActivation escape hatch)."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name_or_fn}'. Known: {sorted(ACTIVATIONS)}"
        )
    return ACTIVATIONS[key]
