"""Flash attention entry point for the fused-kernel tier.

Thin, tile-aware wrapper over the blockwise online-softmax kernels in
``ops/attention_kernels.py`` (forward + FlashAttention-2-style backward
via ``_flash_attention_diff``).  What the tier adds on top:

- tiling comes from a :class:`TileConfig` (``block_q``/``block_kv``)
  instead of the fixed ``_pick_block`` ladder, so the autotuner's
  persisted winners take effect here;
- ragged / non-multiple-of-tile shapes are handled by zero-padding T and
  S up to block multiples with the padded KV positions knocked out via
  the additive [B, S] mask (a masked tail), then slicing the padded query
  rows back off — exact, because masked positions contribute
  ``exp(-1e30)``-scale weights and padded query rows are discarded;
- a ``reference`` lowering (plain ``mha_reference``) that is the
  definition of correctness for the conformance suite.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas.tiles import DEFAULT_TILES, TileConfig


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _q_sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16) else 8


def flash_attention(q, k, v, mask=None, causal: bool = False, scale=None,
                    tile: Optional[TileConfig] = None,
                    interpret: bool = False):
    """[B, H, T, D] flash attention with TileConfig-driven blocks and
    masked-tail padding for ragged T/S.  Differentiable."""
    import deeplearning4j_tpu.ops.attention_kernels as ak

    tile = tile or DEFAULT_TILES["attention"]
    B, H, T, D = q.shape
    S = k.shape[2]
    bq = min(tile.block_q, _round_up(T, _q_sublane(q.dtype)))
    bk = min(tile.block_kv, _round_up(S, 128))
    Tp, Sp = _round_up(T, bq), _round_up(S, bk)

    if (Tp, Sp) == (T, S):
        args = (q, k, v, mask, causal, scale, bq, bk)
    else:
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
        keep = jnp.ones((B, S), q.dtype) if mask is None else mask
        maskp = jnp.pad(keep.astype(q.dtype), ((0, 0), (0, Sp - S)))
        args = (qp, kp, vp, maskp, causal, scale, bq, bk)
    if interpret:
        args = args + (True,)
    out = ak._flash_attention_diff(*args)
    if Tp != T:
        out = out[:, :, :T, :]
    return out


def attention_reference(q, k, v, mask=None, causal: bool = False,
                        scale=None):
    import deeplearning4j_tpu.ops.attention_kernels as ak

    return ak.mha_reference(q, k, v, mask=mask, causal=causal, scale=scale)


def attention_supports(q, k, v, mask=None, causal: bool = False,
                       **kw) -> bool:
    """Hard constraints only — forced-pallas mode must work on the small
    shapes the conformance suite uses."""
    if getattr(q, "ndim", 0) != 4:
        return False
    if jnp.dtype(q.dtype) not in (jnp.dtype(jnp.float32),
                                  jnp.dtype(jnp.bfloat16)):
        return False
    if k.dtype != q.dtype or v.dtype != q.dtype:
        return False
    if mask is not None:
        B, _, _, _ = q.shape
        S = k.shape[2]
        if getattr(mask, "ndim", 0) != 2 or mask.shape != (B, S):
            return False
    return True


def attention_profitable(q, k, v, mask=None, causal: bool = False,
                         **kw) -> bool:
    """Auto-mode perf heuristics: mirror the measured v5e policy the old
    dispatcher encoded (flash wins from ~2k sequence, D a lane multiple)."""
    import deeplearning4j_tpu.ops.attention_kernels as ak

    T, D = q.shape[2], q.shape[3]
    S = k.shape[2]
    return D % 64 == 0 and max(T, S) >= ak._FLASH_MIN_SEQ
