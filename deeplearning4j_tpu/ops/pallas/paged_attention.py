"""Paged-KV decode attention for the fused-kernel tier.

The autoregressive decode hot loop is one query token per sequence
attending over that sequence's whole history.  Storing the history
contiguously forces a per-sequence max-length reservation; instead the
serving layer (``serving/decode.py``) keeps KV in fixed-size *pages*
shared by every sequence, and each sequence owns a *block table* — the
ordered list of page indices that make up its history (the vLLM design,
applied to the TPU tier).  This module is the attention that reads that
layout, shipped under the PR-13 two-implementation contract:

- :func:`paged_attention_reference` — a pure-jnp gather over the block
  tables followed by masked softmax.  It IS the spec; the conformance
  suite pins the Pallas kernel against it on CPU.
- :func:`paged_attention` — a Pallas kernel whose grid walks
  ``(batch, head, page)`` with the block tables and sequence lengths in
  scalar-prefetch memory, so each grid step DMAs exactly one page
  (``pl.BlockSpec`` index maps read the block table to find it) and
  folds it into a running online softmax held in VMEM scratch.  No
  per-sequence padding to a max length ever materializes.

Int8 KV pages ride through the PR-10 quantization seam: pages may be
``int8`` with per-(token, head) f32 scales produced by
``quant_kernels.quantize_tensor(axis=0)`` over rows of D; both
implementations widen with the identical ``q * scale`` dequant
(:func:`dequant_rows`), so int8 conformance is a pure rounding question,
never a tiling one.

Layout contract (shared with ``serving.decode.PagedKVCache``):

- ``q``            [B, H, D]         one decode token per sequence
- ``k_pages``      [P, page, H, D]   f32/bf16, or int8 with scales
- ``v_pages``      [P, page, H, D]
- ``k_scales``     [P, page, H]      f32 (int8 pages only)
- ``v_scales``     [P, page, H]
- ``block_tables`` [B, max_pages]    int32; slots past a sequence's last
                                     page MUST hold a valid index (0) so
                                     the skipped DMAs stay in bounds
- ``seq_lens``     [B]               int32, >= 1

The TileConfig enters at cache-construction time: ``block_kv`` is the
page size the serving layer allocates (the autotuner's knob), so the
kernel's KV tile is the page itself and the grid follows the block table.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas.tiles import DEFAULT_TILES, TileConfig

try:  # degrade to reference-only dispatch when pallas is unavailable
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised via dispatch tests
    pl = None
    pltpu = None

#: Matches ops.attention_kernels.NEG_INF — masked logits, not -jnp.inf,
#: so fully-masked tails stay NaN-free.
NEG_INF = -1e30

_KV_DTYPES = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))


def dequant_rows(x, scales, dtype=jnp.float32):
    """Widen int8 KV rows with their per-(token, head) scales: the exact
    inverse of ``quantize_tensor(rows, axis=0)``.  Shared by the kernel
    body and the reference so both dequantize identically.

    ``x`` [..., D] int8 (or float — then this is a plain cast),
    ``scales`` [...] broadcast over D.
    """
    x = x.astype(dtype)
    if scales is not None:
        x = x * scales.astype(dtype)[..., None]
    return x


# ---------------------------------------------------------------------------
# Reference — the spec
# ---------------------------------------------------------------------------


def paged_attention_reference(q, k_pages, v_pages, block_tables, seq_lens,
                              scale=None, k_scales=None, v_scales=None,
                              **_ignored):
    """Gather each sequence's pages per its block table, run masked
    attention over the reconstructed history.  Pure jnp; f32 math."""
    B, H, D = q.shape
    page = k_pages.shape[1]
    sm = (1.0 / math.sqrt(D)) if scale is None else float(scale)
    k = dequant_rows(k_pages, k_scales)           # [P, page, H, D] f32
    v = dequant_rows(v_pages, v_scales)
    max_pages = block_tables.shape[1]
    L = max_pages * page
    kg = k[block_tables].reshape(B, L, H, D).transpose(0, 2, 1, 3)
    vg = v[block_tables].reshape(B, L, H, D).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32), kg) * sm
    pos = jnp.arange(L)[None, None, :]            # [1, 1, L]
    valid = pos < seq_lens.astype(jnp.int32)[:, None, None]
    s = jnp.where(valid, s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,bhld->bhd", w, vg)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _paged_kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale, quantized):
    if quantized:
        ks_ref, vs_ref, out_ref, acc_ref, m_ref, l_ref = rest
    else:
        ks_ref = vs_ref = None
        out_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)
    page = k_ref.shape[1]

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq_len = sl_ref[b]
    start = p * page

    @pl.when(start < seq_len)
    def _accumulate():
        qv = q_ref[0].astype(jnp.float32)                  # (1, D)
        kb = k_ref[0, :, 0, :]                             # (page, D)
        vb = v_ref[0, :, 0, :]
        kb = dequant_rows(kb, ks_ref[0, :, 0] if quantized else None)
        vb = dequant_rows(vb, vs_ref[0, :, 0] if quantized else None)
        s = jnp.dot(qv, kb.T,
                    preferred_element_type=jnp.float32) * sm_scale
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        s = jnp.where(start + idx < seq_len, s, NEG_INF)   # (1, page)
        m_prev = m_ref[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_new)
        w = jnp.exp(s - m_new)                             # (1, page)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            w, vb, preferred_element_type=jnp.float32)
        l_ref[0, 0] = l_ref[0, 0] * corr + jnp.sum(w)
        m_ref[0, 0] = m_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        norm = jnp.maximum(l_ref[0, 0], 1e-37)             # seq_len >= 1
        out_ref[...] = (acc_ref[...] / norm).reshape(
            out_ref.shape).astype(out_ref.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    scale=None, k_scales=None, v_scales=None,
                    tile: Optional[TileConfig] = None,
                    interpret: bool = False):
    """Paged-KV decode attention: one query token per sequence against a
    block-table-addressed page pool.  Output [B, H, D] in q's dtype."""
    tile = tile or DEFAULT_TILES["paged_attention"]
    B, H, D = q.shape
    P, page, _, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    sm = (1.0 / math.sqrt(D)) if scale is None else float(scale)
    quantized = k_scales is not None
    block_tables = block_tables.astype(jnp.int32)
    seq_lens = seq_lens.astype(jnp.int32)

    def page_map(b, h, p, bt, sl):
        return (bt[b, p], 0, h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, D), lambda b, h, p, bt, sl: (b, h, 0)),
        pl.BlockSpec((1, page, 1, D), page_map),
        pl.BlockSpec((1, page, 1, D), page_map),
    ]
    args = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page, 1), lambda b, h, p, bt, sl:
                         (bt[b, p], 0, h)),
            pl.BlockSpec((1, page, 1), lambda b, h, p, bt, sl:
                         (bt[b, p], 0, h)),
        ]
        args += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, D), lambda b, h, p, bt, sl:
                               (b, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),   # online-softmax accumulator
            pltpu.VMEM((1, 1), jnp.float32),   # running max
            pltpu.VMEM((1, 1), jnp.float32),   # running normalizer
        ],
    )
    kernel = functools.partial(_paged_kernel, sm_scale=sm,
                               quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(block_tables, seq_lens, *args)


# ---------------------------------------------------------------------------
# Dispatch predicates
# ---------------------------------------------------------------------------


def paged_supports(q, k_pages, v_pages, block_tables, seq_lens,
                   scale=None, k_scales=None, v_scales=None,
                   **kw) -> bool:
    """Hard constraints only — forced-pallas mode must work on the small
    shapes the conformance suite uses."""
    if getattr(q, "ndim", 0) != 3 or getattr(k_pages, "ndim", 0) != 4:
        return False
    if jnp.dtype(q.dtype) not in _KV_DTYPES:
        return False
    if k_pages.shape != v_pages.shape:
        return False
    B, H, D = q.shape
    if k_pages.shape[2] != H or k_pages.shape[3] != D:
        return False
    if jnp.dtype(k_pages.dtype) == jnp.dtype(jnp.int8):
        if k_scales is None or v_scales is None:
            return False
        if k_scales.shape != k_pages.shape[:3]:
            return False
    elif jnp.dtype(k_pages.dtype) != jnp.dtype(q.dtype):
        return False
    if getattr(block_tables, "ndim", 0) != 2 or block_tables.shape[0] != B:
        return False
    if getattr(seq_lens, "ndim", 0) != 1 or seq_lens.shape[0] != B:
        return False
    return True


def paged_profitable(q, k_pages, v_pages, block_tables, seq_lens,
                     **kw) -> bool:
    """Auto-mode heuristics: the gather kernel pays off once a sequence's
    reconstructed history is long enough that XLA's dense gather path
    would materialize a large padded [B, L, H, D] intermediate."""
    D = q.shape[2]
    page = k_pages.shape[1]
    return D % 64 == 0 and block_tables.shape[1] * page >= 1024
