"""Single dispatch layer for the fused-kernel tier.

Every kernel in ``ops/pallas`` ships two implementations: a Pallas kernel
parameterized by a :class:`~deeplearning4j_tpu.ops.pallas.tiles.TileConfig`
and a pure-jnp reference that is the definition of correctness.  Call sites
ask this module which implementation to run; the answer depends on three
things:

* availability — ``jax.experimental.pallas`` importable at all (a missing
  import degrades the whole tier to reference-only instead of raising),
* the dispatch mode — ``auto`` (Pallas on TPU/GPU when the kernel's
  support *and* profitability predicates pass, reference everywhere else),
  ``pallas`` (force Pallas wherever the hard support predicate allows;
  on CPU the kernel runs in interpret mode, which is how the conformance
  suite pins ``pallas == reference``), or ``reference`` (force the jnp
  lowering),
* the kernel's own predicates, registered alongside its implementations.

The mode comes from ``DL4J_TPU_KERNEL_TIER`` or :func:`set_dispatch_mode`.
The module also owns the in-process tile table (installed by the autotuner
or loaded from the persisted store) and exposes
:func:`kernel_tier_fingerprint` so ``compile/fingerprint.py`` can fold the
tier configuration into AOT cache keys — a tile change can never collide
with a stale executable.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax

from deeplearning4j_tpu.ops.pallas.tiles import DEFAULT_TILES, TileConfig

_MODES = ("auto", "pallas", "reference")

_lock = threading.Lock()
_mode: str = os.environ.get("DL4J_TPU_KERNEL_TIER", "auto")
if _mode not in _MODES:  # bad env value: fail safe, not loud
    _mode = "auto"

_pallas_ok: Optional[bool] = None


def pallas_available() -> bool:
    """True when ``jax.experimental.pallas`` imports cleanly (memoized)."""
    global _pallas_ok
    if _pallas_ok is None:
        try:
            from jax.experimental import pallas  # noqa: F401

            _pallas_ok = True
        except Exception:
            _pallas_ok = False
    return _pallas_ok


def on_accelerator() -> bool:
    return jax.default_backend() in ("tpu", "gpu")


def interpret_mode() -> bool:
    """Whether a forced-Pallas kernel must run under ``interpret=True``."""
    return not on_accelerator()


def dispatch_mode() -> str:
    return _mode


def set_dispatch_mode(mode: str) -> str:
    """Set the tier mode; returns the previous mode (for try/finally)."""
    global _mode
    if mode not in _MODES:
        raise ValueError(f"unknown kernel-tier mode {mode!r}; want one of {_MODES}")
    with _lock:
        prev, _mode = _mode, mode
    return prev


@dataclass
class KernelSpec:
    name: str
    pallas_fn: Optional[Callable[..., Any]]
    reference_fn: Callable[..., Any]
    #: hard correctness constraints — gate both auto and forced-pallas modes
    supports: Optional[Callable[..., bool]] = None
    #: perf heuristics — gate auto mode only, so forced mode stays testable
    #: on shapes too small to be profitable
    profitable: Optional[Callable[..., bool]] = None


_registry: Dict[str, KernelSpec] = {}
_tiles: Dict[str, TileConfig] = {}
#: KV-cache storage dtype of the decode engine ("f32" / "bf16" / "int8").
#: Part of program identity: an int8-KV decode step traces a different
#: program (in-kernel dequant) than an f32-KV one, so the fingerprint
#: must split them or the AOT cache would serve a stale executable.
_kv_dtype: str = "f32"


def register(
    name: str,
    pallas_fn: Optional[Callable[..., Any]],
    reference_fn: Callable[..., Any],
    supports: Optional[Callable[..., bool]] = None,
    profitable: Optional[Callable[..., bool]] = None,
) -> None:
    _registry[name] = KernelSpec(name, pallas_fn, reference_fn, supports, profitable)


def kernels() -> Dict[str, KernelSpec]:
    return dict(_registry)


def resolve(name: str, *args: Any, **kwargs: Any) -> str:
    """Pick ``"pallas"`` or ``"reference"`` for one call and record it."""
    spec = _registry.get(name)
    impl = "reference"
    if spec is not None and spec.pallas_fn is not None and pallas_available():
        mode = _mode
        if mode != "reference":
            ok = spec.supports is None or bool(spec.supports(*args, **kwargs))
            if ok and mode == "auto":
                ok = on_accelerator() and (
                    spec.profitable is None or bool(spec.profitable(*args, **kwargs))
                )
            if ok:
                impl = "pallas"
    _record(name, impl)
    return impl


def _record(name: str, impl: str) -> None:
    try:
        from deeplearning4j_tpu.monitor.instrument import ops_instruments

        ops_instruments().record_dispatch(name, impl)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Tile table
# ---------------------------------------------------------------------------


def set_tile(kernel: str, cfg: TileConfig, shape_class: Optional[str] = None) -> None:
    key = f"{kernel}/{shape_class}" if shape_class else kernel
    with _lock:
        _tiles[key] = cfg


def get_tile(kernel: str, shape_class: Optional[str] = None) -> TileConfig:
    """Most specific installed tile: shape-class entry > kernel-wide > default."""
    if shape_class is not None:
        cfg = _tiles.get(f"{kernel}/{shape_class}")
        if cfg is not None:
            return cfg
    cfg = _tiles.get(kernel)
    if cfg is not None:
        return cfg
    return DEFAULT_TILES.get(kernel, TileConfig())


def install_tile_table(table: Dict[str, TileConfig]) -> None:
    with _lock:
        _tiles.update(table)


def tile_table() -> Dict[str, TileConfig]:
    return dict(_tiles)


def clear_tiles() -> None:
    with _lock:
        _tiles.clear()


def set_kv_dtype(dtype: str) -> str:
    """Install the decode KV-cache dtype ("f32"/"bf16"/"int8") into the
    tier fingerprint; returns the previous value (for try/finally)."""
    global _kv_dtype
    with _lock:
        prev, _kv_dtype = _kv_dtype, str(dtype)
    return prev


def kv_dtype() -> str:
    return _kv_dtype


def reset() -> None:
    """Test hook: restore env-derived mode and drop installed tiles."""
    global _mode, _kv_dtype
    with _lock:
        _mode = os.environ.get("DL4J_TPU_KERNEL_TIER", "auto")
        if _mode not in _MODES:
            _mode = "auto"
        _tiles.clear()
        _kv_dtype = "f32"


def kernel_tier_fingerprint() -> Dict[str, Any]:
    """Stable description of the tier config, folded into AOT cache keys.

    Distinguishes reference programs from Pallas-default programs from
    autotuned-tile programs: any change in mode, availability, any
    installed tile, or the decode KV-cache dtype changes the fingerprint
    (an f32-KV and an int8-KV decode program never share an AOT entry).
    """
    return {
        "mode": _mode,
        "pallas": pallas_available(),
        "tiles": {k: cfg.to_json() for k, cfg in sorted(_tiles.items())},
        "kv_dtype": _kv_dtype,
    }
