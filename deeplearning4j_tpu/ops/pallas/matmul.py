"""Fused matmul-family Pallas kernels: int8 tiles and dense epilogues.

One generic blockwise kernel serves three public entry points:

- :func:`int8_matmul` — the full low-bit path: int8×int8→int32 stays on
  the MXU for every K block, and the per-output-channel dequant (plus
  optional bias) is fused into the epilogue of the *last* K step.  Because
  the integer contraction is exact (associative, no rounding) and the f32
  epilogue is shared with the jnp reference
  (`quant_kernels.dequant_epilogue`), Pallas and reference agree
  *bit-for-bit* for any tiling — which is what the conformance suite pins.
- :func:`q_matmul` — weight-only quantization: int8 weights are widened
  to the compute dtype inside the kernel (per K block, in VMEM) instead
  of materializing a dequantized copy of W in HBM first.
- :func:`fused_dense` — float matmul with bias + activation fused into
  the epilogue (the cuDNN-style fused primitive), differentiable via a
  ``custom_vjp`` whose backward is the reference lowering's VJP.

Zero-padding to block multiples is exact for matmul (padded rows/cols
contribute zeros to the accumulator and are sliced off), so ragged shapes
need no masking here.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas.tiles import DEFAULT_TILES, TileConfig
from deeplearning4j_tpu.ops.quant_kernels import dequant_epilogue

try:  # degrade to reference-only dispatch when pallas is unavailable
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exercised via dispatch tests
    pl = None
    pltpu = None

#: Epilogue activations.  Both the kernel epilogue and the reference call
#: these same functions, so conformance is a pure tiling question.
EPILOGUE_ACTIVATIONS: Dict[str, Any] = {
    "identity": lambda y: y,
    "linear": lambda y: y,
    "relu": lambda y: jnp.maximum(y, 0.0),
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    # exact erf form, matching ops.activations.gelu
    "gelu": lambda y: jax.nn.gelu(y, approximate=False),
}

_FLOAT_DTYPES = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _sublane(dtype) -> int:
    d = jnp.dtype(dtype)
    if d == jnp.dtype(jnp.int8):
        return 32
    if d == jnp.dtype(jnp.bfloat16):
        return 16
    return 8


def _block_sizes(M: int, K: int, N: int, tile: TileConfig, x_dtype):
    """Clamp the tile to the problem, honouring TPU tiling minima:
    bm is a sublane dim (multiple of the operand's sublane count), bk and
    bn are lane dims (multiples of 128) unless they cover the whole dim."""
    bm = min(tile.block_m, _round_up(M, _sublane(x_dtype)))
    bk = min(tile.block_k, _round_up(K, 128))
    bn = min(tile.block_n, _round_up(N, 128))
    return bm, bk, bn


def _matmul_kernel(x_ref, w_ref, *rest, nk, acc_dtype, compute_dtype,
                   has_scale, has_bias, activation):
    refs = list(rest)
    scale_ref = refs.pop(0) if has_scale else None
    bias_ref = refs.pop(0) if has_bias else None
    out_ref, acc_sc = refs

    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    xb = x_ref[...]
    wb = w_ref[...]
    if compute_dtype is not None:
        xb = xb.astype(compute_dtype)
        wb = wb.astype(compute_dtype)
    acc_sc[...] += jnp.dot(xb, wb, preferred_element_type=acc_dtype)

    @pl.when(k == nk - 1)
    def _finalize():
        y = acc_sc[...]
        if has_scale:
            y = dequant_epilogue(y, scale_ref[...],
                                 bias=bias_ref[...] if has_bias else None)
        else:
            y = y.astype(jnp.float32)
            if has_bias:
                y = y + bias_ref[...].astype(jnp.float32)
        if activation is not None:
            y = EPILOGUE_ACTIVATIONS[activation](y)
        out_ref[...] = y.astype(out_ref.dtype)


def _tiled_matmul(x2, w, *, scale=None, bias=None, activation=None,
                  acc_dtype, compute_dtype, out_dtype,
                  tile: TileConfig, interpret: bool):
    """Grid (M/bm, N/bn, K/bk) with K innermost; VMEM accumulator scratch
    persists across the K steps of one (i, j) output block."""
    M, K = x2.shape
    N = w.shape[1]
    bm, bk, bn = _block_sizes(M, K, N, tile, x2.dtype)
    Mp, Kp, Np = _round_up(M, bm), _round_up(K, bk), _round_up(N, bn)

    xp = jnp.pad(x2, ((0, Mp - M), (0, Kp - K))) if (Mp, Kp) != (M, K) else x2
    wp = jnp.pad(w, ((0, Kp - K), (0, Np - N))) if (Kp, Np) != (K, N) else w

    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
        pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
    ]
    inputs = [xp, wp]
    if scale is not None:
        sp = jnp.pad(scale, ((0, 0), (0, Np - N)))
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, k: (0, j)))
        inputs.append(sp)
    if bias is not None:
        bp = jnp.pad(bias.reshape(1, N).astype(jnp.float32),
                     ((0, 0), (0, Np - N)))
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, k: (0, j)))
        inputs.append(bp)

    kernel = functools.partial(
        _matmul_kernel,
        nk=Kp // bk,
        acc_dtype=acc_dtype,
        compute_dtype=compute_dtype,
        has_scale=scale is not None,
        has_bias=bias is not None,
        activation=activation,
    )
    out = pl.pallas_call(
        kernel,
        grid=(Mp // bm, Np // bn, Kp // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), jnp.dtype(out_dtype)),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        interpret=interpret,
    )(*inputs)
    if (Mp, Np) != (M, N):
        out = out[:M, :N]
    return out


def _combined_scale(w_scale, x_scale, N: int):
    """Normalize per-channel weight scales (and an optional scalar
    activation scale) into the single (1, N) f32 row the epilogue
    multiplies by.  Shared by Pallas and reference so the f32 math — and
    therefore the output bits — are identical."""
    scale = jnp.asarray(w_scale, jnp.float32).reshape(1, N)
    if x_scale is not None:
        scale = jnp.asarray(x_scale, jnp.float32) * scale
    return scale


def _leading_flatten(x):
    lead = x.shape[:-1]
    return x.reshape(-1, x.shape[-1]), lead


# ---------------------------------------------------------------------------
# int8 × int8 → int32 (static activation quantization)
# ---------------------------------------------------------------------------

def int8_matmul(xq, wq, w_scale, x_scale=None, bias=None,
                out_dtype=jnp.float32, tile: Optional[TileConfig] = None,
                interpret: bool = False):
    """int8 activations × int8 weights with an int32 MXU accumulator and
    the dequant epilogue fused into the last K step.  Bitwise-equal to
    :func:`int8_matmul_reference` under any tiling."""
    tile = tile or DEFAULT_TILES["int8_matmul"]
    x2, lead = _leading_flatten(xq)
    N = wq.shape[1]
    y = _tiled_matmul(
        x2, wq,
        scale=_combined_scale(w_scale, x_scale, N),
        bias=bias,
        acc_dtype=jnp.int32, compute_dtype=None, out_dtype=out_dtype,
        tile=tile, interpret=interpret)
    return y.reshape(lead + (N,))


def int8_matmul_reference(xq, wq, w_scale, x_scale=None, bias=None,
                          out_dtype=jnp.float32):
    """Definition of correctness: whole-array int8→int32 contraction,
    then the shared dequant epilogue."""
    y = jax.lax.dot_general(
        xq, wq,
        dimension_numbers=(((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    scale = _combined_scale(w_scale, x_scale, wq.shape[1])
    return dequant_epilogue(y, scale, bias=bias, out_dtype=out_dtype)


def int8_supports(xq, wq, w_scale, x_scale=None, bias=None, **kw) -> bool:
    return (
        getattr(xq, "ndim", 0) >= 2 and getattr(wq, "ndim", 0) == 2
        and jnp.dtype(xq.dtype) == jnp.dtype(jnp.int8)
        and jnp.dtype(wq.dtype) == jnp.dtype(jnp.int8)
        and (x_scale is None or jnp.ndim(x_scale) == 0)
    )


def int8_profitable(xq, wq, *args, **kw) -> bool:
    return wq.shape[0] >= 256 and wq.shape[1] >= 256


# ---------------------------------------------------------------------------
# weight-only int8 (float activations)
# ---------------------------------------------------------------------------

def q_matmul(x, wq, w_scale, bias=None, acc_dtype=None,
             tile: Optional[TileConfig] = None, interpret: bool = False):
    """Weight-only path: int8 weights widen to the compute dtype inside
    the kernel, one K block at a time in VMEM — no dequantized copy of W
    in HBM.  Accumulates in f32 for stability; output in ``acc_dtype``
    (default: x's dtype, matching ``quantized_matmul``)."""
    tile = tile or DEFAULT_TILES["q_matmul"]
    acc = jnp.dtype(acc_dtype) if acc_dtype is not None else x.dtype
    x2, lead = _leading_flatten(x)
    N = wq.shape[1]
    y = _tiled_matmul(
        x2, wq,
        scale=_combined_scale(w_scale, None, N),
        bias=bias,
        acc_dtype=jnp.float32, compute_dtype=jnp.dtype(acc),
        out_dtype=acc, tile=tile, interpret=interpret)
    return y.reshape(lead + (N,))


def q_matmul_reference(x, wq, w_scale, bias=None, acc_dtype=None):
    """Mirrors `quant_kernels.quantized_matmul` (+ optional bias)."""
    acc = jnp.dtype(acc_dtype) if acc_dtype is not None else x.dtype
    y = jax.lax.dot_general(
        x.astype(acc), wq.astype(acc),
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=acc)
    y = y * jnp.asarray(w_scale, acc).reshape((1,) * (y.ndim - 1) + (-1,))
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def q_supports(x, wq, w_scale, bias=None, **kw) -> bool:
    return (
        getattr(x, "ndim", 0) >= 2 and getattr(wq, "ndim", 0) == 2
        and jnp.dtype(x.dtype) in _FLOAT_DTYPES
        and jnp.dtype(wq.dtype) == jnp.dtype(jnp.int8)
    )


def q_profitable(x, wq, *args, **kw) -> bool:
    return wq.shape[0] >= 256 and wq.shape[1] >= 256


# ---------------------------------------------------------------------------
# fused dense (matmul + bias + activation epilogue), differentiable
# ---------------------------------------------------------------------------

def fused_dense_reference(x, w, bias=None, activation=None):
    """f32-accumulated dense with the same epilogue functions the kernel
    applies; output in x's dtype."""
    y = jax.lax.dot_general(
        x, w,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if activation is not None:
        y = EPILOGUE_ACTIVATIONS[activation](y)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_dense_p(x, w, b, activation, tile, interpret):
    x2, lead = _leading_flatten(x)
    N = w.shape[1]
    y = _tiled_matmul(
        x2, w, bias=b, activation=activation,
        acc_dtype=jnp.float32, compute_dtype=None, out_dtype=x.dtype,
        tile=tile, interpret=interpret)
    return y.reshape(lead + (N,))


def _fused_dense_fwd(x, w, b, activation, tile, interpret):
    return _fused_dense_p(x, w, b, activation, tile, interpret), (x, w, b)


def _fused_dense_bwd(activation, tile, interpret, res, g):
    x, w, b = res
    _, vjp = jax.vjp(
        lambda x_, w_, b_: fused_dense_reference(x_, w_, b_, activation),
        x, w, b)
    return vjp(g)


_fused_dense_p.defvjp(_fused_dense_fwd, _fused_dense_bwd)


def fused_dense(x, w, bias=None, activation=None,
                tile: Optional[TileConfig] = None, interpret: bool = False):
    """Dense layer forward with bias + activation fused into the matmul
    epilogue.  Differentiable: the backward pass is the reference
    lowering's VJP (recomputed — flash-style, no epilogue residuals)."""
    tile = tile or DEFAULT_TILES["fused_dense"]
    b = bias if bias is not None else jnp.zeros((w.shape[1],), x.dtype)
    return _fused_dense_p(x, w, b, activation, tile, bool(interpret))


def dense_supports(x, w, bias=None, activation=None, **kw) -> bool:
    return (
        getattr(x, "ndim", 0) >= 2 and getattr(w, "ndim", 0) == 2
        and jnp.dtype(x.dtype) in _FLOAT_DTYPES
        and jnp.dtype(w.dtype) == jnp.dtype(x.dtype)
        and (bias is None or jnp.dtype(bias.dtype) in _FLOAT_DTYPES)
        and (activation is None or activation in EPILOGUE_ACTIVATIONS)
    )


def dense_profitable(x, w, *args, **kw) -> bool:
    rows = 1
    for d in x.shape[:-1]:
        rows *= int(d)
    return rows >= 128 and w.shape[0] >= 128 and w.shape[1] >= 128
