"""Tile schedules for the hand-fused Pallas kernel tier.

A :class:`TileConfig` is the unit the autotuner searches over and the unit
the tile store persists: one frozen record of the block sizes a kernel is
launched with.  Kernels read only the fields they care about (attention uses
``block_q``/``block_kv``, matmul-family kernels use ``block_m``/``block_n``/
``block_k``), so a single config type can describe every kernel in the tier
and round-trip through one JSON table.

Shape classes bucket concrete operand shapes into pow2 classes so a tuned
tile generalises across nearby shapes instead of being keyed to one exact
problem size (the TVM-style "schedule per workload class" idea, mirrored
from the step-level ``ScheduleAutotuner``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Tuple

TILE_FORMAT = "deeplearning4j_tpu.tiles.v1"


@dataclasses.dataclass(frozen=True)
class TileConfig:
    """Block sizes for one fused-kernel launch.

    Attention kernels consume ``block_q``/``block_kv``; matmul-family
    kernels consume ``block_m``/``block_n``/``block_k``.  Unused fields are
    carried along untouched so one config can be stored per kernel name.
    """

    block_q: int = 512
    block_kv: int = 1024
    block_m: int = 256
    block_n: int = 256
    block_k: int = 512

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "TileConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: int(v) for k, v in obj.items() if k in fields})

    def config_key(self) -> str:
        return (
            f"q{self.block_q}-kv{self.block_kv}-"
            f"m{self.block_m}-n{self.block_n}-k{self.block_k}"
        )

    def replace(self, **kw: int) -> "TileConfig":
        return dataclasses.replace(self, **kw)


#: Baseline tile per kernel.  The attention defaults mirror the block sizes
#: the pre-tier dispatcher picked (``_pick_block(T, 512)`` / ``(S, 1024)``),
#: so enabling the tier with no autotuning is behaviour-preserving.
DEFAULT_TILES: Dict[str, TileConfig] = {
    "attention": TileConfig(block_q=512, block_kv=1024),
    "int8_matmul": TileConfig(block_m=256, block_n=256, block_k=512),
    "q_matmul": TileConfig(block_m=256, block_n=256, block_k=512),
    "fused_dense": TileConfig(block_m=256, block_n=256, block_k=512),
    # decode attention: block_kv IS the KV page size the serving layer
    # allocates (one page per grid step), block_q is the single decode row
    "paged_attention": TileConfig(block_q=1, block_kv=16),
}

#: Candidate values per tile dimension, per kernel.  Kept deliberately
#: small: the tile search is grid+greedy over these, and every entry is a
#: real compile+measure on hardware.
TILE_SPACES: Dict[str, Dict[str, List[int]]] = {
    "attention": {
        "block_q": [128, 256, 512],
        "block_kv": [256, 512, 1024, 2048],
    },
    "int8_matmul": {
        "block_m": [128, 256, 512],
        "block_n": [128, 256, 512],
        "block_k": [256, 512, 1024],
    },
    "q_matmul": {
        "block_m": [128, 256, 512],
        "block_n": [128, 256, 512],
        "block_k": [256, 512, 1024],
    },
    "fused_dense": {
        "block_m": [128, 256, 512],
        "block_n": [128, 256, 512],
        "block_k": [256, 512, 1024],
    },
    "paged_attention": {
        "block_kv": [8, 16, 32, 64, 128],
    },
}

#: Dimensions swept by the coarse grid stage (the rest are greedy-refined).
TILE_GRID_DIMS: Dict[str, Tuple[str, ...]] = {
    "attention": ("block_q", "block_kv"),
    "int8_matmul": ("block_m", "block_n"),
    "q_matmul": ("block_m", "block_n"),
    "fused_dense": ("block_m", "block_n"),
    "paged_attention": ("block_kv",),
}


def _pow2_bucket(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def shape_class(**dims: int) -> str:
    """Bucket concrete dims into a pow2 shape-class key, e.g. ``k512-m128-n1024``.

    Keys are sorted so call sites can pass dims in any order.
    """
    items = sorted(dims.items())
    return "-".join(f"{k}{_pow2_bucket(v)}" for k, v in items)


def iter_space(space: Dict[str, Iterable[int]]) -> List[Dict[str, int]]:
    """Cartesian product of a {dim: candidates} space as override dicts."""
    combos: List[Dict[str, int]] = [{}]
    for dim in sorted(space):
        combos = [
            {**combo, dim: int(v)} for combo in combos for v in space[dim]
        ]
    return combos
