"""Hand-fused Pallas kernel tier (cuDNN-style primitive catalog).

Every kernel here ships two implementations — a Pallas TPU/GPU kernel
parameterized by a :class:`TileConfig` and a pure-jnp reference that is
the definition of correctness — selected by ``dispatch``: Pallas on
accelerators, reference on CPU, so tier-1 stays green under
``JAX_PLATFORMS=cpu``.  Tile schedules are searched by
``compile/autotune.py``'s ``TileAutotuner``, persisted per device kind +
shape class, and folded into AOT cache keys via
``compile/fingerprint.kernel_tier_fingerprint``.

Importing this package registers the kernel set; call sites go through
``dispatch.resolve`` and never import kernel modules directly.
"""
from deeplearning4j_tpu.ops.pallas import (attention, dispatch, matmul,
                                           paged_attention, tiles)
from deeplearning4j_tpu.ops.pallas.tiles import (  # noqa: F401
    DEFAULT_TILES,
    TILE_FORMAT,
    TILE_GRID_DIMS,
    TILE_SPACES,
    TileConfig,
    shape_class,
)

dispatch.register(
    "attention",
    pallas_fn=attention.flash_attention,
    reference_fn=attention.attention_reference,
    supports=attention.attention_supports,
    profitable=attention.attention_profitable,
)
dispatch.register(
    "paged_attention",
    pallas_fn=paged_attention.paged_attention,
    reference_fn=paged_attention.paged_attention_reference,
    supports=paged_attention.paged_supports,
    profitable=paged_attention.paged_profitable,
)
dispatch.register(
    "int8_matmul",
    pallas_fn=matmul.int8_matmul,
    reference_fn=matmul.int8_matmul_reference,
    supports=matmul.int8_supports,
    profitable=matmul.int8_profitable,
)
dispatch.register(
    "q_matmul",
    pallas_fn=matmul.q_matmul,
    reference_fn=matmul.q_matmul_reference,
    supports=matmul.q_supports,
    profitable=matmul.q_profitable,
)
dispatch.register(
    "fused_dense",
    pallas_fn=matmul.fused_dense,
    reference_fn=matmul.fused_dense_reference,
    supports=matmul.dense_supports,
    profitable=matmul.dense_profitable,
)

__all__ = [
    "attention",
    "dispatch",
    "matmul",
    "paged_attention",
    "tiles",
    "TileConfig",
    "DEFAULT_TILES",
    "TILE_SPACES",
    "TILE_GRID_DIMS",
    "TILE_FORMAT",
    "shape_class",
]
