"""Loss function inventory.

Covers the reference's `org.nd4j.linalg.lossfunctions.LossFunctions.LossFunction`
enum and ILossFunction implementations (`org/nd4j/linalg/lossfunctions/impl/`).
Each loss is `loss(labels, preactivations_or_probs, mask) -> scalar mean score`
as a pure jax function; gradients come from `jax.grad` of the whole step,
replacing the reference's hand-written `computeGradient` per loss.

Score convention matches the reference: per-example losses are summed over
the output dimension, then averaged over (unmasked) examples.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

LossFn = Callable[..., jnp.ndarray]

_EPS = 1e-7


def _reduce(per_example: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """per_example: [batch] (already summed over features). Mean over batch,
    honoring an optional per-example (or broadcastable) mask."""
    if mask is not None:
        mask = mask.reshape(per_example.shape)
        return jnp.sum(per_example * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(per_example)


def _masked_reduce(elem: jnp.ndarray, mask: Optional[jnp.ndarray],
                   mean_over_features: bool = False) -> jnp.ndarray:
    """Reduce an elementwise loss [batch, ...] to a scalar.

    The mask (if any) covers the leading dims of `elem` — [batch] or
    [batch, time] — reference semantics: masked units are excluded from both
    numerator and denominator.  `mean_over_features` divides by the feature
    count (MSE/MAE-style losses); otherwise features are summed (L1/L2/XENT
    style)."""
    if mask is None:
        per = jnp.sum(elem.reshape(elem.shape[0], -1), axis=-1)
        if mean_over_features:
            n = 1
            for s in elem.shape[1:]:
                n *= s
            per = per / max(n, 1)
        return jnp.mean(per)
    m = mask
    feat = 1
    for s in elem.shape[m.ndim:]:
        feat *= s
    m = m.reshape(m.shape + (1,) * (elem.ndim - m.ndim)).astype(elem.dtype)
    total = jnp.sum(elem * m)
    denom = jnp.maximum(jnp.sum(m), 1.0)
    if mean_over_features:
        denom = denom * max(feat, 1)
    return total / denom


def _sum_features(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(x.reshape(x.shape[0], -1), axis=-1)


def apply_loss(loss, act_fn, pre, labels, mask=None):
    """Single dispatch point for the logits-vs-activations split: losses in
    LOGIT_LOSSES consume raw pre-activations (numerically-stable fused path);
    everything else gets the configured activation applied first."""
    name = loss if isinstance(loss, str) else ""
    if str(name).lower() in LOGIT_LOSSES:
        return get_loss(loss)(labels, pre, mask)
    return get_loss(loss)(labels, act_fn(pre), mask)


def mcxent(labels, logits, mask=None):
    """Multi-class cross entropy on logits (reference MCXENT fused with
    softmax activation — the numerically-stable path libnd4j uses via
    softmax_cross_entropy custom op)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.sum(labels * logp, axis=-1)
    if per.ndim > 1:  # time-series [batch, time]
        if mask is not None and mask.shape == per.shape:
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    return _reduce(per, mask)


def negativeloglikelihood(labels, probs, mask=None):
    """NLL on probabilities (reference NEGATIVELOGLIKELIHOOD; identical to
    MCXENT-on-probs)."""
    per = -jnp.sum(labels * jnp.log(jnp.clip(probs, _EPS, 1.0)), axis=-1)
    if per.ndim > 1:
        if mask is not None and mask.shape == per.shape:
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    return _reduce(per, mask)


def xent(labels, logits, mask=None):
    """Binary cross entropy on logits (reference XENT fused with sigmoid)."""
    elem = (jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    return _masked_reduce(elem, mask)


def mse(labels, preds, mask=None):
    return _masked_reduce((preds - labels) ** 2, mask, mean_over_features=True)


def l2(labels, preds, mask=None):
    return _masked_reduce((preds - labels) ** 2, mask)


def l1(labels, preds, mask=None):
    return _masked_reduce(jnp.abs(preds - labels), mask)


def mae(labels, preds, mask=None):
    return _masked_reduce(jnp.abs(preds - labels), mask, mean_over_features=True)


def hinge(labels, preds, mask=None):
    """labels in {-1, +1} or {0,1} (converted)."""
    y = jnp.where(labels > 0, 1.0, -1.0)
    return _masked_reduce(jnp.maximum(0.0, 1.0 - y * preds), mask)


def squared_hinge(labels, preds, mask=None):
    y = jnp.where(labels > 0, 1.0, -1.0)
    return _masked_reduce(jnp.maximum(0.0, 1.0 - y * preds) ** 2, mask)


def kl_divergence(labels, probs, mask=None):
    elem = labels * (jnp.log(jnp.clip(labels, _EPS, 1.0))
                     - jnp.log(jnp.clip(probs, _EPS, 1.0)))
    return _masked_reduce(elem, mask)


def poisson(labels, preds, mask=None):
    elem = preds - labels * jnp.log(jnp.clip(preds, _EPS, None))
    return _masked_reduce(elem, mask)


def cosine_proximity(labels, preds, mask=None):
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), _EPS)
    pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), _EPS)
    per = -jnp.sum(ln * pn, axis=-1)
    if mask is not None and per.shape == mask.shape:
        return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if per.ndim > 1:
        per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    return _reduce(per, mask)


def mape(labels, preds, mask=None):
    elem = 100.0 * jnp.abs((labels - preds) / jnp.clip(jnp.abs(labels), _EPS, None))
    return _masked_reduce(elem, mask, mean_over_features=True)


def msle(labels, preds, mask=None):
    elem = (jnp.log1p(jnp.clip(preds, 0, None))
            - jnp.log1p(jnp.clip(labels, 0, None))) ** 2
    return _masked_reduce(elem, mask, mean_over_features=True)


def sparse_mcxent(labels, logits, mask=None):
    """Integer-label cross entropy (reference LossSparseMCXENT)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if per.ndim > 1:
        if mask is not None and mask.shape == per.shape:
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    return _reduce(per, mask)


# Names mirror LossFunctions.LossFunction enum values (lowercased).
LOSSES: Dict[str, LossFn] = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l1": l1,
    "l2": l2,
    "mean_absolute_error": mae,
    "mean_squared_logarithmic_error": msle,
    "mean_absolute_percentage_error": mape,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "sparse_mcxent": sparse_mcxent,
}

# Losses that expect raw logits and fuse the final activation internally.
LOGIT_LOSSES = {"mcxent", "xent", "sparse_mcxent"}


def get_loss(name_or_fn) -> LossFn:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(LOSSES)}")
    return LOSSES[key]
