"""Weight initialization inventory.

Covers the reference's `org.deeplearning4j.nn.weights.WeightInit` enum and
`WeightInitUtil` (deeplearning4j-nn/.../nn/weights/).  Fan-in/fan-out
conventions follow the reference: for a dense W of shape [nIn, nOut],
fanIn = nIn, fanOut = nOut; for conv kernels [kh, kw, cin, cout],
fanIn = kh*kw*cin, fanOut = kh*kw*cout.
"""
from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int]) -> Tuple[float, float]:
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    # conv kernels [spatial..., cin, cout]
    receptive = 1.0
    for s in shape[:-2]:
        receptive *= s
    return receptive * shape[-2], receptive * shape[-1]


def init_weights(key: jax.Array, shape: Sequence[int], scheme: str,
                 dtype=jnp.float32, dist_params=None) -> jnp.ndarray:
    """Initialize a weight tensor per a DL4J WeightInit scheme name."""
    scheme = scheme.upper()
    fan_in, fan_out = _fans(shape)
    shape = tuple(shape)
    if scheme == "ZERO":
        return jnp.zeros(shape, dtype)
    if scheme == "ONES":
        return jnp.ones(shape, dtype)
    if scheme == "IDENTITY":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "CONSTANT":
        value = (dist_params or {}).get("value", 0.0)
        return jnp.full(shape, value, dtype)
    if scheme == "NORMAL":
        # Reference NORMAL: N(0, 1/sqrt(fanIn))
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "GAUSSIAN":
        return jax.random.normal(key, shape, dtype)
    if scheme == "UNIFORM":
        a = math.sqrt(1.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "XAVIER":
        # Glorot normal: N(0, 2/(fanIn+fanOut))
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "XAVIER_UNIFORM":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "XAVIER_FAN_IN":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme in ("RELU", "HE", "HE_NORMAL"):
        # He normal: N(0, 2/fanIn)
        return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)
    if scheme in ("RELU_UNIFORM", "HE_UNIFORM"):
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme in ("LECUN_NORMAL",):
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)
    if scheme in ("LECUN_UNIFORM",):
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "SIGMOID_UNIFORM":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "VAR_SCALING_NORMAL_FAN_AVG":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "ORTHOGONAL":
        return jax.nn.initializers.orthogonal()(key, shape, dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
