"""Int8 weight-quantization primitives for the inference path.

The cuDNN case (PAPERS.md, arXiv 1410.0759) is that inference throughput
lives in low-precision primitives; TVM (arXiv 1802.04799) adds that
quantized programs must be first-class *compiled artifacts*.  These
kernels supply the math half of that contract for `quant/` (the artifact
half lives in `compile/fingerprint.py` + the persistent executable cache):

- `QTensor`: a pytree-registered (int8 values, f32 per-channel scales)
  pair.  Because it is a pytree node, the quantized leaves flow through
  `jit` / `device_put` / `tree_map` / fingerprint `tree_spec` untouched —
  the int8 buffer is what sits in device memory, which is exactly what
  the fleet's residency accounting measures.
- `quantize_tensor` / `dequantize`: per-channel symmetric int8 with the
  scale on the *output* axis, so `x @ W ≈ (x @ W_q) * scale[None, :]` is
  an identity up to rounding — the dequantize happens AFTER the matmul,
  inside the jitted program, in the accumulating dtype (guide: Patterns —
  Quantization Kernels).
- `quantized_matmul`: the dense/attention-projection hot path.  The MXU
  consumes the int8 weights cast to the accumulating dtype (bf16 under
  mixed precision, f32 otherwise); nothing in the compiled program ever
  silently widens back to f32 when a bf16 compute dtype is configured.
- `quantized_matmul_static`: optional static activation quantization —
  int8×int8 with an int32 accumulator using calibration-derived input
  scales (`quant/calibrate.py`), the full low-bit MXU path.

TPU tiling note (pallas guide): int8 tiles are (32, 128), so quantized
weight matrices keep their trailing dim a multiple of 128 where the model
allows; XLA handles ragged shapes with padding, correctness never depends
on it.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT8_MAX = 127.0


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Quantized tensor: int8 (or bf16-fallback) values + per-channel
    scales along `axis`.  Pytree children are (q, scale) so the pair
    travels as two ordinary leaves; `axis` is static aux data."""

    def __init__(self, q, scale, axis: int = -1):
        self.q = q
        self.scale = scale
        self.axis = int(axis)

    # ---- pytree protocol ----
    def tree_flatten(self):
        return (self.q, self.scale), (self.axis,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, axis=aux[0])

    # ---- array-ish surface ----
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.q.shape)

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return int(getattr(self.q, "nbytes", 0)
                   + getattr(self.scale, "nbytes", 0))

    def __repr__(self):
        return (f"QTensor(shape={self.shape}, dtype={self.q.dtype}, "
                f"axis={self.axis})")


def _scale_shape(shape: Tuple[int, ...], axis: int) -> Tuple[int, ...]:
    """Broadcast shape of the per-channel scale vector: 1 everywhere but
    `axis`."""
    out = [1] * len(shape)
    out[axis] = shape[axis]
    return tuple(out)


def quantize_tensor(w, axis: int = -1, dtype=jnp.int8) -> QTensor:
    """Symmetric per-channel int8 quantization: one scale per slice along
    `axis` (for a dense W of [n_in, n_out], axis=-1 is per-output-channel,
    making post-matmul dequantization exact).  All-zero channels get
    scale 1 so dequantization stays finite."""
    w = np.asarray(w)
    nd = w.ndim
    axis = axis % nd if nd else 0
    reduce_axes = tuple(i for i in range(nd) if i != axis)
    amax = np.abs(w).max(axis=reduce_axes, keepdims=True) if nd else \
        np.abs(w)
    scale = amax / INT8_MAX
    scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(w / scale), -INT8_MAX, INT8_MAX).astype(np.int8)
    return QTensor(jnp.asarray(q), jnp.asarray(scale), axis=axis)


def dequantize(qt: QTensor, dtype=jnp.float32):
    """Reconstruct a dense tensor in `dtype` — inside a trace this is the
    in-program dequantize; the int8 buffer stays the resident one."""
    return (qt.q.astype(dtype) * qt.scale.astype(dtype)).astype(dtype)


def quantization_error(w, axis: int = -1) -> float:
    """Mean |w - dequant(quant(w))| / mean |w| — the relative information
    loss an int8 round trip costs this tensor (the bf16-fallback signal)."""
    w = np.asarray(w, np.float64)
    qt = quantize_tensor(w, axis=axis)
    deq = np.asarray(qt.q, np.float64) * np.asarray(qt.scale, np.float64)
    denom = float(np.abs(w).mean()) or 1.0
    return float(np.abs(w - deq).mean()) / denom


def range_hostility(w, axis: int = -1) -> float:
    """max / mean of |w| within the worst channel.  int8 resolves ~1/127
    of a channel's max; once the channel's typical magnitude falls below
    one quantization step (hostility > ~127) most of its mass rounds to
    zero — the range-hostile case `quant/ptq.py` sends to bf16 instead."""
    w = np.asarray(w, np.float64)
    nd = w.ndim
    axis = axis % nd if nd else 0
    reduce_axes = tuple(i for i in range(nd) if i != axis)
    aw = np.abs(w)
    amax = aw.max(axis=reduce_axes)
    amean = aw.mean(axis=reduce_axes)
    ratio = amax / np.where(amean == 0.0, 1.0, amean)
    return float(ratio.max()) if ratio.size else 0.0


def dequant_epilogue(y, scale, bias=None, out_dtype=None):
    """Shared int8→float epilogue: widen the int32 accumulator to f32,
    multiply by the (already combined) per-channel scale row, add the
    optional bias — all in f32 — then cast.  Both the jnp reference
    contraction and the Pallas int8 tile call this same function, so the
    two paths agree bit-for-bit on scales for any tiling."""
    y = y.astype(jnp.float32) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if out_dtype is not None:
        y = y.astype(out_dtype)
    return y


def _tier_resolve(kernel, *args, **kwargs):
    """Ask the fused-kernel tier which implementation this call gets.

    Returns ("reference", None) when the tier is unavailable so the pure
    jnp path below never depends on `ops.pallas` importing."""
    try:
        from deeplearning4j_tpu.ops import pallas as tier
        return tier.dispatch.resolve(kernel, *args, **kwargs), tier
    except Exception:
        return "reference", None


def _matmul_shape_class(x, n_out: int):
    from deeplearning4j_tpu.ops.pallas.tiles import shape_class
    rows = 1
    for d in x.shape[:-1]:
        rows *= int(d)
    return shape_class(m=rows, k=int(x.shape[-1]), n=int(n_out))


def _quantized_matmul_ref(x, qt: QTensor, acc_dtype=None):
    acc = jnp.dtype(acc_dtype) if acc_dtype is not None else x.dtype
    x = x.astype(acc)
    y = jax.lax.dot_general(
        x, qt.q.astype(acc),
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=acc)
    return y * qt.scale.astype(acc).reshape((1,) * (y.ndim - 1) + (-1,))


def quantized_matmul(x, qt: QTensor, acc_dtype=None):
    """x @ dequant(W) computed as (x @ W_q) * scale — the matmul consumes
    the int8 weights cast to the accumulating dtype and the per-output-
    channel scales apply to the product, so no f32 copy of W ever exists
    in the program.  `acc_dtype` defaults to x's dtype (bf16 under mixed
    precision).  Exact (up to rounding of W) only for axis == last dim.

    On TPU/GPU (or under a forced `pallas` dispatch mode) this routes to
    the weight-only Pallas tile, which widens W one K-block at a time in
    VMEM instead of streaming a dequantized copy from HBM."""
    if qt.axis != qt.ndim - 1:
        raise ValueError(
            f"quantized_matmul needs per-output-channel scales "
            f"(axis={qt.ndim - 1}), got axis={qt.axis}")
    impl, tier = _tier_resolve("q_matmul", x, qt.q, qt.scale)
    if impl == "pallas":
        sc = _matmul_shape_class(x, qt.shape[-1])
        return tier.matmul.q_matmul(
            x, qt.q, qt.scale, acc_dtype=acc_dtype,
            tile=tier.dispatch.get_tile("q_matmul", sc),
            interpret=tier.dispatch.interpret_mode())
    return _quantized_matmul_ref(x, qt, acc_dtype=acc_dtype)


def quantize_activation(x, scale):
    """Static activation quantization with a calibration-derived scale:
    clip+round to int8 inside the program (guide: stochastic rounding is
    for training; inference uses round-to-nearest)."""
    return jnp.clip(jnp.round(x / scale), -INT8_MAX, INT8_MAX
                    ).astype(jnp.int8)


def quantized_matmul_static(x, qt: QTensor, x_scale,
                            acc_dtype=jnp.float32):
    """Full low-bit path: int8 activations (static calibration scale) ×
    int8 weights with an int32 accumulator, dequantized once at the end
    by `x_scale * w_scale` — the MXU int8 mode the guide's quantization
    pattern targets."""
    if qt.axis != qt.ndim - 1:
        raise ValueError("static quantized matmul needs axis == last dim")
    xq = quantize_activation(x, x_scale)
    acc = jnp.dtype(acc_dtype)
    impl, tier = _tier_resolve("int8_matmul", xq, qt.q, qt.scale, x_scale)
    if impl == "pallas":
        sc = _matmul_shape_class(xq, qt.shape[-1])
        return tier.matmul.int8_matmul(
            xq, qt.q, qt.scale, x_scale=x_scale, out_dtype=acc,
            tile=tier.dispatch.get_tile("int8_matmul", sc),
            interpret=tier.dispatch.interpret_mode())
    y = jax.lax.dot_general(
        xq, qt.q,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    scale = (jnp.asarray(x_scale, jnp.float32)
             * qt.scale.astype(jnp.float32).reshape(
                 (1,) * (y.ndim - 1) + (-1,)))
    return dequant_epilogue(y, scale, out_dtype=acc)


def quantized_dense(x, qt: QTensor, b: Optional[jax.Array] = None,
                    acc_dtype=None):
    """Dense-layer hot path: quantized matmul + bias in the accumulating
    dtype (activation application stays with the calling layer).  When
    the Pallas tier takes the call, the bias add is fused into the tile's
    epilogue."""
    if qt.axis != qt.ndim - 1:
        raise ValueError(
            f"quantized_dense needs per-output-channel scales "
            f"(axis={qt.ndim - 1}), got axis={qt.axis}")
    impl, tier = _tier_resolve("q_matmul", x, qt.q, qt.scale, bias=b)
    if impl == "pallas":
        sc = _matmul_shape_class(x, qt.shape[-1])
        return tier.matmul.q_matmul(
            x, qt.q, qt.scale, bias=b, acc_dtype=acc_dtype,
            tile=tier.dispatch.get_tile("q_matmul", sc),
            interpret=tier.dispatch.interpret_mode())
    y = _quantized_matmul_ref(x, qt, acc_dtype=acc_dtype)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y
