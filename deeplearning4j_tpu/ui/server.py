"""Live training dashboard server.

Reference: `deeplearning4j-ui-parent/deeplearning4j-ui/.../VertxUIServer`
+ TrainModule — a Vert.x HTTP server with websocket pushes that renders
attached StatsStorage sessions.

TPU-side inversion: training never blocks on the UI (the listener writes
into host-side storage off the jitted step's critical path), so a plain
stdlib `http.server` thread that RE-RENDERS the latest stats per request
plus a `<meta http-equiv=refresh>` interval replaces the websocket push —
same live-monitoring capability, zero dependencies."""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.ui.stats import (FileStatsStorage,
                                          InMemoryStatsStorage,
                                          render_html)


class UIServer:
    """`UIServer.get_instance().attach(storage); server.start(9000)` —
    reference `UIServer.getInstance().attach(statsStorage)`."""

    _instance: Optional["UIServer"] = None

    def __init__(self):
        self._storages: List[InMemoryStatsStorage] = []
        self._paths: List[str] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.refresh_seconds = 5

    @staticmethod
    def get_instance() -> "UIServer":
        if UIServer._instance is None:
            UIServer._instance = UIServer()
        return UIServer._instance

    def attach(self, storage: InMemoryStatsStorage) -> "UIServer":
        self._storages.append(storage)
        return self

    def attach_file(self, path: str) -> "UIServer":
        """Monitor a FileStatsStorage written by ANOTHER process (the
        training job); the file is re-read on every render, so the page
        follows the live run."""
        self._paths.append(path)
        return self

    def detach(self, storage: InMemoryStatsStorage) -> "UIServer":
        self._storages = [s for s in self._storages if s is not storage]
        return self

    def detach_file(self, path: str) -> "UIServer":
        self._paths = [p for p in self._paths if p != path]
        return self

    def _render(self) -> str:
        storages = list(self._storages)
        for p in self._paths:
            try:
                storages.append(FileStatsStorage.load(p))
            except (FileNotFoundError, OSError):
                pass                     # run not started yet
        if not storages:
            return ("<html><body><h1>deeplearning4j_tpu UI</h1>"
                    "<p>No StatsStorage attached.</p></body></html>")
        html = "\n<hr/>\n".join(render_html(s) for s in storages)
        tag = (f'<meta http-equiv="refresh" '
               f'content="{self.refresh_seconds}">')
        return html.replace("<head>", "<head>" + tag, 1)

    def start(self, port: int = 9000, host: str = "127.0.0.1") -> int:
        """Start serving; returns the bound port (pass 0 to auto-pick)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API)
                body = ui._render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass                   # keep training logs clean

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
