"""Live training dashboard server.

Reference: `deeplearning4j-ui-parent/deeplearning4j-ui/.../VertxUIServer`
+ TrainModule — a Vert.x HTTP server with websocket pushes that renders
attached StatsStorage sessions.

TPU-side inversion: training never blocks on the UI (the listener writes
into host-side storage off the jitted step's critical path), so a plain
stdlib `http.server` thread that RE-RENDERS the latest stats per request
plus a `<meta http-equiv=refresh>` interval replaces the websocket push —
same live-monitoring capability, zero dependencies."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.monitor.registry import registry
from deeplearning4j_tpu.ui.stats import (FileStatsStorage,
                                          InMemoryStatsStorage,
                                          render_html,
                                          render_registry_html,
                                          render_serving_html)


class UIServer:
    """`UIServer.get_instance().attach(storage); server.start(9000)` —
    reference `UIServer.getInstance().attach(statsStorage)`."""

    _instance: Optional["UIServer"] = None

    def __init__(self):
        self._storages: List[InMemoryStatsStorage] = []
        self._paths: List[str] = []
        self._serving: List = []          # serving.ServingMetrics sources
        self._fleets: List = []           # serving.ModelFleet sources
        self._federations: List = []      # serving.FederationRouter sources
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.refresh_seconds = 5

    @staticmethod
    def get_instance() -> "UIServer":
        if UIServer._instance is None:
            UIServer._instance = UIServer()
        return UIServer._instance

    def attach(self, storage: InMemoryStatsStorage) -> "UIServer":
        self._storages.append(storage)
        return self

    def attach_file(self, path: str) -> "UIServer":
        """Monitor a FileStatsStorage written by ANOTHER process (the
        training job); the file is re-read on every render, so the page
        follows the live run."""
        self._paths.append(path)
        return self

    def detach(self, storage: InMemoryStatsStorage) -> "UIServer":
        self._storages = [s for s in self._storages if s is not storage]
        return self

    def detach_file(self, path: str) -> "UIServer":
        self._paths = [p for p in self._paths if p != path]
        return self

    def attach_serving(self, source) -> "UIServer":
        """Monitor a serving metrics source — anything with a `stats()` or
        `snapshot()` dict method (`serving.ModelServer` /
        `serving.ServingMetrics`).  Rendered as a section on the dashboard
        and exported as JSON at the `/serving` endpoint."""
        self._serving.append(source)
        return self

    def detach_serving(self, source) -> "UIServer":
        self._serving = [s for s in self._serving if s is not source]
        return self

    def attach_fleet(self, fleet) -> "UIServer":
        """Monitor a `serving.ModelFleet` (anything with `fleet_stats()`
        and `readyz()`): exported as JSON at `/fleet`, and folded into the
        aggregate `/readyz` — the pod is ready only when the fleet is."""
        self._fleets.append(fleet)
        return self

    def detach_fleet(self, fleet) -> "UIServer":
        self._fleets = [f for f in self._fleets if f is not fleet]
        return self

    def attach_federation(self, fed) -> "UIServer":
        """Monitor a `serving.FederationRouter` (anything with
        `federation_stats()` and `healthz()`): exported as JSON at
        `/federation` (membership, generation, per-host pending, recent
        eviction / re-placement events) and folded into `/healthz`."""
        self._federations.append(fed)
        return self

    def detach_federation(self, fed) -> "UIServer":
        self._federations = [f for f in self._federations if f is not fed]
        return self

    def _federation_snapshots(self) -> List[dict]:
        out = []
        for f in list(self._federations):
            try:
                out.append(f.federation_stats())
            except Exception as e:  # a dead federation must not 500 the UI
                out.append({"error": repr(e)})
        return out

    def _fleet_snapshots(self) -> List[dict]:
        out = []
        for f in list(self._fleets):
            try:
                out.append(f.fleet_stats())
            except Exception as e:      # a dead fleet must not 500 the UI
                out.append({"error": repr(e)})
        return out

    def _serving_snapshots(self) -> List[dict]:
        out = []
        for s in list(self._serving):
            try:
                fn = getattr(s, "stats", None) or getattr(s, "snapshot")
                out.append(fn())
            except Exception as e:          # a dead source must not 500 the UI
                out.append({"error": repr(e)})
        return out

    def healthz(self) -> dict:
        """Liveness payload for `GET /healthz` — the server thread is up
        and rendering.  Attached fleets contribute their degraded-mode
        ladder level (serving/resilience.py), so one liveness probe also
        tells the operator which named operating mode each fleet is in."""
        fleets = []
        for f in list(self._fleets):
            try:
                fleets.append(f.healthz())
            except Exception as e:      # a dead fleet must not 500 /healthz
                fleets.append({"ok": False, "error": repr(e)})
        feds = []
        for f in list(self._federations):
            try:
                feds.append(f.healthz())
            except Exception as e:
                feds.append({"ok": False, "error": repr(e)})
        return {"ok": True,
                "storages": len(self._storages) + len(self._paths),
                "serving_sources": len(self._serving),
                "fleets": len(self._fleets),
                "fleet_health": fleets,
                "federations": len(self._federations),
                "federation_health": feds}

    def readyz(self) -> dict:
        """Aggregate readiness for `GET /readyz`: every attached serving
        source AND fleet that exposes `readyz()` must report ready (a
        source that raises counts as not ready).  Fleet readiness is
        residency-aware — cold fleet members admit on demand and do not
        block the pod.  With no sources attached the UI is trivially
        ready — it only serves dashboards."""
        sources, ready = [], True
        for s in list(self._serving) + list(self._fleets):
            fn = getattr(s, "readyz", None)
            if fn is None:
                continue
            try:
                r = fn()
            except Exception as e:
                r = {"ready": False, "reasons": [f"readyz raised: {e!r}"]}
            sources.append(r)
            ready = ready and bool(r.get("ready"))
        return {"ready": ready, "sources": sources}

    def _registry_html(self) -> str:
        snap = registry().snapshot(bins=24)
        if not (snap["counters"] or snap["gauges"] or snap["histograms"]):
            return ""
        return render_registry_html(snap)

    def _render(self) -> str:
        storages = list(self._storages)
        for p in self._paths:
            try:
                storages.append(FileStatsStorage.load(p))
            except (FileNotFoundError, OSError):
                pass                     # run not started yet
        serving = "\n<hr/>\n".join(
            render_serving_html(s) for s in self._serving_snapshots())
        reg = self._registry_html()
        if reg:
            serving = serving + "\n<hr/>\n" + reg if serving else reg
        if not storages:
            # nothing attached: keep the notice even when the registry
            # block has process-wide metrics to show below it
            notice = ("<h1>deeplearning4j_tpu UI</h1>"
                      "<p>No StatsStorage attached.</p>"
                      if not self._serving else "")
            if not serving and notice:
                return f"<html><body>{notice}</body></html>"
            html = ("<html><head><title>deeplearning4j_tpu serving</title>"
                    "<style>body{font-family:sans-serif;margin:24px}"
                    "</style></head><body>" + notice + serving
                    + "</body></html>")
        else:
            html = "\n<hr/>\n".join(render_html(s) for s in storages)
            if serving:
                # inject before the LAST closing tag (each attached storage
                # renders a full document)
                i = html.rfind("</body></html>")
                html = html[:i] + "<hr/>\n" + serving + "\n" + html[i:]
        tag = (f'<meta http-equiv="refresh" '
               f'content="{self.refresh_seconds}">')
        return html.replace("<head>", "<head>" + tag, 1)

    def start(self, port: int = 9000, host: str = "127.0.0.1") -> int:
        """Start serving; returns the bound port (pass 0 to auto-pick)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API)
                status = 200
                if self.path.rstrip("/") == "/metrics":
                    # Prometheus text exposition of the process registry
                    body = registry().render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.rstrip("/") == "/serving":
                    # machine-readable SLO metrics (scrape endpoint)
                    body = json.dumps(ui._serving_snapshots()).encode()
                    ctype = "application/json"
                elif self.path.rstrip("/") == "/fleet":
                    # fleet topology: residency, per-model SLO state,
                    # slice allocation, recent controller actions
                    body = json.dumps(ui._fleet_snapshots()).encode()
                    ctype = "application/json"
                elif self.path.rstrip("/") == "/federation":
                    # federation membership: hosts, generation, ladder,
                    # recent eviction / re-placement events
                    body = json.dumps(ui._federation_snapshots()).encode()
                    ctype = "application/json"
                elif self.path.rstrip("/") == "/healthz":
                    # liveness: this thread answered, so the server is up
                    body = json.dumps(ui.healthz()).encode()
                    ctype = "application/json"
                elif self.path.rstrip("/") == "/readyz":
                    # readiness: 200 only when every attached serving
                    # source reports ready (503 tells the LB to drain)
                    payload = ui.readyz()
                    status = 200 if payload["ready"] else 503
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                else:
                    body = ui._render().encode()
                    ctype = "text/html; charset=utf-8"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass                   # keep training logs clean

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
