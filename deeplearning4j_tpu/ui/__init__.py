"""Training UI / metrics (reference `deeplearning4j-ui-parent/**`)."""
from deeplearning4j_tpu.ui.stats import (  # noqa: F401
    FileStatsStorage, InMemoryStatsStorage, StatsListener, render_html)
from deeplearning4j_tpu.ui.server import UIServer  # noqa: F401
